"""Shared suite plumbing: workload x nemesis wiring, sweeps, CLI mains.

Every reference suite repeats the same shape — a workload registry, a
nemesis registry, a test constructor merging them into the test map, and a
sweep over the cross product (tidb/src/tidb/core.clj:32-80,
zookeeper/src/jepsen/zookeeper.clj:112-143, yugabyte's nemeses.clj
registry).  This module is that shape, factored once.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from jepsen_tpu import cli, generator as gen
from jepsen_tpu import os as jos
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.nemesis import combined

STANDARD_NEMESES: Dict[str, Callable[[Dict[str, Any]], Any]] = {
    "none": lambda opts: combined.Package(),
    "partition": lambda opts: combined.partition_package(opts),
    "kill": lambda opts: combined.db_package({**opts, "faults": ["kill"]}),
    "pause": lambda opts: combined.db_package({**opts, "faults": ["pause"]}),
    "clock": lambda opts: combined.clock_package(opts),
    "packet": lambda opts: combined.packet_package(opts),
    "all": lambda opts: combined.nemesis_package(
        {**opts, "faults": ["partition", "kill", "pause", "clock"]}),
}


def build_test(opts: Dict[str, Any], *, suite: str, db,
               workloads: Dict[str, Callable],
               nemeses: Optional[Dict[str, Callable]] = None,
               os=None) -> Dict[str, Any]:
    """Construct a full test map from a suite's registries + CLI opts."""
    nemeses = nemeses or STANDARD_NEMESES
    workload_name = opts.get("workload") or sorted(workloads)[0]
    default_nemesis = "partition" if "partition" in nemeses \
        else sorted(nemeses)[0]
    nemesis_name = opts.get("nemesis") or default_nemesis
    wl = workloads[workload_name](opts)
    # nemesis factories see all suite opts (max_dead_nodes, pause_mode, …)
    pkg = nemeses[nemesis_name](
        {**opts, "interval": float(opts.get("nemesis_interval", 10.0))})

    time_limit = float(opts.get("time_limit", 60.0))
    client_gen = gen.time_limit(time_limit, gen.clients(wl["generator"]))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    # final phases synchronize on quiescence so final reads can't race
    # still-in-flight ops from the main phase
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(
            gen.nemesis(gen.lift(pkg.final_generator))))
    if wl.get("final_generator") is not None:
        parts.append(gen.synchronize(
            gen.clients(gen.lift(wl["final_generator"]))))

    checkers = {"stats": Stats(), "workload": wl["checker"],
                "perf": Perf(), "timeline": Timeline()}
    return {**opts,
            "name": f"{suite}-{workload_name}-{nemesis_name}",
            "os": os if os is not None else jos.Debian(),
            "db": db,
            "client": wl["client"],
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose(checkers)}


def sweep(opts: Dict[str, Any], test_fn: Callable,
          workloads: Dict[str, Callable],
          nemeses: Optional[Dict[str, Callable]] = None) -> list:
    """Workload x nemesis sweep matrix (tidb/core.clj:47-80 pattern)."""
    nemeses = nemeses or STANDARD_NEMESES
    return [test_fn({**opts, "workload": w, "nemesis": n})
            for w in opts.get("workloads", sorted(workloads))
            for n in opts.get("nemeses", sorted(nemeses))]


def suite_opts(workloads, nemeses=None, default_workload=None,
               extra: Optional[Callable] = None):
    nemeses = nemeses or STANDARD_NEMESES

    def opt_fn(parser):
        parser.add_argument(
            "--workload", choices=sorted(workloads),
            default=default_workload or sorted(workloads)[0])
        parser.add_argument(
            "--nemesis", choices=sorted(nemeses),
            default="partition" if "partition" in nemeses
            else sorted(nemeses)[0])
        parser.add_argument("--nemesis-interval", type=float, default=10.0)
        parser.add_argument("--db-port", type=int, default=None,
                            help="override the client port (clients read "
                                 "test['db_port'])")
        if extra:
            extra(parser)

    return opt_fn


def main(test_fn: Callable, workloads, nemeses=None, prog: str = "jepsen-tpu",
         extra_opts: Optional[Callable] = None,
         default_workload: Optional[str] = None) -> int:
    return cli.single_test_cmd(
        test_fn,
        opt_fn=suite_opts(workloads, nemeses, default_workload,
                          extra=extra_opts),
        prog=prog)
