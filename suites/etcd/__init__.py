"""etcd test suite — the canonical small real-database target.

Plays the role of the reference's zookeeper suite
(zookeeper/src/jepsen/zookeeper.clj:112-143, the minimal canonical suite and
BASELINE config #2) and consul's CAS-register competition checker
(consul/src/jepsen/consul/register.clj:72): a linearizable-register workload
against a real consensus store, faults included, verdict from the device
engine.
"""
