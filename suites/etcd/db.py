"""etcd cluster install/start/stop on test nodes.

Same responsibilities as the reference suites' db namespaces (e.g.
zookeeper/src/jepsen/zookeeper.clj's db, tidb/src/tidb/db.clj): download the
release, render config, run as a daemon, implement Kill/Pause/Primary/
LogFiles capabilities for the nemesis packages and log snarfing.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "3.5.17"
URL = ("https://github.com/etcd-io/etcd/releases/download/"
       f"v{VERSION}/etcd-v{VERSION}-linux-amd64.tar.gz")
DIR = "/opt/etcd"
DATA_DIR = "/opt/etcd/data"
PIDFILE = "/var/run/etcd.pid"
LOGFILE = "/var/log/etcd.log"
CLIENT_PORT = 2379
PEER_PORT = 2380


def node_url(node: str, port: int) -> str:
    return f"http://{node}:{port}"


def initial_cluster(test) -> str:
    return ",".join(f"{n}={node_url(n, PEER_PORT)}" for n in test["nodes"])


class EtcdDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        self.start(test, node)
        cu.await_tcp_port(s, CLIENT_PORT, timeout_s=60)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.stop_daemon(s, PIDFILE)
        s.exec("rm", "-rf", DATA_DIR, LOGFILE)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(
            s, f"{DIR}/etcd",
            "--name", node,
            "--data-dir", DATA_DIR,
            "--listen-client-urls", f"http://0.0.0.0:{CLIENT_PORT}",
            "--advertise-client-urls", node_url(node, CLIENT_PORT),
            "--listen-peer-urls", f"http://0.0.0.0:{PEER_PORT}",
            "--initial-advertise-peer-urls", node_url(node, PEER_PORT),
            "--initial-cluster", initial_cluster(test),
            "--initial-cluster-state", "new",
            "--snapshot-count", "10000",
            pidfile=PIDFILE, logfile=LOGFILE)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "etcd", signal="KILL")
        s.exec("rm", "-f", PIDFILE)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "etcd", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "etcd", "CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        import urllib.request
        for node in test["nodes"]:
            try:
                req = urllib.request.Request(
                    node_url(node, CLIENT_PORT) + "/v3/maintenance/status",
                    data=b"{}", headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=2) as r:
                    st = json.load(r)
                leader = st.get("leader")
                member = st.get("header", {}).get("member_id")
                if leader and leader == member:
                    return [node]
            except Exception:  # noqa: BLE001
                continue
        return []

    def setup_primary(self, test, node):
        pass  # etcd elects its own leader

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
