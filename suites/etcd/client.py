"""etcd clients over the v3 JSON/gRPC gateway (stdlib urllib only).

Register ops use etcd transactions for CAS (the same op language as the
reference's zookeeper/consul register clients:
zookeeper/src/jepsen/zookeeper.clj:91-104).  Values are (key, value) tuples
from the independent lift.
"""

from __future__ import annotations

import base64
import json
import socket
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

CLIENT_PORT = 2379


def b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdError(Exception):
    pass


class EtcdConn:
    def __init__(self, node: str, timeout: float = 5.0):
        self.base = f"http://{node}:{CLIENT_PORT}"
        self.timeout = timeout

    def call(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        req = urllib.request.Request(
            self.base + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.load(r)

    def get(self, key: str) -> Optional[str]:
        r = self.call("/v3/kv/range", {"key": b64(key)})
        kvs = r.get("kvs") or []
        return unb64(kvs[0]["value"]) if kvs else None

    def put(self, key: str, value: str) -> None:
        self.call("/v3/kv/put", {"key": b64(key), "value": b64(value)})

    def cas(self, key: str, old: str, new: str) -> bool:
        """Transactional compare-and-set."""
        r = self.call("/v3/kv/txn", {
            "compare": [{"key": b64(key), "target": "VALUE",
                         "value": b64(old), "result": "EQUAL"}],
            "success": [{"requestPut": {"key": b64(key),
                                        "value": b64(new)}}],
        })
        return bool(r.get("succeeded"))


class RegisterClient(jclient.Client):
    """Linearizable per-key register ops: read / write / cas."""

    def __init__(self, conn: Optional[EtcdConn] = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(EtcdConn(node))

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        key = f"jt/r/{k}"
        try:
            if op.f == "read":
                cur = self.conn.get(key)
                return op.with_(type=OK,
                                value=(k, int(cur) if cur is not None
                                       else None))
            if op.f == "write":
                self.conn.put(key, str(v))
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                ok = self.conn.cas(key, str(old), str(new))
                return op.with_(type=OK if ok else FAIL)
            raise ValueError(op.f)
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ConnectionError) as e:
            # Reads that fail definitely didn't happen; mutations are
            # indeterminate (the op may have been applied).
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))


class SetClient(jclient.Client):
    """Grow-only set as one key holding a JSON list, updated with CAS
    retry loops."""

    def __init__(self, conn: Optional[EtcdConn] = None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(EtcdConn(node))

    def invoke(self, test, op: Op) -> Op:
        key = "jt/set"
        try:
            if op.f == "read":
                cur = self.conn.get(key)
                return op.with_(type=OK,
                                value=json.loads(cur) if cur else [])
            if op.f == "add":
                for _ in range(16):
                    cur = self.conn.get(key)
                    if cur is None:
                        self.conn.put(key, json.dumps([op.value]))
                        return op.with_(type=OK)
                    items = json.loads(cur)
                    items.append(op.value)
                    if self.conn.cas(key, cur, json.dumps(items)):
                        return op.with_(type=OK)
                return op.with_(type=FAIL, error="cas-retries-exhausted")
            raise ValueError(op.f)
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ConnectionError) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
