"""etcd suite CLI — workload x nemesis registry and test construction.

Same shape as the reference's suite mains (tidb/src/tidb/core.clj:32-80's
workload registry + sweep matrices, zookeeper.clj:112-143's test fn):

    python -m suites.etcd.runner test --node n1 ... --workload register \
        --nemesis partition
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu import cli, generator as gen
from jepsen_tpu import os as jos
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.nemesis import combined
from jepsen_tpu.workloads import linearizable_register, sets

from suites.etcd.client import RegisterClient, SetClient
from suites.etcd.db import EtcdDB


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 200)),
        threads_per_key=2)
    return {**wl, "client": RegisterClient()}


def set_workload(opts) -> Dict[str, Any]:
    wl = sets.workload()
    return {"client": SetClient(),
            "generator": wl["generator"],
            "final_generator": wl["final_generator"],
            "checker": wl["checker"]}


WORKLOADS = {"register": register_workload, "set": set_workload}

NEMESES = {
    "none": lambda opts: combined.Package(),
    "partition": lambda opts: combined.partition_package(opts),
    "kill": lambda opts: combined.db_package({**opts, "faults": ["kill"]}),
    "pause": lambda opts: combined.db_package({**opts, "faults": ["pause"]}),
    "clock": lambda opts: combined.clock_package(opts),
    "all": lambda opts: combined.nemesis_package(
        {**opts, "faults": ["partition", "kill", "pause", "clock"]}),
}


def etcd_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    workload_name = opts.get("workload", "register")
    nemesis_name = opts.get("nemesis", "partition")
    wl = WORKLOADS[workload_name](opts)
    pkg = NEMESES[nemesis_name](
        {"interval": float(opts.get("nemesis_interval", 10.0))})

    time_limit = float(opts.get("time_limit", 60.0))
    client_gen = gen.time_limit(time_limit, gen.clients(wl["generator"]))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    # final phases barrier on quiescence (gen.synchronize) so a final read
    # can't linearize before a still-in-flight op from the main phase
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(gen.nemesis(gen.lift(pkg.final_generator))))
    if wl.get("final_generator") is not None:
        parts.append(gen.synchronize(gen.clients(gen.lift(wl["final_generator"]))))

    return {**opts,
            "name": f"etcd-{workload_name}-{nemesis_name}",
            "os": jos.Debian(),
            "db": EtcdDB(),
            "client": wl["client"],
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose({"stats": Stats(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}


def all_tests(opts: Dict[str, Any]):
    """Sweep matrix: workloads x nemeses (tidb/core.clj:47-80 pattern)."""
    out = []
    for w in opts.get("workloads", list(WORKLOADS)):
        for n in opts.get("nemeses", list(NEMESES)):
            out.append(etcd_test({**opts, "workload": w, "nemesis": n}))
    return out


def _suite_opts(parser):
    parser.add_argument("--workload", default="register",
                        choices=sorted(WORKLOADS))
    parser.add_argument("--nemesis", default="partition",
                        choices=sorted(NEMESES))
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=200)
    parser.add_argument("--nemesis-interval", type=float, default=10.0)


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(etcd_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-etcd"))
