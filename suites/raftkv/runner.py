"""raftkv suite CLI — real Raft consensus under real faults, one host.

    python -m suites.raftkv.runner test --nemesis partition --time-limit 12
    python -m suites.raftkv.runner test --stale-reads --nemesis partition

Default mode must verify (every op, reads included, commits through the
replicated log on a majority).  ``--stale-reads`` serves leader-local
reads without a quorum round: a leader marooned in a minority partition
keeps answering with stale state — the checker must refute it.
"""

from __future__ import annotations

import random
from typing import Any, Dict

from jepsen_tpu import cli, generator as gen
from jepsen_tpu import net as jnet
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.control import DummyRemote
from jepsen_tpu.nemesis import combined
from jepsen_tpu.net_proxy import ProxyNet, ProxyRouter
from jepsen_tpu.workloads import linearizable_register

from suites.localkv.runner import free_ports
from suites.raftkv.client import RaftRegisterClient
from suites.raftkv.db import RaftKvDB


def _leader_isolating_grudge(ports, wait_s: float = 3.0):
    """Partition the CURRENT leader (live-discovered via ping) from the
    majority — the scenario every Raft consistency argument hinges on: the
    majority must elect a fresh leader and keep committing, while anything
    the marooned leader still answers is judged by the checker.  Discovery
    polls for up to ``wait_s`` so a partition landing mid-election still
    targets a real leader (falling back to random only if none emerges)."""
    def grudge(nodes):
        import time as _time
        from suites.raftkv.client import ping
        deadline = _time.monotonic() + wait_s
        leader = None
        while leader is None and _time.monotonic() < deadline:
            leader = next((n for n in nodes
                           if (ping(ports[n]) or {}).get("role") == "leader"),
                          None)
            if leader is None:
                _time.sleep(0.1)
        target = leader if leader is not None else random.choice(list(nodes))
        return jnet.complete_grudge(jnet.split_one(target, list(nodes)))
    return grudge


def NEMESES(name, opts, ports):
    if name == "none":
        return combined.Package()
    if name == "kill":
        return combined.db_package({**opts, "faults": ["kill"]})
    if name == "partition":
        return combined.partition_package(
            {**opts, "grudge_fn": _leader_isolating_grudge(ports)})
    if name == "maroon-leader":
        # Deterministic stale-leader scenario: ONE partition around the
        # live-discovered leader, held from ``delay`` until the final
        # heal — the forced version of what cycling partitions only
        # sometimes achieve (consul/register.clj:72's scenario).
        return combined.partition_hold_package(
            {**opts, "grudge_fn": _leader_isolating_grudge(ports)})
    raise KeyError(name)


NEMESIS_NAMES = ("none", "kill", "partition", "maroon-leader")


def raftkv_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    nodes = opts.get("nodes") or ["n1", "n2", "n3"]
    ports = free_ports(len(nodes))
    nemesis_name = opts.get("nemesis", "none")
    pkg = NEMESES(nemesis_name,
                  {"interval": float(opts.get("nemesis_interval", 3.0)),
                   "delay": float(opts.get("nemesis_delay", 1.0))},
                  dict(zip(nodes, ports)))

    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 2))),
        ops_per_key=int(opts.get("ops_per_key", 400)),
        threads_per_key=2,
        unique_writes=bool(opts.get("unique_writes")))

    time_limit = float(opts.get("time_limit", 10.0))
    wgen = wl["generator"]
    stagger_s = float(opts.get("stagger_s", 0.0))
    if stagger_s > 0:  # pace clients: bounded history -> bounded analysis
        wgen = gen.stagger(stagger_s, wgen)
    client_gen = gen.time_limit(time_limit, gen.clients(wgen))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(gen.nemesis(gen.lift(pkg.final_generator))))
    if pkg.generator is not None:
        # post-heal recovery phase (see suites/localkv/runner.py): raft
        # additionally needs election time after the final heal
        recovery = float(opts.get("recovery_time", 4.0))
        if recovery > 0:
            parts.append(gen.synchronize(gen.sleep(1.0)))
            parts.append(gen.synchronize(
                gen.time_limit(recovery, gen.clients(wgen))))

    test = {**opts,
            "name": ("raftkv-stale" if opts.get("stale_reads") else "raftkv")
                    + f"-{nemesis_name}",
            "nodes": nodes,
            "raftkv_ports": dict(zip(nodes, ports)),
            "raftkv_stale_reads": bool(opts.get("stale_reads")),
            "remote": DummyRemote(),
            "db": RaftKvDB(),
            "client": RaftRegisterClient(),
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose({"stats": Stats(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}
    if nemesis_name in ("partition", "maroon-leader"):
        router = ProxyRouter(nodes, dict(zip(nodes, ports)))
        test["proxy_router"] = router
        test["net"] = ProxyNet(router)
        test.setdefault("resources", []).append(router)
    return test


def _suite_opts(parser):
    parser.add_argument("--stale-reads", action="store_true",
                        help="leader serves reads without a quorum round "
                             "(must be refuted under partitions)")
    parser.add_argument("--nemesis", default="none",
                        choices=sorted(NEMESIS_NAMES))
    parser.add_argument("--keys", type=int, default=3)
    parser.add_argument("--ops-per-key", type=int, default=400)
    parser.add_argument("--nemesis-interval", type=float, default=3.0)
    parser.add_argument("--nemesis-delay", type=float, default=1.0,
                        help="maroon-leader: seconds before the held "
                             "partition starts")
    parser.add_argument("--unique-writes", action="store_true",
                        help="distinct write values per key: stale reads "
                             "become unambiguous violations")
    parser.add_argument("--stagger-s", type=float, default=0.0,
                        help="mean client pacing delay (bounds history and "
                             "analysis size)")
    parser.add_argument("--raftkv-commit-timeout-ms", type=int, default=3000,
                        help="server-side majority-commit wait before an "
                             "indeterminate reply")


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(raftkv_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-raftkv"))
