"""raftkv wire client: node-pinned with one leader-hint redirect.

Error discipline (zookeeper.clj:91-104 pattern): connect failures and
server-side ``definite`` errors (not-leader, cas-mismatch, truncated
entries) are FAIL; anything mid-flight or marked ``indeterminate`` (commit
timeouts — the entry may still commit!) is INFO."""

from __future__ import annotations

import socket
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites.raftkv.server import recv_frame, send_frame


def ping(port: int, timeout: float = 1.0):
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=timeout) as s:
            send_frame(s, {"type": "ping"})
            return recv_frame(s)
    except (OSError, ValueError):
        return None


class ConnectFailed(Exception):
    """The request was never sent: definite FAIL for any op."""


def _call(port: int, msg, timeout: float = 4.0):
    try:
        sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    except OSError as e:
        raise ConnectFailed(str(e)) from e
    try:
        with sock:
            send_frame(sock, msg)
            reply = recv_frame(sock)
    except OSError as e:
        raise ConnectionError(str(e)) from e
    if reply is None:
        raise ConnectionError("server closed connection")
    return reply


class RaftRegisterClient(jclient.Client):
    def __init__(self, node: Optional[str] = None):
        self.node = node

    def open(self, test, node):
        return RaftRegisterClient(node)

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        if op.f == "read":
            msg = {"op": "read", "key": f"r{k}"}
        elif op.f == "write":
            msg = {"op": "write", "key": f"r{k}", "value": v}
        else:
            msg = {"op": "cas", "key": f"r{k}", "old": v[0], "new": v[1]}
        ports = test["raftkv_ports"]
        try:
            reply = _call(ports[self.node], msg)
            if reply.get("error") == "not-leader":
                hint = reply.get("leader")
                if hint in ports:
                    # one redirect: the hinted leader may itself be stale,
                    # in which case its reply stands on its own merits
                    reply = _call(ports[hint], msg)
                else:
                    return op.with_(type=FAIL, error="not-leader (no hint)")
            if reply.get("ok"):
                if op.f == "read":
                    return op.with_(type=OK, value=(k, reply.get("value")))
                return op.with_(type=OK)
            if reply.get("definite"):
                return op.with_(type=FAIL, error=reply.get("error"))
            return op.with_(type=INFO, error=reply.get("error"))
        except ConnectFailed as e:
            return op.with_(type=FAIL, error=str(e))
        except (OSError, socket.timeout, ConnectionError) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))

    def close(self, test):
        pass
