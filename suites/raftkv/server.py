"""raftkv server — a real Raft consensus KV store in a standalone process.

The second in-repo real-server target (REALRUN.md; the first, localkv, is
primary/backup): leader election, log replication, and majority commit are
all real, over real TCP sockets, so the framework's partition/kill nemeses
exercise *consensus* — leader deposal, elections across partitions,
divergent-log repair — rather than a static primary.

Protocol (length-prefixed JSON frames, shared with localkv):
  peer RPCs    : request_vote, append_entries        (Raft §5)
  client ops   : read / write / cas on named registers
  diagnostics  : ping -> {role, term, leader}

Linearizable by construction: every client op — including reads — is a log
entry, applied to the state machine only once committed on a majority, and
the reply is generated at apply time.  ``--stale-reads`` breaks exactly
that: the leader answers reads from its local state machine immediately,
so a deposed leader marooned in a minority partition keeps serving old
values — the classic stale-leader-read violation the checker must catch.

Durability: currentTerm/votedFor and every log mutation are appended to a
WAL and fsync'd before externalization; a SIGKILL'd node replays it on
restart (Raft's persistent state, §5.1).

Stdlib only; run as ``python server.py --node n1 --port P --peers ...``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import socketserver
import struct
import sys
import threading
import time


def send_frame(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > 1 << 20:
        raise ValueError("frame too large")
    data = _recv_exact(sock, length)
    if data is None:
        return None
    return json.loads(data.decode())


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    def __init__(self, opts):
        self.node = opts.node
        self.port = opts.port
        # peers: {name: (host, port)} — possibly proxy addresses, so a
        # partition nemesis can sever exactly this node's view of a peer
        self.peers = {}
        for spec in filter(None, opts.peers.split(",")):
            name, host, port = spec.split(":")
            self.peers[name] = (host, int(port))
        self.stale_reads = opts.stale_reads
        self.election_timeout = (opts.election_ms / 1000.0,
                                 2 * opts.election_ms / 1000.0)
        self.heartbeat_s = opts.heartbeat_ms / 1000.0
        # How long a client op waits for majority commit before answering
        # indeterminately.  A worker dialing a marooned leader is stuck for
        # exactly this long per write, so partition tests shorten it to
        # keep those workers cycling (and reading!) through the window.
        self.commit_timeout_s = opts.commit_timeout_ms / 1000.0

        self.lock = threading.RLock()
        self.role = FOLLOWER
        self.current_term = 0
        self.voted_for = None
        self.log = []                    # [{"term": t, "cmd": {...}}]
        self.commit_index = 0            # 1-based count of committed entries
        self.last_applied = 0
        self.kv = {}
        self.leader_hint = None
        self.next_index = {}             # leader: peer -> next log index
        self.match_index = {}            # leader: peer -> replicated count
        # client requests awaiting commit: log index -> [event, reply-slot]
        self.waiting = {}
        self.last_heard = time.monotonic()
        self._rng = random.Random(f"{self.node}-{os.getpid()}")

        os.makedirs(opts.data, exist_ok=True)
        self.wal_path = os.path.join(opts.data, "raft.wal")
        self._replay()
        self.wal = open(self.wal_path, "a")

    # -- persistence -------------------------------------------------------

    def _replay(self) -> None:
        if not os.path.exists(self.wal_path):
            return
        with open(self.wal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail write: ignore the partial record
                t = rec.get("t")
                if t == "term":
                    self.current_term = rec["term"]
                    self.voted_for = rec.get("voted")
                elif t == "entry":
                    del self.log[rec["i"] - 1:]
                    self.log.append({"term": rec["term"], "cmd": rec["cmd"]})
                elif t == "trunc":
                    del self.log[rec["i"] - 1:]

    def _persist_term(self) -> None:
        self.wal.write(json.dumps({"t": "term", "term": self.current_term,
                                   "voted": self.voted_for}) + "\n")
        self.wal.flush()
        os.fsync(self.wal.fileno())

    def _persist_entries(self, start_i: int) -> None:
        """Persist log entries from 1-based index start_i to the end."""
        for i in range(start_i, len(self.log) + 1):
            e = self.log[i - 1]
            self.wal.write(json.dumps({"t": "entry", "i": i,
                                       "term": e["term"],
                                       "cmd": e["cmd"]}) + "\n")
        self.wal.flush()
        os.fsync(self.wal.fileno())

    def _persist_trunc(self, from_i: int) -> None:
        self.wal.write(json.dumps({"t": "trunc", "i": from_i}) + "\n")
        self.wal.flush()
        os.fsync(self.wal.fileno())

    # -- role transitions (lock held) --------------------------------------

    def _become_follower(self, term: int) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = None
            self._persist_term()
        self.role = FOLLOWER

    def _fail_waiting(self, from_i: int) -> None:
        """Entries >= from_i were truncated on THIS node: answer waiting
        clients indeterminately.  Raft Figure 8: an entry truncated on a
        deposed leader may survive on another replica and commit later, so
        the op may still take effect — reporting it as a definite failure
        would let the checker drop an op that actually executed and refute
        a correct server."""
        for i in [i for i in self.waiting if i >= from_i]:
            ev, slot = self.waiting.pop(i)
            slot.append({"ok": False, "error": "entry truncated "
                         "(leadership lost)", "indeterminate": True})
            ev.set()

    # -- Raft RPCs ---------------------------------------------------------

    def on_request_vote(self, m):
        with self.lock:
            if m["term"] > self.current_term:
                self._become_follower(m["term"])
            granted = False
            if m["term"] == self.current_term and \
                    self.voted_for in (None, m["candidate"]):
                my_last_term = self.log[-1]["term"] if self.log else 0
                up_to_date = (m["last_log_term"], m["last_log_index"]) >= \
                             (my_last_term, len(self.log))
                if up_to_date:
                    granted = True
                    self.voted_for = m["candidate"]
                    self._persist_term()
                    self.last_heard = time.monotonic()
            return {"type": "vote", "term": self.current_term,
                    "granted": granted}

    def on_append_entries(self, m):
        with self.lock:
            if m["term"] > self.current_term:
                self._become_follower(m["term"])
            if m["term"] < self.current_term:
                return {"type": "append-reply", "term": self.current_term,
                        "ok": False}
            # valid leader for this term
            self.role = FOLLOWER
            self.leader_hint = m["leader"]
            self.last_heard = time.monotonic()
            prev_i = m["prev_log_index"]
            if prev_i > len(self.log) or \
                    (prev_i > 0 and self.log[prev_i - 1]["term"]
                     != m["prev_log_term"]):
                return {"type": "append-reply", "term": self.current_term,
                        "ok": False, "have": len(self.log)}
            entries = m["entries"]
            # delete conflicts, append new
            for j, e in enumerate(entries):
                i = prev_i + 1 + j
                if i <= len(self.log):
                    if self.log[i - 1]["term"] != e["term"]:
                        self._persist_trunc(i)
                        del self.log[i - 1:]
                        self._fail_waiting(i)
                    else:
                        continue
                self.log.append(e)
                self._persist_entries(i)
            if m["leader_commit"] > self.commit_index:
                self.commit_index = min(m["leader_commit"],
                                        prev_i + len(entries))
                self._apply()
            return {"type": "append-reply", "term": self.current_term,
                    "ok": True, "have": len(self.log)}

    # -- state machine -----------------------------------------------------

    def _apply(self) -> None:
        """Apply committed entries; answer any waiting client (lock held)."""
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            e = self.log[self.last_applied - 1]
            reply = self._apply_cmd(e["cmd"])
            w = self.waiting.pop(self.last_applied, None)
            if w is not None:
                ev, slot = w
                slot.append(reply)
                ev.set()

    def _apply_cmd(self, cmd):
        op, key = cmd["op"], cmd.get("key")
        cur = self.kv.get(key)
        if op == "read":
            return {"ok": True, "value": cur}
        if op == "write":
            self.kv[key] = cmd["value"]
            return {"ok": True}
        if op == "cas":
            if cur != cmd["old"]:
                return {"ok": False, "error": "cas-mismatch",
                        "definite": True}
            self.kv[key] = cmd["new"]
            return {"ok": True}
        return {"ok": False, "error": f"bad op {op!r}", "definite": True}

    # -- client ops --------------------------------------------------------

    def on_client(self, m):
        with self.lock:
            if self.role != LEADER:
                return {"ok": False, "error": "not-leader",
                        "leader": self.leader_hint, "definite": True}
            if m["op"] == "read" and self.stale_reads:
                # the deliberate bug: local read, no quorum round
                return {"ok": True, "value": self.kv.get(m["key"])}
            cmd = {"op": m["op"], "key": m.get("key")}
            if m["op"] == "write":
                cmd["value"] = m["value"]
            elif m["op"] == "cas":
                cmd["old"], cmd["new"] = m["old"], m["new"]
            self.log.append({"term": self.current_term, "cmd": cmd})
            i = len(self.log)
            self._persist_entries(i)
            ev, slot = threading.Event(), []
            self.waiting[i] = (ev, slot)
            self.match_index[self.node] = i
        self._replicate_once()
        if not ev.wait(timeout=self.commit_timeout_s):
            with self.lock:
                self.waiting.pop(i, None)
            return {"ok": False, "error": "commit timeout",
                    "indeterminate": True}
        return slot[0]

    # -- leader / election machinery ---------------------------------------

    def _rpc(self, peer, msg, timeout=0.5):
        try:
            with socket.create_connection(self.peers[peer],
                                          timeout=timeout) as s:
                send_frame(s, msg)
                return recv_frame(s)
        except (OSError, ValueError):
            return None

    def _start_election(self) -> None:
        with self.lock:
            self.role = CANDIDATE
            self.current_term += 1
            self.voted_for = self.node
            self._persist_term()
            term = self.current_term
            last_t = self.log[-1]["term"] if self.log else 0
            req = {"type": "request_vote", "term": term,
                   "candidate": self.node,
                   "last_log_index": len(self.log), "last_log_term": last_t}
            self.last_heard = time.monotonic()
        votes = [self.node]
        lock = threading.Lock()
        majority = (len(self.peers) + 1) // 2 + 1
        won = threading.Event()

        def ask(p):
            r = self._rpc(p, req)
            if not r:
                return
            with self.lock:
                if r["term"] > self.current_term:
                    self._become_follower(r["term"])
                    return
                if not (self.role == CANDIDATE
                        and self.current_term == term):
                    return
            if r.get("granted"):
                with lock:
                    votes.append(p)
                    if len(votes) >= majority:
                        won.set()

        ts = [threading.Thread(target=ask, args=(p,), daemon=True)
              for p in self.peers]
        for t in ts:
            t.start()
        won.wait(timeout=self.election_timeout[0])
        with self.lock:
            if self.role == CANDIDATE and self.current_term == term \
                    and len(votes) >= majority:
                self.role = LEADER
                self.leader_hint = self.node
                self.next_index = {p: len(self.log) + 1 for p in self.peers}
                self.match_index = {p: 0 for p in self.peers}
                self.match_index[self.node] = len(self.log)
                print(f"raftkv {self.node} elected leader term {term}",
                      flush=True)
        self._replicate_once()

    def _replicate_once(self) -> None:
        """One append_entries round to every peer (heartbeat + catch-up)."""
        with self.lock:
            if self.role != LEADER:
                return
            term = self.current_term
            peers = list(self.peers)

        def push(p):
            while True:
                with self.lock:
                    if self.role != LEADER or self.current_term != term:
                        return
                    ni = self.next_index.get(p, len(self.log) + 1)
                    prev_i = ni - 1
                    prev_t = (self.log[prev_i - 1]["term"]
                              if prev_i > 0 else 0)
                    entries = self.log[ni - 1:ni + 63]  # <=64 per round
                    req = {"type": "append_entries", "term": term,
                           "leader": self.node, "prev_log_index": prev_i,
                           "prev_log_term": prev_t, "entries": entries,
                           "leader_commit": self.commit_index}
                r = self._rpc(p, req)
                if not r:
                    return
                with self.lock:
                    if r["term"] > self.current_term:
                        self._become_follower(r["term"])
                        return
                    if self.role != LEADER or self.current_term != term:
                        return
                    if r["ok"]:
                        self.match_index[p] = prev_i + len(entries)
                        self.next_index[p] = self.match_index[p] + 1
                        self._advance_commit()
                        if self.next_index[p] > len(self.log):
                            return
                        continue  # more to send
                    # log mismatch: back off (use follower's hint)
                    self.next_index[p] = min(ni - 1,
                                             r.get("have", ni - 1) + 1)
                    if self.next_index[p] < 1:
                        self.next_index[p] = 1

        ts = [threading.Thread(target=push, args=(p,), daemon=True)
              for p in peers]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=1.5)

    def _advance_commit(self) -> None:
        """Leader: commit the highest N replicated on a majority with
        log[N].term == currentTerm (Raft §5.4.2).  Lock held."""
        counts = sorted(self.match_index.values(), reverse=True)
        majority_n = counts[(len(self.peers) + 1) // 2]
        if majority_n > self.commit_index and \
                self.log[majority_n - 1]["term"] == self.current_term:
            self.commit_index = majority_n
            self._apply()

    def _ticker(self) -> None:
        while True:
            time.sleep(self.heartbeat_s / 2)
            with self.lock:
                role = self.role
                heard = self.last_heard
            now = time.monotonic()
            if role == LEADER:
                self._replicate_once()
            elif now - heard > self._rng.uniform(*self.election_timeout):
                self._start_election()

    # -- serving -----------------------------------------------------------

    def handle(self, m):
        t = m.get("type") or m.get("op")
        if t == "request_vote":
            return self.on_request_vote(m)
        if t == "append_entries":
            return self.on_append_entries(m)
        if t == "ping":
            with self.lock:
                return {"ok": True, "node": self.node, "role": self.role,
                        "term": self.current_term,
                        "leader": self.leader_hint}
        if t in ("read", "write", "cas"):
            return self.on_client(m)
        return {"ok": False, "error": f"bad message {t!r}",
                "definite": True}

    def serve(self) -> None:
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_frame(self.request)
                    except (OSError, ValueError):
                        return
                    if msg is None:
                        return
                    try:
                        reply = outer.handle(msg)
                    except Exception as e:  # noqa: BLE001
                        reply = {"ok": False, "error": repr(e),
                                 "indeterminate": True}
                    try:
                        send_frame(self.request, reply)
                    except OSError:
                        return

        class TS(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        threading.Thread(target=self._ticker, daemon=True).start()
        with TS(("127.0.0.1", self.port), Handler) as srv:
            print(f"raftkv {self.node} serving on {self.port} "
                  f"(stale_reads={self.stale_reads})", flush=True)
            srv.serve_forever()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--peers", default="",
                    help="name:host:port,... of the other nodes")
    ap.add_argument("--data", required=True)
    ap.add_argument("--election-ms", type=int, default=400)
    ap.add_argument("--heartbeat-ms", type=int, default=120)
    ap.add_argument("--commit-timeout-ms", type=int, default=3000)
    ap.add_argument("--stale-reads", action="store_true")
    ap.add_argument("--marker", default="", help="argv tag for grepkill")
    RaftNode(ap.parse_args(argv)).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
