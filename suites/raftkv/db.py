"""raftkv DB layer: real Raft daemons on each "node" (localkv's lifecycle
patterns: pidfiles, SIGKILL via marker grepkill, WAL snarfing)."""

from __future__ import annotations

import os
import sys
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

from suites.raftkv.client import ping

SERVER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "server.py")


def port_of(test, node: str) -> int:
    return test["raftkv_ports"][node]


def marker(test, node: str) -> str:
    return f"raftkv-{node}-p{port_of(test, node)}"


def data_dir(test, node: str) -> str:
    return os.path.join(test.get("raftkv_dir", "/tmp/jepsen-raftkv"),
                        marker(test, node))


class RaftKvDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node)
        s.exec("mkdir", "-p", data_dir(test, node))
        self.start(test, node)
        cu.await_tcp_port(s, port_of(test, node), timeout_s=30)

    def teardown(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        cu.stop_daemon(s, os.path.join(d, "server.pid"))
        cu.grepkill(s, marker(test, node))
        if not test.get("leave_db_running"):
            s.exec("rm", "-rf", d)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        router = test.get("proxy_router")

        def peer_addr(dst: str):
            if router is not None:
                return router.addr(node, dst)
            return ("127.0.0.1", port_of(test, dst))

        peers = ",".join(
            f"{n}:{peer_addr(n)[0]}:{peer_addr(n)[1]}"
            for n in test["nodes"] if n != node)
        args = [SERVER,
                "--node", node,
                "--port", str(port_of(test, node)),
                "--peers", peers,
                "--data", d,
                "--election-ms", str(test.get("raftkv_election_ms", 400)),
                "--commit-timeout-ms",
                str(test.get("raftkv_commit_timeout_ms", 3000)),
                "--marker", marker(test, node)]
        if test.get("raftkv_stale_reads"):
            args.append("--stale-reads")
        # PYTHONPATH emptied: see suites/localkv/db.py — the harness env's
        # sitecustomize costs ~2 s of CPU per interpreter start, which
        # under a kill nemesis keeps restarted servers from ever serving.
        cu.start_daemon(s, sys.executable, *args,
                        pidfile=os.path.join(d, "server.pid"),
                        logfile=os.path.join(d, "server.log"),
                        env={"PYTHONPATH": ""})

    def kill(self, test, node):
        s = session(test, node)
        cu.grepkill(s, marker(test, node))
        s.exec("rm", "-f", os.path.join(data_dir(test, node), "server.pid"))

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="CONT")

    # -- Primary capability (real leader discovery) ------------------------
    def primaries(self, test) -> List[str]:
        out = []
        for n in test["nodes"]:
            r = ping(port_of(test, n))
            if r and r.get("role") == "leader":
                out.append(n)
        return out

    # -- LogFiles capability ----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        d = data_dir(test, node)
        return [os.path.join(d, "server.log"), os.path.join(d, "raft.wal")]
