"""ZooKeeper ensemble install/start on test nodes.

Parity: the db reify in zookeeper/src/jepsen/zookeeper.clj:41-73 — apt
packages, per-node myid from the node's index, zoo.cfg with the server.N
ensemble lines, service restart; logs snarfed from /var/log/zookeeper.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

CONF = "/etc/zookeeper/conf"
LOG = "/var/log/zookeeper/zookeeper.log"

ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
maxClientCnxns=0
"""


def node_id(test, node) -> int:
    return test["nodes"].index(node)


class ZookeeperDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def __init__(self, version: str = "3.4.13-2"):
        self.version = version

    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("apt-get", "install", "-y",
               f"zookeeper={self.version}", f"zookeeper-bin={self.version}",
               f"zookeeperd={self.version}")
        s.exec("sh", "-c", f"echo {node_id(test, node)} > {CONF}/myid")
        servers = "\n".join(
            f"server.{i}={n}:2888:3888"
            for i, n in enumerate(test["nodes"]))
        cu.write_file(s, ZOO_CFG + servers + "\n", f"{CONF}/zoo.cfg")
        s.exec("service", "zookeeper", "stop")
        s.exec("service", "zookeeper", "start")
        cu.await_tcp_port(s, 2181, timeout_s=60)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        try:
            s.exec("service", "zookeeper", "stop")
        except Exception:  # noqa: BLE001 — may not be installed yet
            pass
        s.exec("sh", "-c",
               "rm -rf /var/lib/zookeeper/version-* /var/log/zookeeper/*")

    def start(self, test, node):
        session(test, node).sudo().exec("service", "zookeeper", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "QuorumPeerMain")

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "QuorumPeerMain", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "QuorumPeerMain", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOG]
