"""ZooKeeper CAS-register client over versioned znodes.

The reference drives this through avout's zk-atom
(zookeeper/src/jepsen/zookeeper.clj:80-110: read = deref, write = reset!!,
cas = swap!! comparing current); here the same semantics come from the
znode version counter: read returns (value, version), cas is
set_data(version=read-version), retried on BadVersion only for the value
comparison — a version conflict where the value still matches is retried,
a value mismatch is a definite :fail.
"""

from __future__ import annotations

import socket
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.zk import ZkClient, ZkError
from jepsen_tpu.history import FAIL, INFO, OK, Op

CAS_RETRIES = 16


class RegisterClient(jclient.Client):
    def __init__(self, conn: Optional[ZkClient] = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(ZkClient(node, port=test.get("db_port", 2181),
                                       timeout=5.0))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _path(self, k) -> str:
        return f"/jepsen-r{k}"

    def _ensure(self, path):
        try:
            self.conn.create(path, b"")
        except ZkError as e:
            if e.code != -110:  # NodeExists is fine
                raise

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        path = self._path(k)
        try:
            if op.f == "read":
                try:
                    data, _ = self.conn.get_data(path)
                except ZkError as e:
                    if e.no_node:
                        return op.with_(type=OK, value=(k, None))
                    raise
                return op.with_(
                    type=OK, value=(k, int(data) if data else None))
            if op.f == "write":
                self._ensure(path)
                self.conn.set_data(path, str(v).encode(), version=-1)
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                for _ in range(CAS_RETRIES):
                    try:
                        data, ver = self.conn.get_data(path)
                    except ZkError as e:
                        if e.no_node:
                            return op.with_(type=FAIL)
                        raise
                    cur = int(data) if data else None
                    if cur != old:
                        return op.with_(type=FAIL)
                    try:
                        self.conn.set_data(path, str(new).encode(),
                                           version=ver)
                        return op.with_(type=OK)
                    except ZkError as e:
                        if not e.bad_version:
                            raise
                        # lost the race; re-read and re-compare
                return op.with_(type=FAIL, error="cas-retries-exhausted")
            raise ValueError(op.f)
        except (ConnectionError, OSError, socket.timeout, TimeoutError,
                ZkError) as e:
            self.conn.close()
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
