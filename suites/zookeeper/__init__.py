"""ZooKeeper suite — the reference's minimal canonical test
(zookeeper/src/jepsen/zookeeper.clj, BASELINE config #2): a linearizable
compare-and-set register over versioned znodes, checked on device."""
