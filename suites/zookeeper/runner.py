"""ZooKeeper suite CLI.

Parity: zookeeper/src/jepsen/zookeeper.clj:112-143 (zk-test merging
noop-test, mix of r/w/cas staggered, partition-random-node nemesis,
per-key knossos linearizable checking — here the device engine).

    python -m suites.zookeeper.runner test --node n1 ... [--dummy-ssh]
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.zookeeper.client import RegisterClient
from suites.zookeeper.db import ZookeeperDB


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 200)),
        threads_per_key=2)
    return {**wl, "client": RegisterClient()}


WORKLOADS = {"register": register_workload}


def zk_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="zookeeper",
                             db=ZookeeperDB(opts.get("version", "3.4.13-2")),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, zk_test, WORKLOADS)


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=200)
    parser.add_argument("--version", default="3.4.13-2")


if __name__ == "__main__":
    import sys
    sys.exit(common.main(zk_test, WORKLOADS, prog="jepsen-tpu-zookeeper",
                         extra_opts=_extra))
