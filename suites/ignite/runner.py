"""Ignite suite CLI.

Parity: ignite/src/jepsen/ignite/runner.clj's test matrix (register +
bank across concurrency/isolation modes) and nemesis.clj (kill-node
start-stopper, random-halves partitions).
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.workloads import bank as bank_wl
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.ignite.client import BankClient, RegisterClient
from suites.ignite.db import IgniteDB


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 100)),
        threads_per_key=2)
    return {**wl, "client": RegisterClient()}


def bank_workload(opts) -> Dict[str, Any]:
    wl = bank_wl.workload(accounts=list(range(10)))
    return {**wl, "client": BankClient(
        concurrency=opts.get("tx_concurrency", "pessimistic"),
        isolation=opts.get("tx_isolation", "serializable"))}


WORKLOADS = {"register": register_workload, "bank": bank_workload}


def ignite_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    t = common.build_test(opts, suite="ignite", db=IgniteDB(),
                          workloads=WORKLOADS)
    if opts.get("workload") == "bank":
        t["bank"] = {"accounts": list(range(10)),
                     "total_amount": int(opts.get("total_amount", 100))}
    return t


def all_tests(opts: Dict[str, Any]):
    """runner.clj's sweep: workloads x tx modes x nemeses."""
    out = []
    for w in opts.get("workloads", sorted(WORKLOADS)):
        for n in opts.get("nemeses", sorted(common.STANDARD_NEMESES)):
            out.append(ignite_test({**opts, "workload": w, "nemesis": n}))
    return out


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=100)
    parser.add_argument("--total-amount", type=int, default=100)
    parser.add_argument("--pds", action="store_true",
                        help="enable native persistence")
    parser.add_argument("--tx-concurrency", default="pessimistic",
                        choices=["optimistic", "pessimistic"])
    parser.add_argument("--tx-isolation", default="serializable",
                        choices=["read-committed", "repeatable-read",
                                 "serializable"])


if __name__ == "__main__":
    import sys
    sys.exit(common.main(ignite_test, WORKLOADS,
                         prog="jepsen-tpu-ignite", extra_opts=_extra))
