"""Apache Ignite install/config/start.

Parity: ignite/src/jepsen/ignite.clj — download the binary distribution,
render an IgniteConfiguration XML with a static-IP discovery finder over
the test's nodes (configure/configure-client), start ignite.sh as a
daemon, stop via grepkill (nemesis.clj's kill-node start-stopper).
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "2.16.0"
URL = (f"https://archive.apache.org/dist/ignite/{VERSION}/"
       f"apache-ignite-{VERSION}-bin.zip")
DIR = "/opt/ignite"
CONF = f"{DIR}/config/jepsen.xml"
LOGFILE = "/var/log/ignite.log"
PIDFILE = "/var/run/ignite.pid"
THIN_PORT = 10800
DISCO_PORT = 47500

XML = """\
<?xml version="1.0" encoding="UTF-8"?>
<beans xmlns="http://www.springframework.org/schema/beans"
       xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance"
       xsi:schemaLocation="http://www.springframework.org/schema/beans
           http://www.springframework.org/schema/beans/spring-beans.xsd">
  <bean class="org.apache.ignite.configuration.IgniteConfiguration">
    <property name="clientConnectorConfiguration">
      <bean class="org.apache.ignite.configuration.\
ClientConnectorConfiguration">
        <property name="port" value="{thin_port}"/>
        <property name="thinClientEnabled" value="true"/>
      </bean>
    </property>
{pds}
    <property name="discoverySpi">
      <bean class="org.apache.ignite.spi.discovery.tcp.TcpDiscoverySpi">
        <property name="ipFinder">
          <bean class="org.apache.ignite.spi.discovery.tcp.ipfinder.vm.\
TcpDiscoveryVmIpFinder">
            <property name="addresses">
              <list>
{addresses}
              </list>
            </property>
          </bean>
        </property>
      </bean>
    </property>
  </bean>
</beans>
"""

PDS_XML = """\
    <property name="dataStorageConfiguration">
      <bean class="org.apache.ignite.configuration.\
DataStorageConfiguration">
        <property name="defaultDataRegionConfiguration">
          <bean class="org.apache.ignite.configuration.\
DataRegionConfiguration">
            <property name="persistenceEnabled" value="true"/>
          </bean>
        </property>
      </bean>
    </property>
"""


def config(test) -> str:
    addresses = "\n".join(
        f'                <value>{n}:{DISCO_PORT}..{DISCO_PORT + 2}</value>'
        for n in test["nodes"])
    return XML.format(thin_port=THIN_PORT, addresses=addresses,
                      pds=PDS_XML if test.get("pds") else "")


class IgniteDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        s.exec("bash", "-c",
               f"[ -x {DIR}/bin/ignite.sh ] || "
               f"cp -r {DIR}/apache-ignite-*/* {DIR}/ 2>/dev/null || true")
        cu.write_file(s, config(test), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, THIN_PORT, timeout_s=180)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "ignite")
        s.exec("sh", "-c", f"rm -rf {DIR}/work {LOGFILE} {PIDFILE}")

    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(s, f"{DIR}/bin/ignite.sh", CONF,
                        pidfile=PIDFILE, logfile=LOGFILE,
                        env={"IGNITE_HOME": DIR})

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "ignite")
        s.exec("rm", "-f", PIDFILE)

    def pause(self, test, node):
        # the server process is a JVM named "java"; match the full
        # cmdline (the ignite config path) like kill() does
        cu.grepkill(session(test, node).sudo(), "ignite", signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node).sudo(), "ignite", signal="CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
