"""Apache Ignite suite (reference: ignite/ — register and transactional
bank workloads over cache operations)."""
