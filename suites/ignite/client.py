"""Ignite workload clients.

Parity: ignite/src/jepsen/ignite/register.clj:22-49 (cache get / put /
replace(old,new) on cache "REGISTER") and bank.clj:22-78 (n accounts in
cache "ACCOUNTS", transactional read-all and transfer with configurable
concurrency/isolation — txStart…commit around getAll/puts).
"""

from __future__ import annotations

import socket
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.ignite import IgniteClient, IgniteError
from jepsen_tpu.history import FAIL, INFO, OK, Op

THIN_PORT = 10800
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)

CONCURRENCY = {"optimistic": 0, "pessimistic": 1}
ISOLATION = {"read-committed": 0, "repeatable-read": 1, "serializable": 2}


def connect(test, node) -> IgniteClient:
    return IgniteClient(node, port=int(test.get("db_port", THIN_PORT)))


class RegisterClient(jclient.Client):
    CACHE = "REGISTER"

    def __init__(self, conn: Optional[IgniteClient] = None,
                 node: Optional[str] = None):
        self.conn = conn
        self.node = node

    def open(self, test, node):
        c = connect(test, node)
        c.get_or_create_cache(self.CACHE)
        return RegisterClient(c, node)

    def _reconnect(self, test):
        """A dead socket must not poison every later op on this worker —
        the interpreter only swaps clients after an INFO crash."""
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.conn = connect(test, self.node)
        except Exception:  # noqa: BLE001 — node may be down; retry later
            pass

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        key = f"k{k}"
        try:
            if op.f == "read":
                return op.with_(type=OK,
                                value=(k, self.conn.get(self.CACHE, key)))
            if op.f == "write":
                self.conn.put(self.CACHE, key, v)
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                ok = self.conn.replace_if_equals(self.CACHE, key, old, new)
                return op.with_(type=OK if ok else FAIL)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self._reconnect(test)
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except IgniteError as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))


class BankClient(jclient.Client):
    """Transfers and read-alls inside explicit transactions
    (bank.clj:27-78)."""

    CACHE = "ACCOUNTS"

    def __init__(self, concurrency: str = "pessimistic",
                 isolation: str = "serializable",
                 conn: Optional[IgniteClient] = None,
                 node: Optional[str] = None):
        self.concurrency = concurrency
        self.isolation = isolation
        self.conn = conn
        self.node = node

    def open(self, test, node):
        c = connect(test, node)
        c.get_or_create_cache(self.CACHE)
        return BankClient(self.concurrency, self.isolation, c, node)

    def _reconnect(self, test):
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.conn = connect(test, self.node)
        except Exception:  # noqa: BLE001 — node may be down; retry later
            pass

    def setup(self, test):
        wl = test.get("bank", {})
        accounts = wl.get("accounts", list(range(10)))
        total = wl.get("total_amount", 100)
        per = total // len(accounts)
        existing = self.conn.get_all(self.CACHE, accounts)
        if len(existing) < len(accounts):
            self.conn.put_all(self.CACHE, {
                a: per + (total - per * len(accounts) if i == 0 else 0)
                for i, a in enumerate(accounts)})

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _tx(self):
        self.conn.tx_start(CONCURRENCY[self.concurrency],
                           ISOLATION[self.isolation])

    def invoke(self, test, op: Op) -> Op:
        accounts = test.get("bank", {}).get("accounts", list(range(10)))
        try:
            if op.f == "read":
                self._tx()
                try:
                    vals = self.conn.get_all(self.CACHE, accounts)
                    self.conn.tx_end(commit=True)
                except BaseException:
                    # commit may have cleared tx_id before failing
                    if self.conn.tx_id is not None:
                        self.conn.tx_end(commit=False)
                    raise
                return op.with_(type=OK, value=dict(sorted(vals.items())))
            if op.f == "transfer":
                v = op.value
                frm, to, amt = v["from"], v["to"], v["amount"]
                self._tx()
                try:
                    cur = self.conn.get_all(self.CACHE, [frm, to])
                    if cur.get(frm, 0) < amt:
                        self.conn.tx_end(commit=False)
                        return op.with_(type=FAIL,
                                        error="insufficient funds")
                    self.conn.put_all(self.CACHE, {
                        frm: cur.get(frm, 0) - amt,
                        to: cur.get(to, 0) + amt})
                    self.conn.tx_end(commit=True)
                except BaseException:
                    if self.conn.tx_id is not None:
                        self.conn.tx_end(commit=False)
                    raise
                return op.with_(type=OK)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self._reconnect(test)
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except IgniteError as e:
            # tx conflicts / timeouts definitely rolled back
            if "status" in str(e) and op.f == "transfer":
                return op.with_(type=FAIL, error=str(e))
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
