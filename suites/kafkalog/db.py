"""kafkalog DB layer: the real log daemon's lifecycle (localkv's
patterns: pidfiles, marker grepkill, WAL snarfing)."""

from __future__ import annotations

import os
import sys
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

SERVER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "server.py")


def port_of(test, node: str) -> int:
    return test["kafkalog_ports"][node]


def marker(test, node: str) -> str:
    return f"kafkalog-{node}-p{port_of(test, node)}"


def data_dir(test, node: str) -> str:
    return os.path.join(test.get("kafkalog_dir", "/tmp/jepsen-kafkalog"),
                        marker(test, node))


class KafkaLogDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node)
        s.exec("mkdir", "-p", data_dir(test, node))
        self.start(test, node)
        cu.await_tcp_port(s, port_of(test, node), timeout_s=30)

    def teardown(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        cu.stop_daemon(s, os.path.join(d, "server.pid"))
        cu.grepkill(s, marker(test, node))
        if not test.get("leave_db_running"):
            s.exec("rm", "-rf", d)

    def start(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        args = [SERVER,
                "--node", node,
                "--port", str(port_of(test, node)),
                "--data", d,
                "--marker", marker(test, node)]
        if test.get("kafkalog_no_fsync"):
            args.append("--no-fsync")
        dup = float(test.get("kafkalog_dup_sends", 0.0))
        if dup:
            args += ["--dup-sends", str(dup)]
        # PYTHONPATH emptied: the harness env's sitecustomize costs ~2 s
        # per interpreter start (see suites/localkv/db.py)
        cu.start_daemon(s, sys.executable, *args,
                        pidfile=os.path.join(d, "server.pid"),
                        logfile=os.path.join(d, "server.log"),
                        env={"PYTHONPATH": ""})

    def kill(self, test, node):
        s = session(test, node)
        cu.grepkill(s, marker(test, node))
        s.exec("rm", "-f", os.path.join(data_dir(test, node), "server.pid"))

    def pause(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="CONT")

    def log_files(self, test, node) -> List[str]:
        d = data_dir(test, node)
        return [os.path.join(d, "server.log"), os.path.join(d, "log.wal")]
