"""kafkalog wire client: executes the kafka workload's op language against
the real log server.

Consumer positions live here (kafka's assign/seek/poll shape).  assign
and subscribe take ownership of the listed partitions and resume from the
GROUP'S COMMITTED offsets (kafka consumer-group semantics: positions are
auto-committed after each successful poll, so a rebalance or a fresh
client re-reads at most the uncommitted tail and NEVER skips unread
records).  A partition with no committed offset starts at offset 0 —
kafka's auto.offset.reset=earliest; the suite's log has no retention, so
0 always exists.  (Starting such a partition at the log END instead let
the next poll's auto-commit pin never-polled keys to that end, and the
whole group skipped every record below it — an acked record no consumer
era covered read as a lost-write.)  The final-polls catch-up
phase still forces ``op.extra["seek_to_beginning"]``.  ``crash``
completes :info so the interpreter burns the process and opens a fresh
client — kafka.clj's crash-client semantics.

Error discipline: connect failures are FAIL (nothing was sent);
mid-flight failures are INFO for txns containing sends (they may have
landed — the checker's recovered-:info machinery takes over) and FAIL for
pure polls."""

from __future__ import annotations

import socket
from typing import Dict, Optional, Set

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites.kafkalog.server import recv_frame, send_frame


class ConnectFailed(Exception):
    pass


class Conn:
    def __init__(self, port: int, timeout: float = 3.0):
        self.port = port
        self.timeout = timeout
        self.sock = None

    def call(self, msg):
        if self.sock is None:
            try:
                self.sock = socket.create_connection(
                    ("127.0.0.1", self.port), timeout=self.timeout)
            except OSError as e:
                raise ConnectFailed(str(e)) from e
        try:
            send_frame(self.sock, msg)
            reply = recv_frame(self.sock)
        except OSError:
            self.close()
            raise
        if reply is None:
            self.close()
            raise ConnectionError("server closed connection")
        return reply

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class KafkaLogClient(jclient.Client):
    def __init__(self, conn: Optional[Conn] = None,
                 group: str = "jepsen-group"):
        self.conn = conn
        self.group = group
        self.owned: Set[int] = set()
        self.positions: Dict[int, int] = {}

    def open(self, test, node):
        return KafkaLogClient(Conn(test["kafkalog_ports"][node]),
                              group=test.get("kafka_group", "jepsen-group"))

    def _seek(self, keys, to_beginning: bool) -> None:
        self.owned = set(keys)
        if to_beginning:
            self.positions = {k: 0 for k in self.owned}
            return
        committed = self.conn.call(
            {"op": "committed", "group": self.group,
             "keys": sorted(self.owned)})["offsets"]
        # A partition with no committed offset starts at offset 0
        # (auto.offset.reset=earliest).  Seeking to the log END here is
        # wrong: the next poll's auto-commit would commit that end
        # position for keys this era never polled, and the whole group
        # would skip every record below it forever.
        self.positions = {int(k): max(0, int(pos))
                          for k, pos in committed.items()}

    def _auto_commit(self) -> None:
        """Commit the current positions (kafka auto-commit after poll).
        Best-effort: a lost commit only re-reads the uncommitted tail."""
        if not self.positions:
            return
        try:
            self.conn.call({"op": "commit", "group": self.group,
                            "offsets": {str(k): v
                                        for k, v in self.positions.items()}})
        except (ConnectFailed, ConnectionError, OSError):
            pass

    def invoke(self, test, op: Op) -> Op:
        sent_any = False
        try:
            if op.f in ("assign", "subscribe"):
                self._seek(op.value or [],
                           bool(op.extra.get("seek_to_beginning")))
                return op.with_(type=OK)
            if op.f == "crash":
                # deliberate client crash: the process burns, a fresh
                # client (fresh positions) opens for its successor
                return op.with_(type=INFO, error="crashed by request")
            if op.f == "debug-topic-partitions":
                ends = self.conn.call({"op": "end_offsets",
                                       "keys": sorted(op.value or [])})
                return op.with_(type=OK, value=ends["ends"])
            if not isinstance(op.value, (list, tuple)):
                return op.with_(type=FAIL, error="not a txn op")
            out = []
            for mop in op.value:
                if mop[0] == "send":
                    r = self.conn.call({"op": "send", "key": mop[1],
                                        "value": mop[2]})
                    sent_any = True
                    out.append(["send", mop[1], [r["offset"], mop[2]]])
                else:  # poll
                    pos = {k: self.positions.get(k, 0)
                           for k in sorted(self.owned)}
                    r = self.conn.call({"op": "poll", "positions": pos,
                                        "max": 6})
                    recs = {int(k): v for k, v in r["records"].items()}
                    for k, rows in recs.items():
                        if rows:
                            self.positions[k] = rows[-1][0] + 1
                    self._auto_commit()
                    out.append(["poll", recs])
            return op.with_(type=OK, value=out)
        except ConnectFailed as e:
            # nothing of THIS op was sent... unless an earlier mop already
            # landed (reconnect happens per call): sends may have applied
            if sent_any:
                return op.with_(type=INFO, error=str(e))
            return op.with_(type=FAIL, error=str(e))
        except (OSError, socket.timeout, ConnectionError) as e:
            mops = op.value if isinstance(op.value, (list, tuple)) else []
            has_send = any(isinstance(m, (list, tuple)) and m
                           and m[0] == "send" for m in mops)
            return op.with_(type=INFO if (sent_any or has_send) else FAIL,
                            error=str(e))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()
