"""kafkalog suite CLI — the kafka workload end-to-end against a real
partitioned log daemon.

    python -m suites.kafkalog.runner test --time-limit 8
    python -m suites.kafkalog.runner test --nemesis kill --no-fsync

Default mode must verify (fsync'd WAL: kills cost availability, never
acked records).  ``--no-fsync`` loses the acked tail on SIGKILL and later
sends re-use the lost offsets — the kafka checker's lost-write /
inconsistent-offsets analyses must refute it.  ``--dup-sends`` seeds
double-applied sends the duplicate analysis must catch.

The generator is the REFERENCE pipeline (kafka.clj:2106): list-append
txns rewritten to send/poll, subscribe interleaving, unseen-chasing,
offset tracking, and a final-polls catch-up phase that crashes clients,
assigns from the beginning, and polls until every tracked offset has
been observed.
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu import cli, generator as gen
from jepsen_tpu.checker import compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.control import DummyRemote
from jepsen_tpu.nemesis import combined
from jepsen_tpu.workloads import kafka
from jepsen_tpu.workloads.kafka import KafkaStats

from suites.localkv.runner import free_ports
from suites.kafkalog.client import KafkaLogClient
from suites.kafkalog.db import KafkaLogDB


class VanishedLog:
    """Suite-specific strengthening of the kafka analyses: the kafkalog
    daemon is an append-only log with NO retention/compaction and
    synchronous polls, so a record once observed at (k, offset) can never
    legitimately disappear from a later seek-to-beginning read.  The
    generic offset analyses cannot use this (real kafka has retention, and
    an empty poll is indistinguishable from consumer lag) — which is
    exactly how a kill that wipes the whole log AFTER everything was
    observed once slipped past them: nothing contradicts a history whose
    final catch-up simply reads nothing.  Here an OK poll in a
    seek-to-beginning era that returns records starting past the key's
    earliest observed offset — or no records at all while the key
    demonstrably held observed records — refutes durability.

    (jepsen.checker protocol shape; composed into the suite's checker the
    way localkv adds its own invariants.)"""

    def check(self, test, history, opts=None):
        from jepsen_tpu.workloads.kafka import _poll_records
        # ONE chronological pass: ``observed`` holds only offsets seen
        # STRICTLY BEFORE the op being judged, so a record that lands
        # (and is observed) after an era's legitimately-empty early poll
        # can never retroactively refute it.
        observed: Dict[Any, Dict[int, Any]] = {}
        vanished = []
        era_keys: Dict[Any, list] = {}     # process -> keys of current era
        era_first: Dict[Any, Dict[int, int]] = {}  # process -> k -> first
        for op in history:
            if op.f == "assign" and op.type == "invoke" \
                    and (op.extra or {}).get("seek_to_beginning"):
                era_keys[op.process] = [int(k) for k in (op.value or [])]
                era_first[op.process] = {}
            elif op.f in ("assign", "subscribe") and op.type == "invoke":
                era_keys.pop(op.process, None)
            elif (op.type == "ok" and op.process in era_keys
                  and isinstance(op.value, (list, tuple))):
                for m in op.value:
                    if not (isinstance(m, (list, tuple)) and m
                            and m[0] == "poll" and isinstance(m[1], dict)):
                        continue
                    for k in era_keys[op.process]:
                        recs = m[1].get(k, m[1].get(str(k), []))
                        firsts = era_first[op.process]
                        if k in firsts:
                            continue  # era's first record already judged
                        prior = observed.get(k, {})
                        if recs:
                            # Latch the era-first record even with no prior
                            # observations: this poll's records land in
                            # ``observed`` below, so skipping the latch here
                            # would judge the era's SECOND poll against the
                            # first poll's own records — a false positive
                            # on any clean two-poll catch-up.
                            firsts[k] = int(recs[0][0])
                            if prior and int(recs[0][0]) > min(prior):
                                vanished.append(
                                    {"key": k, "era-first": int(recs[0][0]),
                                     "earliest-observed": min(prior),
                                     "process": op.process})
                        elif prior:
                            # synchronous read from the beginning returned
                            # nothing although observed records existed
                            firsts[k] = -1
                            vanished.append(
                                {"key": k, "era-first": None,
                                 "earliest-observed": min(prior),
                                 "process": op.process})
                        # empty poll, nothing observed yet: legitimately
                        # empty log — the era's first records are still to
                        # come, so leave the latch open
            if op.type == "ok":
                for k, o, v in _poll_records(op):
                    observed.setdefault(int(k), {}).setdefault(int(o), v)
        return {"valid": not vanished,
                "vanished": vanished[:16],
                "vanished-count": len(vanished)}


def NEMESES(name, opts):
    if name == "none":
        return combined.Package()
    if name == "kill":
        return combined.db_package({**opts, "faults": ["kill"]})
    if name == "pause":
        return combined.db_package({**opts, "faults": ["pause"]})
    raise KeyError(name)


NEMESIS_NAMES = ("none", "kill", "pause")


def kafkalog_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    # Single broker: every client talks to ONE log daemon (the reference's
    # kafka workload likewise drives one cluster through many clients).
    # Multiple nodes would be multiple INDEPENDENT logs, and the offset
    # analyses would correctly — but meaninglessly — refute the overlap.
    nodes = (opts.get("nodes") or ["n1"])[:1]
    ports = free_ports(len(nodes))
    nemesis_name = opts.get("nemesis", "none")
    pkg = NEMESES(nemesis_name,
                  {"interval": float(opts.get("nemesis_interval", 3.0))})

    wl = kafka.workload(partitions=int(opts.get("partitions", 4)),
                        reference_shape=True,
                        concurrency=int(opts.get("concurrency", 4)))

    time_limit = float(opts.get("time_limit", 8.0))
    wgen = wl["generator"]
    stagger_s = float(opts.get("stagger_s", 0.01))
    if stagger_s > 0:
        wgen = gen.stagger(stagger_s, wgen)
    client_gen = gen.time_limit(time_limit, gen.clients(wgen))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(gen.nemesis(gen.lift(
            pkg.final_generator))))
    # the final-polls catch-up phase: crash, assign from the beginning,
    # poll until every tracked offset is seen (bounded by its own window)
    final_s = float(opts.get("final_time", 6.0))
    parts.append(gen.synchronize(gen.time_limit(
        final_s, gen.clients(gen.lift(wl["final_generator"])))))

    return {**opts,
            "name": "kafkalog"
                    + ("-nofsync" if opts.get("no_fsync") else "")
                    + (f"-dup" if opts.get("dup_sends") else "")
                    + f"-{nemesis_name}",
            "nodes": nodes,
            "kafkalog_ports": dict(zip(nodes, ports)),
            "kafkalog_no_fsync": bool(opts.get("no_fsync")),
            "kafkalog_dup_sends": float(opts.get("dup_sends", 0.0)),
            "remote": DummyRemote(),
            "db": KafkaLogDB(),
            "client": KafkaLogClient(),
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose({"stats": KafkaStats(),
                                "durability": VanishedLog(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}


def _suite_opts(parser):
    parser.add_argument("--nemesis", default="none",
                        choices=sorted(NEMESIS_NAMES))
    parser.add_argument("--nemesis-interval", type=float, default=3.0)
    parser.add_argument("--partitions", type=int, default=4)
    parser.add_argument("--no-fsync", action="store_true",
                        help="ack before fsync: kills lose the acked tail "
                             "(must be refuted)")
    parser.add_argument("--dup-sends", type=float, default=0.0,
                        help="probability a send applies twice (must be "
                             "refuted)")
    parser.add_argument("--stagger-s", type=float, default=0.01)
    parser.add_argument("--final-time", type=float, default=6.0)


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(kafkalog_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-kafkalog"))
