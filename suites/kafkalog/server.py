"""kafkalog server — a real partitioned append-only log in a standalone
process: the system-under-test that exercises the kafka workload's
analyses (jepsen_tpu/workloads/kafka.py; reference analyses at
jepsen/src/jepsen/tests/kafka.clj) against a real wire server instead of
constructed histories.

Semantics (a deliberately small kafka): named integer partitions, each an
append-only list of values; ``send`` appends and acks the assigned offset;
``poll`` reads from a caller-supplied per-partition position (consumer
positions live client-side, like kafka's assign/seek/poll);
``end_offsets`` reports log ends (the client's assign/subscribe seek-to-end
and the final-polls catch-up both use it).

Durability: every send appends to a per-server WAL and — in the default
mode — fsyncs before acking, so a SIGKILL'd server replays to exactly the
acked log.  Seeded bugs the checker must catch:

- ``--no-fsync``: acks before the WAL hits disk; a kill loses the acked
  tail, and any later send re-uses those offsets -> the kafka checker's
  lost-write / inconsistent-offsets analyses fire.
- ``--dup-sends P``: with probability P a send is applied twice (two
  offsets ack one value... the second append is silent) -> duplicate.

Protocol: length-prefixed JSON frames (shared with localkv/raftkv):
  {"op": "send", "key": k, "value": v}                -> {"ok", "offset"}
  {"op": "poll", "positions": {k: pos}, "max": n}     -> {"ok", "records":
                                                          {k: [[o, v]...]}}
  {"op": "end_offsets", "keys": [k...]}               -> {"ok", "ends"}
  {"op": "commit", "group": g, "offsets": {k: pos}}   -> {"ok"}
  {"op": "committed", "group": g, "keys": [k...]}     -> {"ok", "offsets"}
  {"op": "ping"}                                      -> {"ok", "node"}

Stdlib only; run as ``python server.py --node n1 --port P --data DIR``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socketserver
import struct
import sys
import threading


def send_frame(sock, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock):
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > 1 << 20:
        raise ValueError("frame too large")
    data = _recv_exact(sock, length)
    if data is None:
        return None
    return json.loads(data.decode())


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class LogStore:
    def __init__(self, data_dir: str, fsync: bool, dup_p: float,
                 seed: str):
        os.makedirs(data_dir, exist_ok=True)
        self.lock = threading.Lock()
        self.logs: dict = {}     # k -> [value]
        # group -> {k: committed position} — kafka's __consumer_offsets
        # role: consumer groups resume from committed positions, so a
        # rebalance NEVER skips unread records (a seek-to-latest client
        # produced era-jump gaps that read as lost-writes).  Persisted in
        # the WAL under the same fsync policy as the data (kafka's
        # offsets topic is a log with the same durability knobs).
        self.committed: dict = {}
        self.fsync = fsync
        self.dup_p = dup_p
        self._rng = random.Random(seed)
        self.wal_path = os.path.join(data_dir, "log.wal")
        self._replay()
        # fsync mode: small buffer, flush+fsync per send.  no-fsync mode:
        # a large USERSPACE buffer that is never flushed — a SIGKILL then
        # really loses the acked tail (flushing to the OS page cache would
        # survive a process kill; only the user buffer models the
        # ack-before-durable bug a kill can expose).
        self.wal = open(self.wal_path, "a",
                        buffering=(8 * 1024 * 1024) if not fsync else -1)

    def _replay(self) -> None:
        if not os.path.exists(self.wal_path):
            return
        with open(self.wal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail write
                if "c" in rec:  # committed-offsets record
                    g = self.committed.setdefault(rec["c"], {})
                    for k, pos in rec["o"].items():
                        kk = int(k) if str(k).isdigit() else k
                        g[kk] = max(g.get(kk, -1), int(pos))
                    continue
                self.logs.setdefault(rec["k"], []).append(rec["v"])

    def send(self, k, v):
        with self.lock:
            log = self.logs.setdefault(k, [])
            log.append(v)
            off = len(log) - 1
            self.wal.write(json.dumps({"k": k, "v": v}) + "\n")
            if self.dup_p and self._rng.random() < self.dup_p:
                # seeded duplicate: the record lands twice, one ack
                log.append(v)
                self.wal.write(json.dumps({"k": k, "v": v}) + "\n")
            if self.fsync:
                self.wal.flush()
                os.fsync(self.wal.fileno())
            return off

    def poll(self, positions, max_records):
        out = {}
        with self.lock:
            for k, pos in positions.items():
                log = self.logs.get(int(k) if str(k).isdigit() else k, [])
                pos = max(0, int(pos))
                out[k] = [[o, log[o]]
                          for o in range(pos, min(pos + max_records,
                                                  len(log)))]
        return out

    def end_offsets(self, keys):
        with self.lock:
            return {k: len(self.logs.get(
                int(k) if str(k).isdigit() else k, [])) for k in keys}

    def commit(self, group, offsets):
        """Advance the group's committed positions (monotonic max — a
        stale consumer's late commit must not rewind a newer one past
        re-read safety; kafka's group coordinator is last-write-wins, the
        max keeps the gap-free invariant strictly)."""
        with self.lock:
            g = self.committed.setdefault(group, {})
            for k, pos in offsets.items():
                kk = int(k) if str(k).isdigit() else k
                g[kk] = max(g.get(kk, -1), int(pos))
            self.wal.write(json.dumps({"c": group, "o": offsets}) + "\n")
            if self.fsync:
                self.wal.flush()
                os.fsync(self.wal.fileno())

    def committed_offsets(self, group, keys):
        with self.lock:
            g = self.committed.get(group, {})
            return {k: g.get(int(k) if str(k).isdigit() else k, -1)
                    for k in keys}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--data", required=True)
    ap.add_argument("--no-fsync", action="store_true",
                    help="ack sends before the WAL hits disk (a kill "
                         "loses the acked tail: lost-write bug)")
    ap.add_argument("--dup-sends", type=float, default=0.0,
                    help="probability a send is applied twice (duplicate "
                         "bug)")
    ap.add_argument("--marker", default="", help="argv tag for grepkill")
    opts = ap.parse_args(argv)
    store = LogStore(opts.data, fsync=not opts.no_fsync,
                     dup_p=opts.dup_sends, seed=f"{opts.node}-{os.getpid()}")

    class Handler(socketserver.BaseRequestHandler):
        def handle(self):
            while True:
                try:
                    msg = recv_frame(self.request)
                except (OSError, ValueError):
                    return
                if msg is None:
                    return
                try:
                    op = msg.get("op")
                    if op == "send":
                        off = store.send(msg["key"], msg["value"])
                        reply = {"ok": True, "offset": off}
                    elif op == "poll":
                        reply = {"ok": True,
                                 "records": store.poll(
                                     msg.get("positions") or {},
                                     int(msg.get("max", 8)))}
                    elif op == "end_offsets":
                        reply = {"ok": True,
                                 "ends": store.end_offsets(
                                     msg.get("keys") or [])}
                    elif op == "commit":
                        store.commit(msg.get("group", ""),
                                     msg.get("offsets") or {})
                        reply = {"ok": True}
                    elif op == "committed":
                        reply = {"ok": True,
                                 "offsets": store.committed_offsets(
                                     msg.get("group", ""),
                                     msg.get("keys") or [])}
                    elif op == "ping":
                        reply = {"ok": True, "node": opts.node}
                    else:
                        reply = {"ok": False, "error": f"bad op {op!r}",
                                 "definite": True}
                except Exception as e:  # noqa: BLE001
                    reply = {"ok": False, "error": repr(e),
                             "indeterminate": True}
                try:
                    send_frame(self.request, reply)
                except OSError:
                    return

    class TS(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    with TS(("127.0.0.1", opts.port), Handler) as srv:
        print(f"kafkalog {opts.node} serving on {opts.port} "
              f"(fsync={store.fsync}, dup={store.dup_p})", flush=True)
        srv.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
