"""Monotonic and sequential workloads shared by cockroachdb and tidb.

Parity:
- monotonic: cockroachdb/src/jepsen/cockroach/monotonic.clj (and
  tidb/src/tidb/monotonic.clj) — each ``add`` transaction reads the current
  maximum and inserts max+1; under serializability the committed values are
  exactly 0..n with no gaps or duplicates, and each process's own adds
  increase (monotonic.clj:110-139, check-monotonic 166).
- sequential: cockroachdb/src/jepsen/cockroach/sequential.clj (and
  tidb/src/tidb/sequential.clj) — a key is split over a chain of tables;
  writers fill the chain in order, readers scan it in reverse, so any read
  must look like [nil ... nil v ... v]: seeing a later write implies every
  earlier write is visible (sequential.clj:106-163, trailing-nil? 135).

Both are expressed in plain portable SQL over the sqlkit connection shape.
"""

from __future__ import annotations

import itertools
import random
from collections import Counter
from typing import Any, Dict, List

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History, INFO, OK, Op
from jepsen_tpu.workloads import sets

from suites.sqlkit import _SqlClient

# --------------------------------------------------------------------------
# Monotonic
# --------------------------------------------------------------------------


def monotonic_generator():
    return gen.mix([gen.repeat({"f": "add"}),
                    gen.stagger(1.0, gen.repeat({"f": "read"}))])


class MonotonicClient(_SqlClient):
    """add: txn { v = 1 + max(val); insert (v, process) }; read: all rows."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS mono "
                        "(val INT PRIMARY KEY, proc INT)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query("SELECT val, proc FROM mono")
                return op.with_(type=OK,
                                value=sorted((int(r[0]), int(r[1]))
                                             for r in rows))
            # add
            self.conn.query("BEGIN")
            try:
                rows = self.conn.query("SELECT MAX(val) FROM mono")
                cur = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else -1
                v = cur + 1
                self.conn.query(
                    f"INSERT INTO mono VALUES ({v}, {op.process})")
                self.conn.query("COMMIT")
                return op.with_(type=OK, value=v)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class MonotonicChecker(Checker):
    """Committed adds must form a contiguous, duplicate-free range, and each
    process's adds must increase in invocation order
    (monotonic.clj:166-264's duplicate/reorder analysis)."""

    def check(self, test, history: History, opts=None):
        adds: List[Op] = [op for op in history
                          if op.f == "add" and op.type == OK]
        # indeterminate adds may have committed: their would-be values
        # can't be recovered, so any value is excusable as a gap filler
        indeterminate = sum(1 for op in history
                            if op.f == "add" and op.type == INFO)
        vals = [op.value for op in adds if op.value is not None]
        counts = Counter(vals)
        dupes = sorted(v for v, c in counts.items() if c > 1)
        gaps = []
        if vals:
            expect = set(range(min(vals), max(vals) + 1))
            gaps = sorted(expect - set(vals))
            # each indeterminate add excuses one hole (interpreter
            # crash->info semantics: the op may have been applied)
            gaps = gaps[indeterminate:] if indeterminate else gaps
        # per-process monotonicity in completion order
        reorders = []
        by_proc: Dict[int, int] = {}
        for op in adds:
            if op.value is None:
                continue
            last = by_proc.get(op.process)
            if last is not None and op.value <= last:
                reorders.append({"process": op.process,
                                 "prev": last, "value": op.value})
            by_proc[op.process] = op.value
        # reads: value sets must also be gap/dupe-free prefixes
        bad_reads = []
        for op in history:
            if op.f == "read" and op.type == OK and op.value:
                rv = [v for v, _p in op.value]
                if len(set(rv)) != len(rv) or \
                        sorted(rv) != list(range(min(rv), max(rv) + 1)):
                    bad_reads.append(op.to_dict())
        if not adds:
            return {"valid": UNKNOWN, "error": "no adds completed"}
        return {"valid": not (dupes or gaps or reorders or bad_reads),
                "add-count": len(adds),
                "duplicates": dupes[:10], "gaps": gaps[:10],
                "reorders": reorders[:10], "bad-reads": bad_reads[:5]}


def monotonic_workload(conn_factory) -> Dict[str, Any]:
    return {"generator": monotonic_generator(),
            "checker": MonotonicChecker(),
            "client": MonotonicClient(conn_factory)}


# --------------------------------------------------------------------------
# Sequential
# --------------------------------------------------------------------------

N_TABLES = 5


def sequential_generator(keys: int = 32):
    counter = itertools.count()
    written: List[int] = []

    def one():
        if written and random.random() < 0.5:
            return {"f": "read", "value": random.choice(written)}
        k = next(counter) % keys
        written.append(k)
        return {"f": "write", "value": k}

    return gen.FnGen(one)


class SequentialClient(_SqlClient):
    """write k: insert k into seq0..seqN in order (separate txns, as in
    sequential.clj:75-104); read k: select from seqN..seq0 in reverse."""

    def setup(self, test):
        for i in range(N_TABLES):
            self.conn.query(f"CREATE TABLE IF NOT EXISTS seq{i} "
                            f"(k INT PRIMARY KEY)")

    def invoke(self, test, op: Op) -> Op:
        try:
            k = op.value
            if op.f == "write":
                for i in range(N_TABLES):
                    try:
                        self.conn.query(f"INSERT INTO seq{i} VALUES ({k})")
                    except Exception as e:  # noqa: BLE001
                        # a duplicate means this row is already present
                        # (sequential.clj tolerates re-inserts); anything
                        # else — including definitely-not-applied retryable
                        # conflicts — must abort the chain, or we'd leave a
                        # hole the checker reads as a violation
                        if "duplicate" not in str(e).lower():
                            raise
                return op.with_(type=OK)
            # read in reverse write order
            seen = []
            for i in reversed(range(N_TABLES)):
                rows = self.conn.query(f"SELECT k FROM seq{i} "
                                       f"WHERE k = {k}")
                seen.append(int(rows[0][0]) if rows else None)
            return op.with_(type=OK, value=(k, seen))
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class SequentialChecker(Checker):
    """A reverse-order read must be nils followed by values: a non-nil
    followed by a nil means a later write was visible while an earlier one
    was not (trailing-nil?, sequential.clj:135-163)."""

    def check(self, test, history: History, opts=None):
        bad = []
        n = 0
        for op in history:
            if op.f != "read" or op.type != OK or op.value is None:
                continue
            n += 1
            _k, seen = op.value
            saw_value = False
            for cell in seen:
                if cell is not None:
                    saw_value = True
                elif saw_value:
                    bad.append(op.to_dict())
                    break
        if n == 0:
            return {"valid": UNKNOWN, "error": "no reads completed"}
        return {"valid": not bad, "read-count": n, "bad-reads": bad[:10]}


def sequential_workload(conn_factory, keys: int = 32) -> Dict[str, Any]:
    return {"generator": sequential_generator(keys),
            "checker": SequentialChecker(),
            "client": SequentialClient(conn_factory)}


# --------------------------------------------------------------------------
# Dirty reads (galera/src/jepsen/galera/dirty_reads.clj; also used by the
# percona and crate suites)
# --------------------------------------------------------------------------

N_ROWS = 4


def dirty_reads_generator():
    counter = itertools.count(1)
    return gen.mix([gen.repeat({"f": "read"}),
                    gen.FnGen(lambda: {"f": "write",
                                       "value": next(counter)})])


class DirtyReadsClient(_SqlClient):
    """Writers set every row of the table to one unique value in a single
    transaction; readers scan the table.  A reader observing a *failed*
    transaction's value is a dirty read (dirty_reads.clj:1-6,54-66)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS dirty "
                        "(id INT PRIMARY KEY, x INT)")
        for i in range(N_ROWS):
            try:
                self.conn.query(f"INSERT INTO dirty VALUES ({i}, -1)")
            except Exception:  # noqa: BLE001 — another node inserted first
                pass

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query("SELECT id, x FROM dirty")
                return op.with_(type=OK,
                                value=[int(r[1]) for r in rows])
            x = op.value
            self.conn.query("BEGIN")
            try:
                for i in range(N_ROWS):
                    self.conn.query(
                        f"UPDATE dirty SET x = {x} WHERE id = {i}")
                self.conn.query("COMMIT")
                return op.with_(type=OK)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class DirtyReadsChecker(Checker):
    """Any read observing a value written only by a FAILED transaction is a
    dirty read (dirty_reads.clj:73-96)."""

    def check(self, test, history: History, opts=None):
        from jepsen_tpu.history import FAIL
        failed = {op.value for op in history
                  if op.f == "write" and op.type == FAIL
                  and op.value is not None}
        seen = set()
        n_reads = 0
        for op in history:
            if op.f == "read" and op.type == OK and op.value is not None:
                n_reads += 1
                seen.update(v for v in op.value if v != -1)
        dirty = sorted(seen & failed)
        if n_reads == 0:
            return {"valid": UNKNOWN, "error": "no reads completed"}
        return {"valid": not dirty, "read-count": n_reads,
                "dirty-values": dirty[:10]}


def dirty_reads_workload(conn_factory) -> Dict[str, Any]:
    return {"generator": dirty_reads_generator(),
            "checker": DirtyReadsChecker(),
            "client": DirtyReadsClient(conn_factory)}


# --------------------------------------------------------------------------
# Lost updates via read-modify-write set (crate/src/jepsen/crate/
# lost_updates.clj: set-add through an optimistic RMW on one row)
# --------------------------------------------------------------------------


class RmwSetClient(_SqlClient):
    """add v: transactionally read the elements row, append v, write back;
    read: parse the row.  Under weak isolation concurrent RMWs silently
    drop elements — the lost-updates anomaly (lost_updates.clj:56-80)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS append "
                        "(k INT PRIMARY KEY, vals TEXT)")

    def _read(self):
        rows = self.conn.query("SELECT vals FROM append WHERE k = 0")
        cur = (rows[0][0] or "") if rows else None
        return cur

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                cur = self._read() or ""
                return op.with_(
                    type=OK,
                    value=[int(x) for x in cur.split(",") if x])
            v = op.value
            self.conn.query("BEGIN")
            try:
                cur = self._read()
                if cur is None:
                    self.conn.query(
                        f"INSERT INTO append VALUES (0, '{v}')")
                else:
                    new = f"{cur},{v}" if cur else str(v)
                    self.conn.query(f"UPDATE append SET vals = '{new}' "
                                    f"WHERE k = 0")
                self.conn.query("COMMIT")
                return op.with_(type=OK)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


def lost_updates_workload(conn_factory) -> Dict[str, Any]:
    wl = sets.workload()
    return {**wl, "client": RmwSetClient(conn_factory)}


# --------------------------------------------------------------------------
# Comments (strict-serializability write precedence)
# --------------------------------------------------------------------------

COMMENT_TABLES = 5


def comments_generator(keys: int = 4, ops_per_key: int = 120,
                       threads_per_key: int = 2):
    """Blind inserts of globally-sequential ids mixed with read-alls,
    lifted over keys (comments.clj:148-167's independent shape)."""
    from jepsen_tpu import independent
    ids = itertools.count()

    def key_gen(k):
        def one():
            if random.random() < 0.5:
                return {"f": "write", "value": (k, next(ids))}
            return {"f": "read", "value": (k, None)}
        return gen.limit(ops_per_key, gen.FnGen(one))

    return independent.concurrent_generator(threads_per_key,
                                            list(range(keys)), key_gen)


class CommentsClient(_SqlClient):
    """Blind insert of (id, key) into one of COMMENT_TABLES tables chosen
    by id (the reference splits tables to land in different shard ranges,
    comments.clj:30-41); reads select the key's ids from EVERY table in
    one transaction."""

    def setup(self, test):
        for t in range(COMMENT_TABLES):
            self.conn.query(f"CREATE TABLE IF NOT EXISTS comment_{t} "
                            "(id INT PRIMARY KEY, k INT)")

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "write":
                self.conn.query(
                    f"INSERT INTO comment_{v % COMMENT_TABLES} "
                    f"VALUES ({v}, {k})")
                return op.with_(type=OK)
            # read: all tables, one txn
            self.conn.query("BEGIN")
            try:
                seen = []
                for t in range(COMMENT_TABLES):
                    rows = self.conn.query(
                        f"SELECT id FROM comment_{t} WHERE k = {k}")
                    seen.extend(int(r[0]) for r in rows)
                self.conn.query("COMMIT")
                return op.with_(type=OK, value=(k, sorted(seen)))
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class CommentsChecker(Checker):
    """T1 < T2 (w1 completed before w2 was invoked) but a read sees T2
    without T1: the strict-serializability violation comments.clj:89-140
    replays for.  Expected sets are per-write snapshots of the completed
    writes at invocation; a read containing w must contain w's whole
    expected set."""

    def check(self, test, history: History, opts=None):
        completed: set = set()
        expected: Dict[Any, frozenset] = {}
        for op in history:
            if op.f != "write":
                continue
            if op.type == "invoke":
                expected[op.value] = frozenset(completed)
            elif op.type == OK:
                completed.add(op.value)
        errors = []
        reads = 0
        for op in history:
            if op.f != "read" or op.type != OK or \
                    not isinstance(op.value, (list, tuple, set, frozenset)):
                continue
            reads += 1
            seen = set(op.value)
            want: set = set()
            for v in seen:
                want |= expected.get(v, frozenset())
            missing = want - seen
            if missing:
                errors.append({"missing": sorted(missing),
                               "expected-count": len(want),
                               "read": op.to_dict()})
        if reads == 0:
            return {"valid": UNKNOWN, "error": "no reads completed"}
        return {"valid": not errors, "reads": reads,
                "errors": errors[:8]}


def comments_workload(conn_factory, keys: int = 4,
                      ops_per_key: int = 120) -> Dict[str, Any]:
    from jepsen_tpu import independent
    return {"generator": comments_generator(keys, ops_per_key),
            "checker": independent.checker(CommentsChecker()),
            "client": CommentsClient(conn_factory)}


# --------------------------------------------------------------------------
# Counter (yugabyte/src/yugabyte/counter.clj: concurrent increments of one
# row, reads graded by the counter envelope — jepsen checker.clj:737)
# --------------------------------------------------------------------------


def counter_generator(max_delta: int = 5):
    def add():
        return {"f": "add", "value": random.randint(1, max_delta)}
    return gen.mix([gen.FnGen(add),
                    gen.stagger(1 / 10, gen.repeat({"f": "read"}))])


class SqlCounterClient(_SqlClient):
    """One counter row; add = relative UPDATE, read = SELECT.  The
    yugabyte reference drives a CQL counter column (ycql/counter.clj);
    the SQL shape is the same single-row relative update."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS counter "
                        "(id INT PRIMARY KEY, val INT)")
        try:
            self.conn.query("INSERT INTO counter VALUES (0, 0)")
        except Exception:  # noqa: BLE001 — another client may win the race
            # Only a duplicate-key race is benign: verify the row actually
            # exists so a genuinely failed seed insert propagates instead of
            # silently reading 0 for the whole run.
            if not self.conn.query("SELECT val FROM counter WHERE id = 0"):
                raise

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query(
                    "SELECT val FROM counter WHERE id = 0")
                val = int(rows[0][0]) if rows else 0
                return op.with_(type=OK, value=val)
            d = int(op.value)
            sign, mag = ("+", d) if d >= 0 else ("-", -d)
            self.conn.query(f"UPDATE counter SET val = val {sign} {mag} "
                            f"WHERE id = 0")
            return op.with_(type=OK if self.conn.rowcount else FAIL)
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


def counter_workload(conn_factory, max_delta: int = 5) -> Dict[str, Any]:
    from jepsen_tpu.checker import CounterChecker
    return {"generator": counter_generator(max_delta),
            "checker": CounterChecker(),
            "client": SqlCounterClient(conn_factory)}


# --------------------------------------------------------------------------
# Multi-key ACID (yugabyte/src/yugabyte/multi_key_acid.clj: transactional
# writes over a composite-key table, linearizable as a multi-register per
# independent group)
# --------------------------------------------------------------------------


def mka_generator(groups: int = 3, keys_per_group: int = 3,
                  values: int = 5, ops_per_group: int = 120,
                  threads_per_group: int = 2):
    from jepsen_tpu import independent

    def group_gen(_g):
        def read():
            ks = random.sample(range(keys_per_group),
                               random.randint(1, keys_per_group))
            return {"f": "read", "value": [[k, None] for k in sorted(ks)]}

        def write():
            ks = random.sample(range(keys_per_group),
                               random.randint(1, keys_per_group))
            return {"f": "write",
                    "value": [[k, random.randrange(values)]
                              for k in sorted(ks)]}
        return gen.limit(ops_per_group,
                         gen.mix([gen.FnGen(read), gen.FnGen(write)]))

    return independent.concurrent_generator(
        threads_per_group, list(range(groups)), group_gen)


class MkaClient(_SqlClient):
    """Writes upsert every (k, v) of the op inside ONE transaction; reads
    are a single whole-group SELECT (statement-atomic), filled into the
    requested key list (multi_key_acid.clj r/w shapes)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS mka "
                        "(grp INT, k INT, v INT, PRIMARY KEY (grp, k))")

    def invoke(self, test, op: Op) -> Op:
        g, pairs = op.value
        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT k, v FROM mka WHERE grp = {g}")
                have = {int(r[0]): int(r[1]) for r in rows}
                filled = [[k, have.get(k)] for k, _ in pairs]
                return op.with_(type=OK, value=(g, filled))
            self.conn.query("BEGIN")
            try:
                for k, v in pairs:
                    self.conn.query(f"UPDATE mka SET v = {v} "
                                    f"WHERE grp = {g} AND k = {k}")
                    if self.conn.rowcount == 0:
                        self.conn.query(
                            f"INSERT INTO mka VALUES ({g}, {k}, {v})")
                self.conn.query("COMMIT")
                return op.with_(type=OK)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


def mka_workload(conn_factory, groups: int = 3, keys_per_group: int = 3,
                 ops_per_group: int = 120,
                 algorithm: str = "competition") -> Dict[str, Any]:
    from jepsen_tpu import independent
    from jepsen_tpu.checker import Linearizable
    from jepsen_tpu.models import get_model
    # Device-tier multi-register (k int32 lanes); the competition facade
    # races it against both host solvers and falls back cleanly when a
    # history leaves the packed int32 domain.  Key counts past the packed
    # encoding's 31-bit budget get the host-tier model outright.
    from jepsen_tpu.models import MultiRegister
    try:
        model = get_model("multi-register", keys=keys_per_group, vbits=3)
    except ValueError:
        model = MultiRegister()
    return {"generator": mka_generator(groups, keys_per_group,
                                       ops_per_group=ops_per_group),
            "checker": independent.checker(Linearizable(model, algorithm)),
            "client": MkaClient(conn_factory)}
