"""mysql-cluster suite — MySQL NDB Cluster bank workload.

Parity: mysql-cluster/src/jepsen/mysql_cluster.clj — management node on
the first host, NDB data nodes on the rest, SQL (API) nodes everywhere.
"""

from suites.mysql_cluster.runner import WORKLOADS, all_tests, mysql_cluster_test  # noqa: F401
