"""MySQL NDB Cluster install/start.

Parity: mysql-cluster/src/jepsen/mysql_cluster.clj — ndb_mgmd on node 1,
ndbd data nodes, mysqld API nodes with ndbcluster enabled, config.ini
generated from the test's node list.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "8.0.35"
URL = (f"https://dev.mysql.com/get/Downloads/MySQL-Cluster-8.0/"
       f"mysql-cluster-{VERSION}-linux-glibc2.28-x86_64.tar.xz")
DIR = "/opt/mysql-cluster"
DATA = f"{DIR}/data"
SQL_PORT = 3306
MGM_PORT = 1186

MGMD_PID, MGMD_LOG = f"{DIR}/mgmd.pid", f"{DIR}/mgmd.log"
NDBD_PID, NDBD_LOG = f"{DIR}/ndbd.pid", f"{DIR}/ndbd.log"
MYSQLD_PID, MYSQLD_LOG = f"{DIR}/mysqld.pid", f"{DIR}/mysqld.log"


def mgm_node(test) -> str:
    return test["nodes"][0]


def data_nodes(test) -> List[str]:
    """ndbd runs on every node but the management node; a single-node test
    colocates one data node with the mgm daemon."""
    return test["nodes"][1:] or test["nodes"][:1]


def config_ini(test) -> str:
    dn = data_nodes(test)
    # NDB requires the data-node count to be a multiple of NoOfReplicas
    replicas = 2 if len(dn) % 2 == 0 else 1
    lines = ["[ndbd default]", f"NoOfReplicas={replicas}",
             "DataMemory=256M", "",
             "[ndb_mgmd]", f"HostName={mgm_node(test)}",
             f"DataDir={DATA}/mgmd", ""]
    for n in dn:
        lines += ["[ndbd]", f"HostName={n}", f"DataDir={DATA}/ndbd", ""]
    for n in test["nodes"]:
        lines += ["[mysqld]", f"HostName={n}", ""]
    return "\n".join(lines)


def my_cnf(test) -> str:
    return (f"[mysqld]\nndbcluster\n"
            f"ndb-connectstring={mgm_node(test)}\n"
            f"bind-address=0.0.0.0\nport={SQL_PORT}\n"
            f"datadir={DATA}/mysqld\n"
            f"[mysql_cluster]\nndb-connectstring={mgm_node(test)}\n")


class MysqlClusterDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        s.exec("bash", "-c",
               f"[ -x {DIR}/bin/ndbd ] || "
               f"cp -r {DIR}/mysql-cluster-*/* {DIR}/ 2>/dev/null || true")
        s.exec("mkdir", "-p", f"{DATA}/mgmd", f"{DATA}/ndbd",
               f"{DATA}/mysqld")
        cu.write_file(s, config_ini(test), f"{DIR}/config.ini")
        cu.write_file(s, my_cnf(test), f"{DIR}/my.cnf")
        self.start(test, node)
        cu.await_tcp_port(s, SQL_PORT, timeout_s=300)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        for pid in (MYSQLD_PID, NDBD_PID, MGMD_PID):
            cu.stop_daemon(s, pid)
        s.exec("rm", "-rf", DATA, MGMD_LOG, NDBD_LOG, MYSQLD_LOG)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        if node == mgm_node(test):
            cu.start_daemon(s, f"{DIR}/bin/ndb_mgmd",
                            "--nodaemon",
                            "-f", f"{DIR}/config.ini",
                            "--configdir", f"{DATA}/mgmd",
                            pidfile=MGMD_PID, logfile=MGMD_LOG)
            cu.await_tcp_port(s, MGM_PORT, timeout_s=60)
        if node in data_nodes(test):
            cu.start_daemon(s, f"{DIR}/bin/ndbd", "--nodaemon",
                            "-c", mgm_node(test),
                            pidfile=NDBD_PID, logfile=NDBD_LOG)
        s.exec("bash", "-c",
               f"[ -d {DATA}/mysqld/mysql ] || "
               f"{DIR}/bin/mysqld --defaults-file={DIR}/my.cnf "
               f"--initialize-insecure")
        cu.start_daemon(s, f"{DIR}/bin/mysqld",
                        f"--defaults-file={DIR}/my.cnf",
                        pidfile=MYSQLD_PID, logfile=MYSQLD_LOG)

    def kill(self, test, node):
        s = session(test, node).sudo()
        for pat in ("mysqld", "ndbd", "ndb_mgmd"):
            cu.grepkill(s, pat)
        for pid in (MYSQLD_PID, NDBD_PID, MGMD_PID):
            s.exec("rm", "-f", pid)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        s = session(test, node).sudo()
        for pat in ("mysqld", "ndbd"):
            cu.signal(s, pat, "STOP")

    def resume(self, test, node):
        s = session(test, node).sudo()
        for pat in ("mysqld", "ndbd"):
            cu.signal(s, pat, "CONT")

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [MGMD_LOG, NDBD_LOG, MYSQLD_LOG]
