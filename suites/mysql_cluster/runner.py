"""mysql-cluster suite CLI.

Parity: mysql-cluster/src/jepsen/mysql_cluster.clj — bank over NDB.

    python -m suites.mysql_cluster.runner test --node n1 ... --workload bank
"""

from __future__ import annotations

from jepsen_tpu.clients.mysql import MysqlClient

from suites import sqlsuite
from suites.mysql_cluster.db import SQL_PORT, MysqlClusterDB


def conn(node, test):
    return MysqlClient(node,
                       port=int(test.get("db_port", SQL_PORT)),
                       user=test.get("db_user", "root"),
                       password=test.get("db_password", ""),
                       database=test.get("db_name", "test")).connect()


WORKLOADS, mysql_cluster_test, all_tests, main = sqlsuite.make_suite(
    "mysql-cluster", MysqlClusterDB(), conn, default_workload="bank")


if __name__ == "__main__":
    import sys
    sys.exit(main())
