"""Elasticsearch suite CLI.

Parity: elasticsearch/src/jepsen/elasticsearch — set workload
(sets.clj) and the dirty-read workload + checker (dirty_read.clj:
106-156: dirty = reads never visible in any strong read; lost =
acknowledged writes missing from every strong read; nodes must agree).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, List

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, SetChecker
from jepsen_tpu.history import History, OK

from suites import common
from suites.elasticsearch.client import DirtyReadClient, SetClient
from suites.elasticsearch.db import ElasticsearchDB


class DirtyReadChecker(Checker):
    """dirty_read.clj:106-156's set algebra."""

    def check(self, test, history: History, opts=None):
        ok = [op for op in history if op.type == OK]
        writes = {op.value for op in ok if op.f == "write"}
        reads = {op.value for op in ok if op.f == "read"}
        strong = [set(op.value or []) for op in ok
                  if op.f == "strong-read"]
        if not strong:
            return {"valid": "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        dirty = sorted(reads - on_some)
        lost = sorted(writes - on_some)
        some_lost = sorted(writes - on_all)
        nodes_agree = on_all == on_some
        return {"valid": nodes_agree and not dirty and not lost,
                "nodes-agree": nodes_agree,
                "read-count": len(reads),
                "on-all-count": len(on_all),
                "on-some-count": len(on_some),
                "not-on-all": sorted(on_some - on_all)[:32],
                "dirty-count": len(dirty), "dirty": dirty[:32],
                "lost-count": len(lost), "lost": lost[:32],
                "some-lost-count": len(some_lost)}


def set_workload(opts) -> Dict[str, Any]:
    counter = itertools.count()
    return {"client": SetClient(),
            "generator": gen.stagger(
                1 / 50, gen.FnGen(lambda: {"f": "add",
                                           "value": next(counter)})),
            "final_generator": gen.once({"f": "read"}),
            "checker": SetChecker()}


def dirty_read_workload(opts) -> Dict[str, Any]:
    """Writers stream increasing ids; readers probe recent writes; every
    worker ends with a strong read (dirty_read.clj:158-189)."""
    counter = itertools.count()
    in_flight: List[int] = []

    def one():
        if in_flight and random.random() < 0.5:
            return {"f": "read", "value": random.choice(in_flight[-10:])}
        v = next(counter)
        in_flight.append(v)
        return {"f": "write", "value": v}

    return {"client": DirtyReadClient(),
            "generator": gen.stagger(1 / 50, gen.FnGen(one)),
            "final_generator": gen.each_thread(gen.lift(
                [gen.once({"f": "refresh"}),
                 gen.once({"f": "strong-read"})])),
            "checker": DirtyReadChecker()}


WORKLOADS = {"set": set_workload, "dirty-read": dirty_read_workload}


def elasticsearch_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="elasticsearch",
                             db=ElasticsearchDB(), workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, elasticsearch_test, WORKLOADS)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(elasticsearch_test, WORKLOADS,
                         prog="jepsen-tpu-elasticsearch"))
