"""Elasticsearch REST clients.

Parity: elasticsearch/src/jepsen/elasticsearch/sets.clj (create docs into
an index; final search-all read) and dirty_read.clj:30-104 (write a doc
with a known id, read it back by id, strong-read = refresh + search-all).
"""

from __future__ import annotations

import socket
import urllib.error
from typing import List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.http import HttpClient, HttpError
from jepsen_tpu.history import FAIL, INFO, OK, Op

HTTP_PORT = 9200
INDEX = "jepsen"
NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


def connect(test, node) -> HttpClient:
    return HttpClient(node, int(test.get("db_port", HTTP_PORT)),
                      timeout=10.0)


def search_all_ids(conn: HttpClient, index: str) -> List[int]:
    """Search every document id, paging with search_after so reads past
    the 10k result window can't silently truncate (the reference's
    full-index search, elasticsearch/core.clj:125-151)."""
    out: List[int] = []
    after = None
    while True:
        body = {"size": 1000, "query": {"match_all": {}},
                "_source": ["id"], "sort": [{"_id": "asc"}]}
        if after is not None:
            body["search_after"] = after
        _, r = conn.post(f"/{index}/_search", body)
        hits = (r.get("hits") or {}).get("hits") or []
        if not hits:
            break
        out.extend(int(h["_source"]["id"]) for h in hits)
        after = hits[-1].get("sort")
        if after is None:  # server without sort support: one page only
            break
    return sorted(out)


class SetClient(jclient.Client):
    """Insert docs as set elements; read = refresh + search-all
    (sets.clj:29-100)."""

    def __init__(self, conn: Optional[HttpClient] = None):
        self.conn = conn

    def open(self, test, node):
        c = connect(test, node)
        try:
            c.put(f"/{INDEX}")
        except (HttpError, *NET_ERRORS):
            pass  # already exists / node down; setup retried by writes
        return SetClient(c)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.post(f"/{INDEX}/_doc/{op.value}",
                               {"id": op.value})
                return op.with_(type=OK)
            if op.f == "read":
                self.conn.post(f"/{INDEX}/_refresh")
                return op.with_(type=OK,
                                value=search_all_ids(self.conn, INDEX))
            raise ValueError(op.f)
        except (HttpError, *NET_ERRORS) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e)[:200])
            return op.with_(type=INFO, error=str(e)[:200])


class DirtyReadClient(jclient.Client):
    """write / read-by-id / strong-read (dirty_read.clj:52-104)."""

    def __init__(self, conn: Optional[HttpClient] = None):
        self.conn = conn

    def open(self, test, node):
        c = connect(test, node)
        try:
            c.put(f"/{INDEX}")
        except (HttpError, *NET_ERRORS):
            pass
        return DirtyReadClient(c)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                self.conn.post(f"/{INDEX}/_doc/{op.value}",
                               {"id": op.value})
                return op.with_(type=OK)
            if op.f == "read":
                try:
                    _, r = self.conn.get(f"/{INDEX}/_doc/{op.value}")
                except HttpError as e:
                    if e.status == 404:
                        return op.with_(type=FAIL)
                    raise
                return op.with_(type=OK if r.get("found") else FAIL)
            if op.f == "refresh":
                self.conn.post(f"/{INDEX}/_refresh")
                return op.with_(type=OK)
            if op.f == "strong-read":
                self.conn.post(f"/{INDEX}/_refresh")
                return op.with_(type=OK,
                                value=search_all_ids(self.conn, INDEX))
            raise ValueError(op.f)
        except (HttpError, *NET_ERRORS) as e:
            if op.f in ("read", "strong-read"):
                return op.with_(type=FAIL, error=str(e)[:200])
            return op.with_(type=INFO, error=str(e)[:200])
