"""Elasticsearch suite (reference: elasticsearch/ — set and dirty-read
workloads probing lost updates and uncommitted visibility)."""
