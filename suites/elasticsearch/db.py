"""Elasticsearch install/config.

Parity: elasticsearch/src/jepsen/elasticsearch/core.clj:212-296 — deb
install, elasticsearch.yml with unicast discovery over the test's nodes
and a cluster name, service start, teardown nukes the data dir.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "7.17.9"
URL = (f"https://artifacts.elastic.co/downloads/elasticsearch/"
       f"elasticsearch-{VERSION}-amd64.deb")
CONF = "/etc/elasticsearch/elasticsearch.yml"
LOGFILE = "/var/log/elasticsearch/jepsen.log"
DATA = "/var/lib/elasticsearch"
HTTP_PORT = 9200


def config(test, node) -> str:
    hosts = ", ".join(f'"{n}"' for n in test["nodes"])
    return (f"cluster.name: jepsen\n"
            f"node.name: {node}\n"
            f"network.host: 0.0.0.0\n"
            f"http.port: {HTTP_PORT}\n"
            f"discovery.seed_hosts: [{hosts}]\n"
            f"cluster.initial_master_nodes: [{hosts}]\n")


class ElasticsearchDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "dpkg-query -l elasticsearch >/dev/null 2>&1 || "
               f"{{ wget -nv -O /tmp/es.deb {URL} && "
               "dpkg -i --force-confnew /tmp/es.deb; }")
        cu.write_file(s, config(test, node), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, HTTP_PORT, timeout_s=240)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "elasticsearch")
        s.exec("sh", "-c", f"rm -rf {DATA}/* || true")

    def start(self, test, node):
        session(test, node).sudo().exec("service", "elasticsearch",
                                        "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "elasticsearch")

    def pause(self, test, node):
        cu.grepkill(session(test, node).sudo(), "elasticsearch",
                    signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node).sudo(), "elasticsearch",
                    signal="CONT")

    def log_files(self, test, node) -> List[str]:
        return ["/var/log/elasticsearch/jepsen.log"]
