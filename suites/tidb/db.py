"""TiDB cluster install/start: pd-server quorum, tikv-server, tidb-server.

Parity: tidb/src/tidb/db.clj — community tarball, per-component
pid/log/data files (db.clj:23-41), PD initial-cluster bootstrapping, TiKV
pointed at the PD quorum, TiDB on top, optional faketime LD_PRELOAD wrapper
for clock-rate skew (db.clj:12, core.clj:344-346).
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu import faketime
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "v7.5.0"
URL = (f"https://download.pingcap.org/"
       f"tidb-community-server-{VERSION}-linux-amd64.tar.gz")
DIR = "/opt/tidb"
BIN = f"{DIR}/bin"
PD_PORT, PD_PEER_PORT = 2379, 2380
KV_PORT = 20160
SQL_PORT = 4000

PD_PID, PD_LOG = f"{DIR}/pd.pid", f"{DIR}/pd.log"
KV_PID, KV_LOG = f"{DIR}/kv.pid", f"{DIR}/kv.log"
DB_PID, DB_LOG = f"{DIR}/db.pid", f"{DIR}/db.log"


def pd_name(node: str) -> str:
    return f"pd-{node.replace('.', '-')}"


def initial_cluster(test) -> str:
    return ",".join(f"{pd_name(n)}=http://{n}:{PD_PEER_PORT}"
                    for n in test["nodes"])


def pd_endpoints(test) -> str:
    return ",".join(f"{n}:{PD_PORT}" for n in test["nodes"])


class TiDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        s.exec("bash", "-c",
               f"[ -x {BIN}/pd-server ] || "
               f"cp -r {DIR}/tidb-community-server-*/* {DIR}/ "
               f"2>/dev/null || true")
        if test.get("faketime"):
            # wrap each server in a clock-rate-skewing LD_PRELOAD script
            # (tidb/db.clj:12's faketime wrappers; --faketime MAX_RATIO at
            # core.clj:344-346)
            import random as _random
            faketime.install(test, node)
            ratio = float(test["faketime"])
            for b in ("pd-server", "tikv-server", "tidb-server"):
                real = f"{BIN}/{b}"
                s.exec("bash", "-c",
                       f"[ -f {real}.real ] || mv {real} {real}.real")
                faketime.wrap_binary(
                    test, node, f"{real}.real", real,
                    offset_s=0.0,
                    rate=_random.uniform(1.0 / ratio, ratio))
        self.start(test, node)
        cu.await_tcp_port(s, SQL_PORT, timeout_s=180)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        for pid in (DB_PID, KV_PID, PD_PID):
            cu.stop_daemon(s, pid)
        s.exec("rm", "-rf", f"{DIR}/data", PD_LOG, KV_LOG, DB_LOG)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(
            s, f"{BIN}/pd-server",
            "--name", pd_name(node),
            "--data-dir", f"{DIR}/data/pd",
            "--client-urls", f"http://0.0.0.0:{PD_PORT}",
            "--advertise-client-urls", f"http://{node}:{PD_PORT}",
            "--peer-urls", f"http://0.0.0.0:{PD_PEER_PORT}",
            "--advertise-peer-urls", f"http://{node}:{PD_PEER_PORT}",
            "--initial-cluster", initial_cluster(test),
            pidfile=PD_PID, logfile=PD_LOG)
        cu.await_tcp_port(s, PD_PORT, timeout_s=120)
        cu.start_daemon(
            s, f"{BIN}/tikv-server",
            "--pd", pd_endpoints(test),
            "--addr", f"0.0.0.0:{KV_PORT}",
            "--advertise-addr", f"{node}:{KV_PORT}",
            "--data-dir", f"{DIR}/data/kv",
            pidfile=KV_PID, logfile=KV_LOG)
        cu.start_daemon(
            s, f"{BIN}/tidb-server",
            "--store", "tikv",
            "--path", pd_endpoints(test),
            "-P", str(SQL_PORT),
            pidfile=DB_PID, logfile=DB_LOG)

    def kill(self, test, node):
        s = session(test, node).sudo()
        for pat in ("tidb-server", "tikv-server", "pd-server"):
            cu.grepkill(s, pat)
        for pid in (DB_PID, KV_PID, PD_PID):
            s.exec("rm", "-f", pid)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        s = session(test, node).sudo()
        for pat in ("tidb-server", "tikv-server", "pd-server"):
            cu.signal(s, pat, "STOP")

    def resume(self, test, node):
        s = session(test, node).sudo()
        for pat in ("tidb-server", "tikv-server", "pd-server"):
            cu.signal(s, pat, "CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        import json
        import urllib.request
        for node in test["nodes"]:
            try:
                with urllib.request.urlopen(
                        f"http://{node}:{PD_PORT}/pd/api/v1/leader",
                        timeout=2) as r:
                    leader = json.load(r)
                name = leader.get("name", "")
                for n in test["nodes"]:
                    if pd_name(n) == name:
                        return [n]
            except Exception:  # noqa: BLE001
                continue
        return []

    def setup_primary(self, test, node):
        pass

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [PD_LOG, KV_LOG, DB_LOG]
