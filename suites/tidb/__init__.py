"""tidb suite — the reference's fullest modern suite shape.

Parity: tidb/src/tidb/{core,db,sql,nemesis}.clj + per-workload files
(bank, register, sets, long_fork, monotonic, sequential, txn): PD/TiKV/
TiDB three-tier cluster, MySQL-protocol clients, workload-options sweep
matrices (core.clj:112-174), faketime clock-rate skew support
(core.clj:344, db.clj:12).
"""

from suites.tidb.runner import WORKLOADS, all_tests, tidb_test  # noqa: F401
