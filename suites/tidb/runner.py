"""tidb suite CLI — full workload registry + sweep matrices + faketime.

Parity: tidb/src/tidb/core.clj — the workloads table (core.clj:32-45:
bank, register, sets, append/txn, long-fork, monotonic, sequential),
``--faketime MAX_RATIO`` clock-rate skew (core.clj:344-346), and the
all-combinations sweep (core.clj:112-174 all-workload-options) exposed as
``all_tests`` for ``test-all``.

    python -m suites.tidb.runner test --node n1 ... \
        --workload register --nemesis kill --faketime 1.05
"""

from __future__ import annotations

from jepsen_tpu.clients.mysql import MysqlClient

from suites import sqlextra, sqlsuite
from suites.tidb.db import SQL_PORT, TiDB


def conn(node, test):
    return MysqlClient(node,
                       port=int(test.get("db_port", SQL_PORT)),
                       user=test.get("db_user", "root"),
                       password=test.get("db_password", ""),
                       database=test.get("db_name", "test")).connect()


EXTRA = {
    "monotonic": lambda opts: sqlextra.monotonic_workload(conn),
    "sequential": lambda opts: sqlextra.sequential_workload(
        conn, keys=int(opts.get("keys", 32))),
}

WORKLOADS, tidb_test, all_tests, _main = sqlsuite.make_suite(
    "tidb", TiDB(), conn, extra_workloads=EXTRA,
    default_workload="register")


def main() -> int:
    from suites import common

    def extra_opts(parser):
        sqlsuite._sql_opts(parser)
        parser.add_argument(
            "--faketime", type=float, default=None,
            help="skew server clock rates up to this ratio via libfaketime")

    return common.main(tidb_test, WORKLOADS, prog="jepsen-tpu-tidb",
                       extra_opts=extra_opts,
                       default_workload="register")


if __name__ == "__main__":
    import sys
    sys.exit(main())
