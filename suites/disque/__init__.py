"""Disque suite — distributed queue over the RESP-based disque protocol
(disque/src/jepsen/disque.clj): enqueue/dequeue/drain, total-queue
checking."""
