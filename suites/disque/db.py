"""Disque install/start (disque/src/jepsen/disque.clj's db: build from the
pinned release, start on port 7711, CLUSTER MEET the peers)."""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

URL = "https://github.com/antirez/disque/archive/1.0-rc1.tar.gz"
DIR = "/opt/disque"
PIDFILE = "/var/run/disque.pid"
LOGFILE = "/var/log/disque.log"
PORT = 7711


class DisqueDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        if not cu.exists(s, f"{DIR}/src/disque-server"):
            cu.install_archive(s, URL, DIR)
            s.exec("sh", "-c", f"cd {DIR} && make -j2")
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=60)
        # join the cluster through node 0
        first = test["nodes"][0]
        if node != first:
            s.exec(f"{DIR}/src/disque", "-p", str(PORT),
                   "cluster", "meet", first, str(PORT))

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.stop_daemon(s, PIDFILE)
        s.exec("sh", "-c", f"rm -rf {DIR}/*.rdb {LOGFILE} || true")

    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(s, f"{DIR}/src/disque-server",
                        "--port", str(PORT),
                        "--appendonly", "yes",
                        pidfile=PIDFILE, logfile=LOGFILE)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "disque-server")
        s.exec("rm", "-f", PIDFILE)

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "disque-server", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "disque-server", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
