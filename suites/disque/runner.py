"""Disque suite CLI (disque/src/jepsen/disque.clj:280-300: enqueue/dequeue
mix, final drain, total-queue checker)."""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu.workloads import queue as queue_wl

from suites import common
from suites.disque.client import QueueClient
from suites.disque.db import DisqueDB


def queue_workload(opts) -> Dict[str, Any]:
    wl = queue_wl.workload()
    return {**wl, "client": QueueClient()}


WORKLOADS = {"queue": queue_workload}


def disque_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="disque", db=DisqueDB(),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, disque_test, WORKLOADS)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(disque_test, WORKLOADS, prog="jepsen-tpu-disque"))
