"""Disque queue client: ADDJOB / GETJOB / ACKJOB over RESP.

Parity: disque/src/jepsen/disque.clj:140-260 — enqueue is ADDJOB with a
replication timeout, dequeue GETJOBs then ACKJOBs, drain loops dequeue
until exhaustion (returning everything pulled; the checker counts them as
dequeues).
"""

from __future__ import annotations

import socket
import time
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.resp import RespClient, RespError
from jepsen_tpu.history import FAIL, INFO, OK, Op

PORT = 7711
QUEUE = "jepsen"
TIMEOUT_MS = 100
DRAIN_BUDGET_S = 10.0


class QueueClient(jclient.Client):
    def __init__(self, conn: Optional[RespClient] = None):
        self.conn = conn

    def open(self, test, node):
        return QueueClient(RespClient(
            node, test.get("db_port", PORT), timeout=5.0))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _dequeue_one(self):
        jobs = self.conn.call("GETJOB", "TIMEOUT", TIMEOUT_MS,
                              "FROM", QUEUE)
        if not jobs:
            return None
        _q, jid, body = jobs[0]
        self.conn.call("ACKJOB", jid)
        return int(body)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                self.conn.call("ADDJOB", QUEUE, str(op.value), TIMEOUT_MS)
                return op.with_(type=OK)
            if op.f == "dequeue":
                v = self._dequeue_one()
                if v is None:
                    return op.with_(type=FAIL)
                return op.with_(type=OK, value=v)
            if op.f == "drain":
                out = []
                deadline = time.monotonic() + DRAIN_BUDGET_S
                while time.monotonic() < deadline:
                    v = self._dequeue_one()
                    if v is None:
                        return op.with_(type=OK, value=out)
                    out.append(v)
                return op.with_(type=INFO, value=out, error="drain-timeout")
            raise ValueError(op.f)
        except (RespError, ConnectionError, OSError, socket.timeout,
                TimeoutError) as e:
            self.conn.close()
            if op.f == "dequeue":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
