"""LogCabin suite CLI.

Parity: logcabin/src/jepsen/logcabin.clj's cas-register test: a single
CAS register at /jepsen checked for linearizability, under partitions
(the reference's default nemesis battery).
"""

from __future__ import annotations

import random
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import get_model

from suites import common
from suites.logcabin.client import CasClient
from suites.logcabin.db import LogCabinDB


def register_workload(opts) -> Dict[str, Any]:
    """One global register (the reference uses a single /jepsen path, not
    an independent keyspace)."""
    n = int(opts.get("ops", 300))
    g = gen.limit(n, gen.mix([
        gen.FnGen(lambda: {"f": "read"}),
        gen.FnGen(lambda: {"f": "write", "value": random.randrange(5)}),
        gen.FnGen(lambda: {"f": "cas",
                           "value": [random.randrange(5),
                                     random.randrange(5)]})]))
    return {"client": CasClient(),
            "generator": gen.stagger(1 / 10, g),
            "checker": linearizable(get_model("cas-register"),
                                    opts.get("algorithm")),
            "model": get_model("cas-register")}


WORKLOADS = {"cas-register": register_workload}


def logcabin_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="logcabin", db=LogCabinDB(),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, logcabin_test, WORKLOADS)


def _extra(parser):
    parser.add_argument("--ops", type=int, default=300)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(logcabin_test, WORKLOADS,
                         prog="jepsen-tpu-logcabin", extra_opts=_extra))
