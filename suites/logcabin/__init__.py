"""LogCabin (Raft) suite (reference: logcabin/ — CAS register driven
through the node-side TreeOps CLI)."""
