"""LogCabin build/bootstrap/reconfigure.

Parity: logcabin/src/jepsen/logcabin.clj:23-150 — git clone + scons build,
per-node config (serverId from the node's index, listenAddresses),
bootstrap on node 1, start everywhere, then the Reconfigure tool on node 1
grows the cluster to all nodes; stop is grepkill + pidfile removal.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

REPO = "https://github.com/logcabin/logcabin.git"
PORT = 5254
CONF = "/root/logcabin.conf"
LOGFILE = "/root/logcabin.log"
PIDFILE = "/root/logcabin.pid"
STORE = "/root/storage"
BIN = "/root/LogCabin"
RECONFIG = "/root/Reconfigure"
TREEOPS = "/root/TreeOps"


def server_id(test, node) -> int:
    return test["nodes"].index(node) + 1


def server_addr(node) -> str:
    return f"{node}:{PORT}"


def cluster_addrs(test) -> str:
    return ",".join(server_addr(n) for n in test["nodes"])


class LogCabinDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        if not cu.exists(s, BIN):
            s.exec("apt-get", "install", "-y", "git", "g++", "scons",
                   "protobuf-compiler", "libprotobuf-dev",
                   "libcrypto++-dev")
            s.exec("sh", "-c",
                   f"[ -d /logcabin ] || git clone --depth 1 {REPO} "
                   f"/logcabin")
            s.exec("sh", "-c",
                   "cd /logcabin && git submodule update --init && scons")
            s.exec("sh", "-c",
                   "cp -f /logcabin/build/LogCabin "
                   "/logcabin/build/Examples/Reconfigure "
                   "/logcabin/build/Examples/TreeOps /root/")
        cu.write_file(s,
                      f"serverId = {server_id(test, node)}\n"
                      f"listenAddresses = {server_addr(node)}\n",
                      CONF)
        s.exec("rm", "-rf", LOGFILE)
        if node == test["nodes"][0]:
            # bootstrap the initial single-server cluster (logcabin.clj:79)
            s.exec("sh", "-c",
                   f"cd /root && {BIN} -c {CONF} -l {LOGFILE} --bootstrap")
        self.start(test, node)

    def setup_primary(self, test, node):
        """Grow the bootstrapped single-server cluster to every node —
        runs after all per-node setups complete (logcabin.clj:135-140's
        post-synchronize reconfigure)."""
        s = session(test, node).sudo()
        addrs = " ".join(server_addr(n) for n in test["nodes"])
        s.exec("sh", "-c",
               f"cd /root && {RECONFIG} -c {cluster_addrs(test)} "
               f"set {addrs}")

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "LogCabin")
        s.exec("rm", "-rf", PIDFILE, STORE)

    def start(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               f"cd /root && {BIN} -c {CONF} -d -l {LOGFILE} -p {PIDFILE}")

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "LogCabin")
        s.exec("rm", "-f", PIDFILE)

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "LogCabin", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "LogCabin", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
