"""LogCabin CAS-register client: drives the node-side TreeOps CLI over the
control plane.

Parity: logcabin/src/jepsen/logcabin.clj:152-246 — reads/writes/CAS on a
tree path via `TreeOps read|write` with JSON-encoded values; CAS is a
conditioned write (`-p path:value`), and a failed condition surfaces as
the documented exception message, which maps to :fail.  Timeouts map to
:fail for reads and :info for mutations (a timed-out write or CAS may
still have been applied).
"""

from __future__ import annotations

import json
import re
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.control import session
from jepsen_tpu.control.core import RemoteCommandFailed
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites.logcabin.db import TREEOPS, cluster_addrs

OP_TIMEOUT_S = 3
KEY = "/jepsen"

CAS_FAIL_RE = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Path '.*' has value "
    r"'.*', not '.*' as required")
TIMEOUT_RE = re.compile(
    r"Exiting due to LogCabin::Client::Exception: Client-specified "
    r"timeout elapsed")


class CasClient(jclient.Client):
    def __init__(self, node: Optional[str] = None):
        self.node = node

    def open(self, test, node):
        return CasClient(node)

    def setup(self, test):
        try:
            self._write(test, json.dumps(None))
        except RemoteCommandFailed:
            pass

    def _session(self, test):
        return session(test, self.node).sudo()

    def _treeops(self, test) -> str:
        return test.get("treeops_bin", TREEOPS)

    def _read(self, test) -> str:
        return self._session(test).exec(
            "sh", "-c",
            f"{self._treeops(test)} -c {cluster_addrs(test)} -q "
            f"-t {OP_TIMEOUT_S} read {KEY}")

    def _write(self, test, value: str, cond: Optional[str] = None) -> None:
        p = f"-p '{KEY}:{cond}' " if cond is not None else ""
        self._session(test).exec(
            "sh", "-c",
            f"echo -n '{value}' | {self._treeops(test)} "
            f"-c {cluster_addrs(test)} -q {p}-t {OP_TIMEOUT_S} "
            f"write {KEY}")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                raw = self._read(test).strip()
                return op.with_(type=OK,
                                value=json.loads(raw) if raw else None)
            if op.f == "write":
                self._write(test, json.dumps(op.value))
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = op.value
                try:
                    self._write(test, json.dumps(new),
                                cond=json.dumps(old))
                except RemoteCommandFailed as e:
                    msg = (getattr(e, "result", None) and
                           e.result.err or str(e)).strip()
                    if CAS_FAIL_RE.search(msg):
                        return op.with_(type=FAIL, error="precondition")
                    raise
                return op.with_(type=OK)
            raise ValueError(op.f)
        except RemoteCommandFailed as e:
            msg = (getattr(e, "result", None) and e.result.err
                   or str(e)).strip()
            if TIMEOUT_RE.search(msg):
                return op.with_(type=FAIL if op.f == "read" else INFO,
                                error="timeout")
            if op.f == "read":
                return op.with_(type=FAIL, error=msg[:200])
            return op.with_(type=INFO, error=msg[:200])
