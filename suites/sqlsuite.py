"""SQL suite factory: one registry of SQL-backed workloads, many suites.

The reference's SQL-family suites (postgres-rds, stolon, cockroachdb, crate,
yugabyte YSQL, tidb, galera, percona, mysql-cluster) all assemble the same
workloads — bank (cockroachdb/src/jepsen/cockroach/bank.clj), register
(cockroach/register.clj), sets (cockroach/sets.clj), Elle list-append
(stolon/src/jepsen/stolon/append.clj), rw-register / G2 / long-fork
(cockroach/{comments,adya}.clj, jepsen/src/jepsen/tests/long_fork.clj) —
over a jdbc connection with per-dialect error classification.  Here the
workloads are factored once over any ``query(sql)`` connection
(suites/sqlkit.py); a suite supplies its conn factory + DB + OS.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from jepsen_tpu.workloads import adya, bank, cycle, linearizable_register
from jepsen_tpu.workloads import long_fork as lf
from jepsen_tpu.workloads import sets

from suites import common, sqlkit


def make_workloads(conn_factory: Callable) -> Dict[str, Callable]:
    """name -> (opts -> workload dict) over one SQL connection factory."""

    def bank_wl(opts):
        wl = bank.workload(total=int(opts.get("total_amount", 100)))
        return {**wl, "client": sqlkit.BankClient(conn_factory)}

    def register_wl(opts):
        wl = linearizable_register.workload(
            keys=range(int(opts.get("keys", 8))),
            ops_per_key=int(opts.get("ops_per_key", 200)),
            threads_per_key=int(opts.get("threads_per_key", 2)))
        return {**wl, "client": sqlkit.RegisterClient(conn_factory)}

    def set_wl(opts):
        wl = sets.workload()
        return {**wl, "client": sqlkit.SetClient(conn_factory)}

    def append_wl(opts):
        wl = cycle.append_workload(keys=int(opts.get("keys", 8)))
        return {**wl, "client": sqlkit.AppendClient(conn_factory)}

    def wr_wl(opts):
        wl = cycle.wr_workload(keys=int(opts.get("keys", 8)))
        return {**wl, "client": sqlkit.TxnClient(conn_factory)}

    def long_fork_wl(opts):
        wl = lf.workload()
        return {**wl, "client": sqlkit.TxnClient(conn_factory)}

    def g2_wl(opts):
        wl = adya.g2_workload()
        return {**wl, "client": sqlkit.TxnClient(conn_factory)}

    def counter_wl(opts):
        from suites import sqlextra
        return sqlextra.counter_workload(
            conn_factory, max_delta=int(opts.get("max_delta", 5)))

    def mka_wl(opts):
        from suites import sqlextra
        return sqlextra.mka_workload(
            conn_factory, groups=int(opts.get("groups", 3)),
            keys_per_group=int(opts.get("keys_per_group", 3)),
            ops_per_group=int(opts.get("ops_per_group", 120)))

    return {"bank": bank_wl, "register": register_wl, "set": set_wl,
            "append": append_wl, "wr": wr_wl, "long-fork": long_fork_wl,
            "g2": g2_wl, "counter": counter_wl, "multi-key-acid": mka_wl}


def make_suite(suite: str, db, conn_factory: Callable, os=None,
               nemeses: Optional[Dict[str, Callable]] = None,
               extra_workloads: Optional[Dict[str, Callable]] = None,
               default_workload: str = "register"):
    """Returns (WORKLOADS, test_fn, all_tests, main)."""
    workloads = make_workloads(conn_factory)
    if extra_workloads:
        workloads.update(extra_workloads)

    def test_fn(opts: Dict[str, Any]) -> Dict[str, Any]:
        opts = {**opts}
        opts.setdefault("workload", default_workload)
        t = common.build_test(opts, suite=suite, db=db,
                              workloads=workloads, nemeses=nemeses, os=os)
        # BankClient.setup reads the account/total config from the test map
        if opts.get("workload") == "bank":
            t["bank"] = {"accounts": list(range(8)),
                         "total_amount": int(opts.get("total_amount", 100))}
        return t

    def all_tests(opts: Dict[str, Any]):
        return common.sweep(opts, test_fn, workloads, nemeses)

    def main() -> int:
        return common.main(test_fn, workloads, nemeses,
                           prog=f"jepsen-tpu-{suite}",
                           extra_opts=_sql_opts,
                           default_workload=default_workload)

    return workloads, test_fn, all_tests, main


def _sql_opts(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=200)
    parser.add_argument("--threads-per-key", type=int, default=2)
    parser.add_argument("--total-amount", type=int, default=100)
