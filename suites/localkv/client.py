"""localkv wire client: real TCP, length-prefixed JSON frames.

Error mapping follows the reference's client discipline (e.g.
zookeeper.clj:91-104, and every suite client here): failed reads are safe
to report FAIL (a read that didn't happen constrains nothing), mutations
whose fate is unknown become INFO, and replies the server marks
``definite`` may FAIL.
"""

from __future__ import annotations

import socket
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites.localkv.server import recv_frame, send_frame


class ConnectFailed(Exception):
    """Connection could not even be established: the request was never
    sent, so the op definitely did not happen (definite FAIL for any op —
    without this distinction every mutation against a killed node becomes a
    forever-pending indeterminate ghost and the configuration space of the
    linearizability search doubles per attempt)."""


class Conn:
    def __init__(self, port: int, timeout: float = 2.0):
        self.port = port
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None

    def call(self, msg):
        if self.sock is None:
            try:
                self.sock = socket.create_connection(
                    ("127.0.0.1", self.port), timeout=self.timeout)
            except OSError as e:
                raise ConnectFailed(str(e)) from e
        try:
            send_frame(self.sock, msg)
            reply = recv_frame(self.sock)
        except OSError:
            self.close()
            raise
        if reply is None:
            self.close()
            raise ConnectionError("server closed connection")
        return reply

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class RegisterClient(jclient.Client):
    """Per-key register ops (read/write/cas) against the node's server."""

    def __init__(self, conn: Optional[Conn] = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(Conn(test["localkv_ports"][node]))

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        key = f"r{k}"
        try:
            if op.f == "read":
                reply = self.conn.call({"op": "read", "key": key})
                if reply.get("ok"):
                    return op.with_(type=OK, value=(k, reply.get("value")))
                return op.with_(type=FAIL, error=reply.get("error"))
            if op.f == "write":
                reply = self.conn.call({"op": "write", "key": key,
                                        "value": v})
            else:  # cas
                old, new = v
                reply = self.conn.call({"op": "cas", "key": key,
                                        "old": old, "new": new})
            if reply.get("ok"):
                return op.with_(type=OK)
            if reply.get("definite"):
                return op.with_(type=FAIL, error=reply.get("error"))
            return op.with_(type=INFO, error=reply.get("error"))
        except ConnectFailed as e:
            return op.with_(type=FAIL, error=str(e))
        except (OSError, socket.timeout) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()
