"""localkv suite CLI — real-process end-to-end runs on one host.

    python -m suites.localkv.runner test --time-limit 10
    python -m suites.localkv.runner test --unsafe --time-limit 10

Unlike the dummy-remote pipeline tests, nothing here is faked: servers are
real OS processes serving real TCP sockets, faults are real signals, and
the histories the checker judges came over the wire.  ``--unsafe`` turns on
follower local reads with a replication delay, which the linearizability
checker must refute; the default mode must verify.  This is the in-repo
stand-in for the reference's one-host docker cluster runs
(docker/README.md:12-29) in environments with no docker daemon or DB
binaries — see REALRUN.md.
"""

from __future__ import annotations

import socket
from typing import Any, Dict

from jepsen_tpu import cli, generator as gen
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.control import DummyRemote
from jepsen_tpu.nemesis import combined
from jepsen_tpu.workloads import linearizable_register

from suites.localkv.client import RegisterClient
from suites.localkv.db import LocalKvDB


def free_ports(n: int):
    """Ask the OS for n distinct free TCP ports."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


NEMESES = {
    "none": lambda opts: combined.Package(),
    "kill": lambda opts: combined.db_package({**opts, "faults": ["kill"]}),
    "pause": lambda opts: combined.db_package({**opts, "faults": ["pause"]}),
    "kill+pause": lambda opts: combined.db_package(
        {**opts, "faults": ["kill", "pause"]}),
}


def localkv_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    nodes = opts.get("nodes") or ["n1", "n2", "n3"]
    ports = free_ports(len(nodes))
    unsafe = bool(opts.get("unsafe"))
    nemesis_name = opts.get("nemesis", "kill")
    pkg = NEMESES[nemesis_name](
        {"interval": float(opts.get("nemesis_interval", 3.0))})

    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 4))),
        ops_per_key=int(opts.get("ops_per_key", 150)),
        threads_per_key=2)

    time_limit = float(opts.get("time_limit", 10.0))
    client_gen = gen.time_limit(time_limit, gen.clients(wl["generator"]))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(gen.nemesis(gen.lift(pkg.final_generator))))

    return {**opts,
            "name": ("localkv-unsafe" if unsafe else "localkv")
                    + f"-{nemesis_name}",
            "nodes": nodes,
            "localkv_ports": dict(zip(nodes, ports)),
            "localkv_unsafe": unsafe,
            "remote": DummyRemote(),  # local-exec: commands really run
            "db": LocalKvDB(),
            "client": RegisterClient(),
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose({"stats": Stats(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}


def _suite_opts(parser):
    parser.add_argument("--unsafe", action="store_true",
                        help="follower local reads + replication delay "
                             "(must be refuted)")
    parser.add_argument("--nemesis", default="kill",
                        choices=sorted(NEMESES))
    parser.add_argument("--keys", type=int, default=4)
    parser.add_argument("--ops-per-key", type=int, default=150)
    parser.add_argument("--nemesis-interval", type=float, default=3.0)


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(localkv_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-localkv"))
