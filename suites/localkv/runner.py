"""localkv suite CLI — real-process end-to-end runs on one host.

    python -m suites.localkv.runner test --time-limit 10
    python -m suites.localkv.runner test --unsafe --time-limit 10

Unlike the dummy-remote pipeline tests, nothing here is faked: servers are
real OS processes serving real TCP sockets, faults are real signals, and
the histories the checker judges came over the wire.  ``--unsafe`` turns on
follower local reads with a replication delay, which the linearizability
checker must refute; the default mode must verify.  This is the in-repo
stand-in for the reference's one-host docker cluster runs
(docker/README.md:12-29) in environments with no docker daemon or DB
binaries — see REALRUN.md.
"""

from __future__ import annotations

import socket
from typing import Any, Dict

import random

from jepsen_tpu import cli, generator as gen
from jepsen_tpu import net as jnet
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.control import DummyRemote
from jepsen_tpu.nemesis import combined
from jepsen_tpu.net_proxy import ProxyNet, ProxyRouter
from jepsen_tpu.workloads import linearizable_register

from suites.localkv.client import RegisterClient
from suites.localkv.db import LocalKvDB


def free_ports(n: int):
    """Ask the OS for n distinct free TCP ports."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _follower_isolating_grudge(nodes):
    """Partition one random follower from everyone else (the primary is
    nodes[0]): follower-side mutations become indeterminate, follower-side
    local reads (unsafe mode) go stale — a real refutation driver."""
    f = random.choice(list(nodes[1:]))
    return jnet.complete_grudge(jnet.split_one(f, list(nodes)))


NEMESES = {
    "none": lambda opts: combined.Package(),
    "kill": lambda opts: combined.db_package({**opts, "faults": ["kill"]}),
    "pause": lambda opts: combined.db_package({**opts, "faults": ["pause"]}),
    "kill+pause": lambda opts: combined.db_package(
        {**opts, "faults": ["kill", "pause"]}),
    # socket-level partitions via the framework-owned TCP proxy layer
    # (jepsen_tpu.net_proxy): real severed connections, stock grudge algebra
    "partition": lambda opts: combined.partition_package(
        {**opts, "grudge_fn": _follower_isolating_grudge}),
    # deterministic refutation schedule: one follower severed from t=delay
    # until the final heal, so unsafe local reads have a long, forced
    # staleness window instead of a lucky start/stop cycle
    "partition-hold": lambda opts: combined.partition_hold_package(
        {**opts, "grudge_fn": _follower_isolating_grudge}),
}


def localkv_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    nodes = opts.get("nodes") or ["n1", "n2", "n3"]
    ports = free_ports(len(nodes))
    unsafe = bool(opts.get("unsafe"))
    nemesis_name = opts.get("nemesis", "kill")
    pkg = NEMESES[nemesis_name](
        {"interval": float(opts.get("nemesis_interval", 3.0)),
         "delay": float(opts.get("nemesis_delay", 1.0))})

    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 4))),
        ops_per_key=int(opts.get("ops_per_key", 150)),
        threads_per_key=2,
        unique_writes=bool(opts.get("unique_writes")))

    time_limit = float(opts.get("time_limit", 10.0))
    wgen = wl["generator"]
    stagger_s = float(opts.get("stagger_s", 0.0))
    if stagger_s > 0:  # pace clients: bounded history -> bounded analysis
        wgen = gen.stagger(stagger_s, wgen)
    client_gen = gen.time_limit(time_limit, gen.clients(wgen))
    parts = [client_gen]
    if pkg.generator is not None:
        parts = [gen.any_gen(client_gen,
                             gen.nemesis(gen.time_limit(time_limit,
                                                        pkg.generator)))]
    if pkg.final_generator is not None:
        parts.append(gen.synchronize(gen.nemesis(gen.lift(pkg.final_generator))))
    if pkg.generator is not None:
        # Post-heal recovery phase: after the final nemesis op restores
        # every node, run the workload against the healthy cluster for a
        # while.  Under an aggressive fault schedule (kill every second for
        # the whole window) a short run can otherwise end with some op type
        # never once succeeding — a legitimate `unknown` from the stats
        # checker, but one that says "the schedule left no healthy window",
        # not "the store is broken".  This is the reference's standard
        # final-generator shape (nemesis stop, then more client ops).
        recovery = float(opts.get("recovery_time", 3.0))
        if recovery > 0:
            parts.append(gen.synchronize(
                gen.time_limit(recovery, gen.clients(wgen))))

    test = {**opts,
            "name": ("localkv-unsafe" if unsafe else "localkv")
                    + f"-{nemesis_name}",
            "nodes": nodes,
            "localkv_ports": dict(zip(nodes, ports)),
            "localkv_unsafe": unsafe,
            "remote": DummyRemote(),  # local-exec: commands really run
            "db": LocalKvDB(),
            "client": RegisterClient(),
            "nemesis": pkg.nemesis,
            "generator": parts,
            "checker": compose({"stats": Stats(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}
    if nemesis_name in ("partition", "partition-hold"):
        # Inter-node links dial through harness-owned TCP proxies so the
        # stock Partitioner severs real sockets (VERDICT: partitions
        # exercised end-to-end against real processes).
        router = ProxyRouter(nodes, dict(zip(nodes, ports)))
        test["proxy_router"] = router
        test["net"] = ProxyNet(router)
        # closed by core.run when the run ends (listener sockets + threads)
        test.setdefault("resources", []).append(router)
    return test


def _suite_opts(parser):
    parser.add_argument("--unsafe", action="store_true",
                        help="follower local reads + replication delay "
                             "(must be refuted)")
    parser.add_argument("--nemesis", default="kill",
                        choices=sorted(NEMESES))
    parser.add_argument("--keys", type=int, default=4)
    parser.add_argument("--ops-per-key", type=int, default=150)
    parser.add_argument("--nemesis-interval", type=float, default=3.0)


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(localkv_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-localkv"))
