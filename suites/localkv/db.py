"""localkv DB layer: real daemon lifecycle on each "node".

Every command here executes for real (the runner uses a non-record
DummyRemote, the local-exec transport): ``start_daemon`` forks an actual
``python server.py`` with a pidfile and logfile, ``kill`` delivers a real
SIGKILL via pkill, pause/resume are real SIGSTOP/SIGCONT, and log snarfing
downloads the server's actual WAL and stdout log.  Nodes are logical names
mapped to 127.0.0.1 ports — the same one-host topology as the reference's
docker environment (docker/README.md:12-29), with the network layer being
the real loopback stack.
"""

from __future__ import annotations

import os
import sys
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

SERVER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "server.py")


def port_of(test, node: str) -> int:
    return test["localkv_ports"][node]


def marker(test, node: str) -> str:
    """Distinctive argv tag so grepkill targets exactly this daemon."""
    return f"localkv-{node}-p{port_of(test, node)}"


def data_dir(test, node: str) -> str:
    return os.path.join(test.get("localkv_dir", "/tmp/jepsen-localkv"),
                        marker(test, node))


class LocalKvDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        s.exec("mkdir", "-p", d)
        self.start(test, node)
        cu.await_tcp_port(s, port_of(test, node), timeout_s=30)

    def teardown(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        cu.stop_daemon(s, os.path.join(d, "server.pid"))
        cu.grepkill(s, marker(test, node))
        if not test.get("leave_db_running"):
            s.exec("rm", "-rf", d)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        nodes = test["nodes"]
        primary = f"{nodes[0]}:{port_of(test, nodes[0])}"
        peers = ",".join(f"{n}:{port_of(test, n)}" for n in nodes[1:])
        args = [SERVER,
                "--node", node,
                "--port", str(port_of(test, node)),
                "--primary", primary,
                "--peers", peers,
                "--data", d,
                "--marker", marker(test, node)]
        if test.get("localkv_unsafe"):
            args += ["--local-reads",
                     "--repl-delay", str(test.get("repl_delay", 0.05))]
        cu.start_daemon(s, sys.executable, *args,
                        pidfile=os.path.join(d, "server.pid"),
                        logfile=os.path.join(d, "server.log"))

    def kill(self, test, node):
        s = session(test, node)
        cu.grepkill(s, marker(test, node))
        s.exec("rm", "-f", os.path.join(data_dir(test, node), "server.pid"))

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        return [test["nodes"][0]]

    # -- LogFiles capability ----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        d = data_dir(test, node)
        return [os.path.join(d, "server.log"), os.path.join(d, "wal.jsonl")]
