"""localkv DB layer: real daemon lifecycle on each "node".

Every command here executes for real (the runner uses a non-record
DummyRemote, the local-exec transport): ``start_daemon`` forks an actual
``python server.py`` with a pidfile and logfile, ``kill`` delivers a real
SIGKILL via pkill, pause/resume are real SIGSTOP/SIGCONT, and log snarfing
downloads the server's actual WAL and stdout log.  Nodes are logical names
mapped to 127.0.0.1 ports — the same one-host topology as the reference's
docker environment (docker/README.md:12-29), with the network layer being
the real loopback stack.
"""

from __future__ import annotations

import os
import sys
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

SERVER = os.path.join(os.path.dirname(os.path.abspath(__file__)), "server.py")


def port_of(test, node: str) -> int:
    return test["localkv_ports"][node]


def marker(test, node: str) -> str:
    """Distinctive argv tag so grepkill targets exactly this daemon."""
    return f"localkv-{node}-p{port_of(test, node)}"


def data_dir(test, node: str) -> str:
    return os.path.join(test.get("localkv_dir", "/tmp/jepsen-localkv"),
                        marker(test, node))


class LocalKvDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        s.exec("mkdir", "-p", d)
        self.start(test, node)
        cu.await_tcp_port(s, port_of(test, node), timeout_s=30)

    def teardown(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        cu.stop_daemon(s, os.path.join(d, "server.pid"))
        cu.grepkill(s, marker(test, node))
        if not test.get("leave_db_running"):
            s.exec("rm", "-rf", d)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node)
        d = data_dir(test, node)
        nodes = test["nodes"]
        # With a proxy router in the test map, every inter-node link dials
        # through a harness-owned TCP proxy so a partition nemesis can
        # sever it at the socket layer (jepsen_tpu.net_proxy).  Client
        # traffic still hits the node directly — like the reference,
        # partitions cut db-node links, not the control plane.
        router = test.get("proxy_router")

        def peer_port(dst: str) -> int:
            if router is not None and dst != node:
                return router.addr(node, dst)[1]
            return port_of(test, dst)  # self-dial needs no (and has no) link

        primary = f"{nodes[0]}:{peer_port(nodes[0])}"
        peers = ",".join(f"{n}:{peer_port(n)}" for n in nodes[1:])
        args = [SERVER,
                "--node", node,
                "--port", str(port_of(test, node)),
                "--primary", primary,
                "--peers", peers,
                "--data", d,
                "--marker", marker(test, node)]
        if test.get("localkv_unsafe"):
            args += ["--local-reads",
                     "--repl-delay", str(test.get("repl_delay", 0.05))]
        # PYTHONPATH is emptied for the daemon: the harness environment may
        # inject a sitecustomize that imports accelerator plugins (~2 s of
        # CPU per interpreter start).  The server is stdlib-only, and with
        # that tax a 1 s-interval kill nemesis would keep restarted servers
        # from EVER reaching their accept loop — observed as runs where no
        # op succeeds after the first kill.
        cu.start_daemon(s, sys.executable, *args,
                        pidfile=os.path.join(d, "server.pid"),
                        logfile=os.path.join(d, "server.log"),
                        env={"PYTHONPATH": ""})

    def kill(self, test, node):
        s = session(test, node)
        cu.grepkill(s, marker(test, node))
        s.exec("rm", "-f", os.path.join(data_dir(test, node), "server.pid"))

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node), marker(test, node), signal="CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        return [test["nodes"][0]]

    # -- LogFiles capability ----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        d = data_dir(test, node)
        return [os.path.join(d, "server.log"), os.path.join(d, "wal.jsonl")]
