"""localkv server — a real replicated KV store in a standalone process.

This is the system-under-test for the suite's *real-process* end-to-end
runs: N of these run as independent OS daemons (started over the control
plane with pidfiles, killed with real SIGKILL), speak a length-prefixed
JSON protocol over real TCP sockets, replicate asynchronously, and persist
a write-ahead log that survives crashes.

Topology: static primary (first node of the roster).  Followers forward
every mutation to the primary; the primary serializes ops under a lock,
appends to its WAL before acking, and replicates to followers
asynchronously.  Two read modes:

- default: reads are forwarded to the primary too -> linearizable (single
  serialization point, ack after apply);
- ``--local-reads``: a follower answers reads from its own (asynchronously
  maintained, hence stale) replica -> NOT linearizable; with
  ``--repl-delay`` the staleness window is wide enough that a short Jepsen
  run reliably refutes it.

Stdlib only; runnable as a bare script (the DB layer invokes it via
``python server.py ...`` on each "node").
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import socket
import socketserver
import struct
import sys
import threading
import time


def send_frame(sock: socket.socket, obj) -> None:
    data = json.dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(data)) + data)


def recv_frame(sock: socket.socket):
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > 1 << 20:
        raise ValueError("frame too large")
    data = _recv_exact(sock, length)
    if data is None:
        return None
    return json.loads(data.decode())


def _recv_exact(sock: socket.socket, n: int):
    buf = b""
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            return None
        buf += part
    return buf


class Store:
    """Keyed registers + write-ahead log; replay on restart."""

    def __init__(self, wal_path: str):
        self.kv = {}
        self.seq = 0
        self.lock = threading.Lock()
        self.wal_path = wal_path
        if os.path.exists(wal_path):
            with open(wal_path) as f:
                for line in f:
                    if line.strip():
                        rec = json.loads(line)
                        self.kv[rec["k"]] = rec["v"]
                        self.seq = rec["s"]
        self.wal = open(wal_path, "a")

    def log(self, key, value) -> int:
        self.seq += 1
        self.wal.write(json.dumps({"k": key, "v": value, "s": self.seq}) + "\n")
        self.wal.flush()
        os.fsync(self.wal.fileno())
        return self.seq


class Replicator(threading.Thread):
    """Async replication to one peer: at-least-once per live connection,
    reconnect on error, bounded queue (drops oldest when a peer is dead —
    this is the asynchrony --local-reads exposes)."""

    def __init__(self, peer_addr, delay: float):
        super().__init__(daemon=True)
        self.peer = peer_addr
        self.delay = delay
        self.q: queue.Queue = queue.Queue(maxsize=10000)
        self.sock = None

    def submit(self, msg) -> None:
        try:
            self.q.put_nowait(msg)
        except queue.Full:
            pass

    def run(self) -> None:
        while True:
            msg = self.q.get()
            if self.delay:
                time.sleep(self.delay)
            for _ in range(2):
                try:
                    if self.sock is None:
                        self.sock = socket.create_connection(self.peer,
                                                             timeout=2)
                    send_frame(self.sock, msg)
                    recv_frame(self.sock)
                    break
                except OSError:
                    try:
                        if self.sock:
                            self.sock.close()
                    except OSError:
                        pass
                    self.sock = None


class Server:
    def __init__(self, opts):
        self.node = opts.node
        self.port = opts.port
        self.is_primary = opts.node == opts.primary.split(":")[0]
        self.primary_addr = ("127.0.0.1", int(opts.primary.split(":")[1]))
        self.local_reads = opts.local_reads
        os.makedirs(opts.data, exist_ok=True)
        self.store = Store(os.path.join(opts.data, "wal.jsonl"))
        self.repls = []
        if self.is_primary:
            for peer in filter(None, opts.peers.split(",")):
                _n, p = peer.split(":")
                r = Replicator(("127.0.0.1", int(p)), opts.repl_delay)
                r.start()
                self.repls.append(r)

    # -- op handling -------------------------------------------------------

    def handle(self, msg):
        op = msg.get("op")
        if op == "ping":
            return {"ok": True, "node": self.node,
                    "primary": self.is_primary}
        if op == "repl":
            with self.store.lock:
                self.store.kv[msg["key"]] = msg["value"]
            return {"ok": True}
        if op == "read" and (self.is_primary or self.local_reads):
            with self.store.lock:
                return {"ok": True, "value": self.store.kv.get(msg["key"])}
        if not self.is_primary:
            return self.forward(msg)
        # primary mutation path: serialize, WAL, ack, replicate async
        with self.store.lock:
            key = msg["key"]
            cur = self.store.kv.get(key)
            if op == "write":
                value = msg["value"]
            elif op == "cas":
                if cur != msg["old"]:
                    return {"ok": False, "error": "cas-mismatch",
                            "definite": True}
                value = msg["new"]
            else:
                return {"ok": False, "error": f"bad op {op!r}",
                        "definite": True}
            self.store.kv[key] = value
            self.store.log(key, value)
        for r in self.repls:
            r.submit({"op": "repl", "key": key, "value": value})
        return {"ok": True}

    def forward(self, msg):
        """Relay to the primary.  A connect failure is definite (the op
        never reached the primary); a mid-flight failure is indeterminate."""
        try:
            sock = socket.create_connection(self.primary_addr, timeout=2)
        except OSError as e:
            return {"ok": False, "error": f"primary-unreachable: {e}",
                    "definite": True}
        try:
            with sock:
                send_frame(sock, msg)
                reply = recv_frame(sock)
            if reply is None:
                raise OSError("primary closed mid-reply")
            return reply
        except OSError as e:
            return {"ok": False, "error": f"forward-failed: {e}",
                    "indeterminate": True}

    # -- serving -----------------------------------------------------------

    def serve(self) -> None:
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = recv_frame(self.request)
                    except (OSError, ValueError):
                        return
                    if msg is None:
                        return
                    try:
                        reply = outer.handle(msg)
                    except Exception as e:  # noqa: BLE001
                        reply = {"ok": False, "error": repr(e),
                                 "indeterminate": True}
                    try:
                        send_frame(self.request, reply)
                    except OSError:
                        return

        class TS(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        with TS(("127.0.0.1", self.port), Handler) as srv:
            print(f"localkv {self.node} serving on {self.port} "
                  f"(primary={self.is_primary})", flush=True)
            srv.serve_forever()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--node", required=True)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--primary", required=True, help="node:port of primary")
    ap.add_argument("--peers", default="", help="node:port,... of followers")
    ap.add_argument("--data", required=True)
    ap.add_argument("--local-reads", action="store_true")
    ap.add_argument("--repl-delay", type=float, default=0.0)
    ap.add_argument("--marker", default="", help="argv tag for grepkill")
    Server(ap.parse_args(argv)).serve()
    return 0


if __name__ == "__main__":
    sys.exit(main())
