"""Aerospike workload clients.

Parity: aerospike/src/aerospike/cas_register.clj:43-76 (read/write/cas on
one bin, CAS via fetch + generation-checked write), counter.clj:43-60
(read/add via the incr op), set.clj:11-41 (string-append a " v" suffix,
read splits on spaces).  Error taxonomy follows support.clj's with-errors:
reads fail definitely, mutations are indeterminate on timeout/connection
errors.
"""

from __future__ import annotations

import socket
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients import aerospike as aswire
from jepsen_tpu.clients.aerospike import AerospikeClient, AerospikeError
from jepsen_tpu.history import FAIL, INFO, OK, Op

PORT = 3000
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)


def connect(test, node) -> AerospikeClient:
    return AerospikeClient(node, port=int(test.get("db_port", PORT)),
                           namespace="jepsen", timeout=5.0)


class CasRegisterClient(jclient.Client):
    """Per-key CAS register on set "cats", bin "value"."""

    SET = "cats"

    def __init__(self, conn: Optional[AerospikeClient] = None):
        self.conn = conn

    def open(self, test, node):
        return CasRegisterClient(connect(test, node))

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                rec = self.conn.get(self.SET, k)
                val = rec[0].get("value") if rec else None
                return op.with_(type=OK, value=(k, val))
            if op.f == "write":
                self.conn.put(self.SET, k, {"value": v})
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                rec = self.conn.get(self.SET, k)
                if rec is None or rec[0].get("value") != old:
                    return op.with_(type=FAIL, error="precondition")
                try:
                    self.conn.put(self.SET, k, {"value": new},
                                  generation=rec[1])
                except AerospikeError as e:
                    if e.code == aswire.RESULT_GENERATION:
                        return op.with_(type=FAIL, error="generation")
                    raise
                return op.with_(type=OK)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self.conn.close()
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except AerospikeError as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))


class CounterClient(jclient.Client):
    """Counter on set "counters", key "pounce" (counter.clj:43-66)."""

    SET = "counters"
    KEY = "pounce"

    def __init__(self, conn: Optional[AerospikeClient] = None):
        self.conn = conn

    def open(self, test, node):
        return CounterClient(connect(test, node))

    def setup(self, test):
        try:
            self.conn.put(self.SET, self.KEY, {"value": 0})
        except (AerospikeError, *NET_ERRORS):
            pass

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rec = self.conn.get(self.SET, self.KEY)
                return op.with_(type=OK,
                                value=rec[0].get("value") if rec else 0)
            if op.f == "add":
                self.conn.add(self.SET, self.KEY, {"value": op.value})
                return op.with_(type=OK)
            raise ValueError(op.f)
        except (AerospikeError, *NET_ERRORS) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))


class SetClient(jclient.Client):
    """Per-key grow-only set: append " v" to a string bin; reads split on
    whitespace (set.clj:18-36)."""

    SET = "cats"

    def __init__(self, conn: Optional[AerospikeClient] = None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(connect(test, node))

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                rec = self.conn.get(self.SET, k)
                raw = rec[0].get("value", "") if rec else ""
                vals = sorted(int(x) for x in str(raw).split() if x)
                return op.with_(type=OK, value=(k, vals))
            if op.f == "add":
                self.conn.append(self.SET, k, {"value": f" {v}"})
                return op.with_(type=OK)
            raise ValueError(op.f)
        except (AerospikeError, *NET_ERRORS) as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
