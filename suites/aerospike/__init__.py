"""Aerospike suite (reference: aerospike/ — CAS register, counter, set,
and pause workloads over the strong-consistency namespace)."""
