"""Aerospike suite CLI: workload registry + the "full" havoc nemesis.

Parity: aerospike/src/aerospike/core.clj:17-78 (workload table
cas-register/counter/set/pause, workload+nemesis wiring) and nemesis.clj:
kill-nemesis with a max-dead-nodes cap (17-57), randomized
kill/restart/revive/recluster schedule (59-101), full-nemesis composing
kills + random-halves partitions + clock faults (103-121), and the
heal-everything final generator (130-145).  The pause workload
(pause.clj:173-233) couples a set workload with a pause/resume nemesis in
process, net, or clock mode.
"""

from __future__ import annotations

import random
from typing import Any, Dict

from jepsen_tpu import control
from jepsen_tpu import generator as gen
from jepsen_tpu import independent, nemesis as jnem
from jepsen_tpu.checker.core import CounterChecker, SetChecker
from jepsen_tpu.control import util as cu
from jepsen_tpu.nemesis import combined
from jepsen_tpu.nemesis.partition import Partitioner, random_halves_grudge
from jepsen_tpu.nemesis.time import ClockNemesis, clock_gen
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.aerospike import db as asdb
from suites.aerospike.client import CasRegisterClient, CounterClient, SetClient
from suites.aerospike.db import AerospikeDB


def _nonempty_subset(nodes):
    return random.sample(nodes, random.randint(1, len(nodes)))


class KillNemesis(jnem.Nemesis):
    """Kill/restart with at most ``max_dead`` simultaneously-dead nodes,
    plus revive/recluster admin ops (nemesis.clj:17-57)."""

    def __init__(self, signal: str = "KILL", max_dead: int = 2):
        self.signal = signal
        self.max_dead = max_dead
        self.dead: set = set()

    def invoke(self, test, op):
        nodes = op.value or test["nodes"]
        results = {}
        for node in nodes:
            s = control.session(test, node).sudo()
            if op.f == "kill":
                if len(self.dead | {node}) > self.max_dead:
                    results[node] = "still-alive"
                    continue
                self.dead.add(node)
                cu.grepkill(s, "asd", signal=self.signal)
                results[node] = "killed"
            elif op.f == "restart":
                s.exec("service", "aerospike", "restart")
                self.dead.discard(node)
                results[node] = "started"
            elif op.f == "revive":
                try:
                    asdb.revive(s)
                    results[node] = "revived"
                except Exception as e:  # noqa: BLE001 — node may be down
                    results[node] = f"not-running: {e}"
            elif op.f == "recluster":
                try:
                    asdb.recluster(s)
                    results[node] = "reclustered"
                except Exception as e:  # noqa: BLE001
                    results[node] = f"not-running: {e}"
            else:
                raise ValueError(op.f)
        return op.with_(type="info", value=results)

    def fs(self):
        return ["kill", "restart", "revive", "recluster"]


class KillerGen(gen.Generator):
    """Generator form of killer_gen — needs the test map for node lists."""

    def __init__(self, queue=()):
        self.queue = list(queue)

    def op(self, test, ctx):
        queue = self.queue
        if not queue:
            queue = random.choice(
                [[("kill", True)], [("restart", True)],
                 [("revive", False), ("recluster", False)]])[:]
        (f, subset), rest = queue[0], queue[1:]
        nodes = list(test["nodes"])
        value = _nonempty_subset(nodes) if subset else nodes
        op = gen.fill_op({"type": "info", "f": f, "value": value}, ctx)
        if op is gen.PENDING:
            return (gen.PENDING, self)
        return (op, KillerGen(rest))

    def update(self, test, ctx, event):
        return self


def full_package(opts: Dict[str, Any]) -> combined.Package:
    """Compose kills + partitions + clock (nemesis.clj:103-145)."""
    max_dead = int(opts.get("max_dead_nodes", 2))
    signal = "TERM" if opts.get("clean_kill") else "KILL"
    killer = KillNemesis(signal=signal, max_dead=max_dead)
    part = Partitioner(random_halves_grudge, start_f="partition-start",
                       stop_f="partition-stop")
    members = [killer, part, ClockNemesis()]
    nem = jnem.Compose(members, [set(killer.fs()),
                                 {"partition-start", "partition-stop"},
                                 set(ClockNemesis().fs())])

    parts = []
    if not opts.get("no_clocks"):
        parts.append(clock_gen())
    if not opts.get("no_kills"):
        parts.append(KillerGen())
    if not opts.get("no_partitions"):
        parts.append(gen.cycle(gen.lift(
            [{"type": "info", "f": "partition-start"},
             {"type": "info", "f": "partition-stop"}])))
    interval = float(opts.get("interval", 5.0))
    g = gen.stagger(interval, gen.mix(parts)) if parts else None

    def restart_all(test, ctx):
        return {"type": "info", "f": "restart", "value": list(test["nodes"])}

    final = [{"type": "info", "f": "partition-stop"},
             {"type": "info", "f": "reset-clock"},
             # bare fns repeat forever; final phases need exactly one
             gen.once(restart_all),
             {"type": "info", "f": "revive"},
             {"type": "info", "f": "recluster"}]
    return combined.Package(nemesis=nem, generator=g, final_generator=final)


NEMESES = dict(common.STANDARD_NEMESES)
NEMESES["full"] = full_package


def cas_register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 150)),
        threads_per_key=2)
    return {**wl, "client": CasRegisterClient()}


def counter_workload(opts) -> Dict[str, Any]:
    """100:1 add/read mix (counter.clj:68-76)."""
    g = gen.mix([gen.repeat({"f": "add", "value": 1}),
                 gen.stagger(0.1, gen.repeat({"f": "read"}))])
    return {"client": CounterClient(), "generator": g,
            "checker": CounterChecker()}


def set_workload(opts) -> Dict[str, Any]:
    """Per-key append-based sets with final reads (set.clj:47-72)."""
    keys = list(range(int(opts.get("keys", 4))))

    def adds(k):
        counter = iter(range(10_000))

        def one():
            v = next(counter, None)
            # exhaustion must surface as None, not StopIteration
            return None if v is None else {"f": "add", "value": v}

        return gen.FnGen(one)

    return {
        "client": SetClient(),
        "generator": independent.concurrent_generator(
            int(opts.get("threads_per_key", 2)), keys, adds),
        "final_generator": independent.sequential_generator(
            keys, lambda k: gen.once({"f": "read"})),
        "checker": independent.checker(SetChecker()),
    }


def pause_workload(opts) -> Dict[str, Any]:
    """Set workload under a targeted pause/resume nemesis
    (pause.clj:173-233); mode selects process SIGSTOP, net slowdown, or
    clock bump."""
    return set_workload(opts)


def pause_package(opts: Dict[str, Any]) -> combined.Package:
    mode = opts.get("pause_mode", "process")
    if mode == "net":
        return combined.packet_package(opts)
    if mode == "clock":
        return combined.clock_package(opts)
    return combined.db_package({**opts, "faults": ["pause"]})


WORKLOADS = {
    "cas-register": cas_register_workload,
    "counter": counter_workload,
    "set": set_workload,
    "pause": pause_workload,
}


def aerospike_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    nemeses = dict(NEMESES)
    if opts.get("workload") == "pause":
        # coupled workload+nemesis special case (core.clj:33-40)
        opts = {**opts, "nemesis": "pause"}
        nemeses["pause"] = pause_package
    return common.build_test(opts, suite="aerospike", db=AerospikeDB(),
                             workloads=WORKLOADS, nemeses=nemeses)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, aerospike_test, WORKLOADS, NEMESES)


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=150)
    parser.add_argument("--replication-factor", type=int, default=3)
    parser.add_argument("--max-dead-nodes", type=int, default=2)
    parser.add_argument("--clean-kill", action="store_true")
    parser.add_argument("--pause-mode", default="process",
                        choices=["process", "net", "clock"])
    parser.add_argument("--heartbeat-interval", type=int, default=150)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(aerospike_test, WORKLOADS, NEMESES,
                         prog="jepsen-tpu-aerospike", extra_opts=_extra))
