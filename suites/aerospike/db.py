"""Aerospike server install/config/roster management.

Parity: aerospike/src/aerospike/support.clj — install! (211-255, dpkg of
server+tools packages), configure! (257-277, templated aerospike.conf with
heartbeat interval and a strong-consistency namespace), start!/stop!/wipe!
(279-321), roster management for the SC namespace (154-209:
roster-set + recluster until all nodes are active), and the asinfo
revive/recluster admin commands (136-152).
"""

from __future__ import annotations

import time
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

NAMESPACE = "jepsen"
PACKAGE_DIR = "/tmp/packages"
CONF = "/etc/aerospike/aerospike.conf"
LOGFILE = "/var/log/aerospike.log"
PORT = 3000

CONF_TEMPLATE = """\
service {{
  user root
  group root
  pidfile /var/run/aerospike/asd.pid
  proto-fd-max 15000
}}
logging {{
  file {logfile} {{ context any info }}
}}
network {{
  service {{ address any
             port {port} }}
  heartbeat {{ mode mesh
               port 3002
{mesh_seeds}
               interval {heartbeat_interval}
               timeout 10 }}
  fabric {{ port 3001 }}
  info {{ port 3003 }}
}}
namespace {namespace} {{
  replication-factor {replication_factor}
  default-ttl 0
  strong-consistency true
  storage-engine memory {{ data-size 1G }}
}}
"""


def config(test, node) -> str:
    seeds = "\n".join(f"               mesh-seed-address-port {n} 3002"
                      for n in test["nodes"])
    return CONF_TEMPLATE.format(
        logfile=LOGFILE, port=PORT, namespace=NAMESPACE,
        mesh_seeds=seeds,
        heartbeat_interval=int(test.get("heartbeat_interval", 150)),
        replication_factor=int(test.get("replication_factor", 3)))


def revive(s) -> None:
    """asinfo -v revive:namespace=… (support.clj:142-147)."""
    s.exec("asinfo", "-v", f"revive:namespace={NAMESPACE}")


def recluster(s) -> None:
    """asinfo -v recluster: (support.clj:149-152)."""
    s.exec("asinfo", "-v", "recluster:")


class AerospikeDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        if not cu.exists(s, "/usr/bin/asd"):
            # packages staged on the control node are uploaded then dpkg'd
            # (support.clj:211-255: local-packages dir -> remote dir);
            # --force-confnew keeps our conf
            import glob
            local = test.get("local_package_dir", "packages")
            debs = sorted(glob.glob(f"{local}/*.deb"))
            if not debs:
                raise RuntimeError(
                    f"no aerospike .deb packages staged in {local!r}; "
                    "set test['local_package_dir'] "
                    "(support.clj:211-226 semantics)")
            s.exec("mkdir", "-p", PACKAGE_DIR)
            s.upload(debs, PACKAGE_DIR)
            s.exec("sh", "-c",
                   f"dpkg -i --force-confnew {PACKAGE_DIR}/*.deb")
        cu.write_file(s, config(test, node), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=120)
        if node == test["nodes"][0]:
            self._set_roster(s, test)

    def _set_roster(self, s, test) -> None:
        """Set the SC roster to the observed node list and recluster
        (support.clj:163-209)."""
        for _ in range(30):
            out = s.exec("asinfo", "-v",
                         f"roster:namespace={NAMESPACE}").strip()
            observed = ""
            for part in out.split(":"):
                if part.startswith("observed_nodes="):
                    observed = part.split("=", 1)[1]
            if observed and len(observed.split(",")) == len(test["nodes"]):
                s.exec("asinfo", "-v",
                       f"roster-set:namespace={NAMESPACE};nodes={observed}")
                recluster(s)
                return
            time.sleep(1)
        raise RuntimeError("roster never observed all nodes")

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "asd")
        s.exec("sh", "-c", f"rm -rf {LOGFILE} /opt/aerospike/data || true")

    def start(self, test, node):
        session(test, node).sudo().exec("service", "aerospike", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "asd")

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "asd", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "asd", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
