"""RethinkDB suite (reference: rethinkdb/ — document CAS under partitions
and topology reconfiguration)."""
