"""RethinkDB suite CLI.

Parity: rethinkdb/src/jepsen/rethinkdb/document_cas.clj:129-185 (cas-test
with write/read mode matrix, cas-reconfigure-test) and rethinkdb.clj:
180-231 (reconfigure! + reconfigure-nemesis: random replica subset,
random primary, addressed by server tag).
"""

from __future__ import annotations

import random
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as jnem
from jepsen_tpu.clients import rethinkdb as rq
from jepsen_tpu.nemesis import combined
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.rethinkdb.client import DB, TABLE, DocumentCasClient, connect
from suites.rethinkdb.db import RethinkDB


class ReconfigureNemesis(jnem.Nemesis):
    """Randomly reshape the table's replica set (rethinkdb.clj:196-231)."""

    def invoke(self, test, op):
        nodes = list(test["nodes"])
        last_err = None
        for _ in range(10):
            # re-sample topology every attempt: retrying one dead primary
            # ten times would waste the whole op under partitions
            size = random.randint(1, len(nodes))
            replicas = random.sample(nodes, size)
            primary = random.choice(replicas)
            try:
                conn = connect(test, primary)
                try:
                    res = conn.run(rq.reconfigure(
                        DB, TABLE, shards=1,
                        replicas={n: 1 for n in replicas},
                        primary_tag=primary))
                    if res.get("reconfigured") != 1:
                        raise rq.ReqlError(f"reconfigured={res}")
                    return op.with_(type="info",
                                    value={"replicas": replicas,
                                           "primary": primary})
                finally:
                    conn.close()
            except Exception as e:  # noqa: BLE001 — unreachable servers
                last_err = e
        return op.with_(type="info", error=str(last_err))

    def fs(self):
        return ["reconfigure"]


def reconfigure_package(opts: Dict[str, Any]) -> combined.Package:
    """start/stop partitions interposed with reconfigures
    (cas-reconfigure-test's generator, document_cas.clj:160-180)."""
    part = combined.partition_package(opts)
    nem = jnem.Compose([ReconfigureNemesis(), part.nemesis],
                       [{"reconfigure"},
                        {"start-partition", "stop-partition"}])
    interval = float(opts.get("interval", 5.0))
    g = gen.stagger(interval, gen.cycle(gen.lift([
        {"type": "info", "f": "start-partition"},
        {"type": "info", "f": "reconfigure"},
        {"type": "info", "f": "stop-partition"},
        {"type": "info", "f": "reconfigure"}])))
    return combined.Package(
        nemesis=nem, generator=g,
        final_generator=[{"type": "info", "f": "stop-partition"}])


NEMESES = dict(common.STANDARD_NEMESES)
NEMESES["reconfigure"] = reconfigure_package


def cas_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 60)),
        threads_per_key=2)
    return {**wl, "client": DocumentCasClient(
        write_acks=opts.get("write_acks", "majority"),
        read_mode=opts.get("read_mode", "majority"))}


WORKLOADS = {"document-cas": cas_workload}


def rethinkdb_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="rethinkdb", db=RethinkDB(),
                             workloads=WORKLOADS, nemeses=NEMESES)


def all_tests(opts: Dict[str, Any]):
    """Write/read-mode matrix x nemeses (document_cas.clj:129's
    cas-test variants)."""
    out = []
    for wa, rm in opts.get("modes", [("majority", "majority"),
                                     ("majority", "single"),
                                     ("single", "majority")]):
        for n in opts.get("nemeses", sorted(NEMESES)):
            out.append(rethinkdb_test({**opts, "workload": "document-cas",
                                       "write_acks": wa, "read_mode": rm,
                                       "nemesis": n}))
    return out


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=60)
    parser.add_argument("--write-acks", default="majority",
                        choices=["majority", "single"])
    parser.add_argument("--read-mode", default="majority",
                        choices=["majority", "single", "outdated"])


if __name__ == "__main__":
    import sys
    sys.exit(common.main(rethinkdb_test, WORKLOADS, NEMESES,
                         prog="jepsen-tpu-rethinkdb", extra_opts=_extra))
