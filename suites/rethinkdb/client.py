"""RethinkDB document-CAS client and table bootstrap.

Parity: rethinkdb/src/jepsen/rethinkdb/document_cas.clj:53-110 — one
document per key in db "jepsen" table "cas"; read via row["val"] with a
nil default, write via insert with conflict=update, CAS via an update
branch that errors unless the current value matches.  Table creation sets
write_acks and read_mode (31-49 set-write-acks!).
"""

from __future__ import annotations

import socket
import threading
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients import rethinkdb as rq
from jepsen_tpu.history import FAIL, INFO, OK, Op

DB = "jepsen"
TABLE = "cas"
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)


def connect(test, node) -> rq.RethinkClient:
    return rq.RethinkClient(node,
                            port=int(test.get("db_port", rq_port(test))),
                            user=test.get("db_user", "admin"),
                            password=test.get("db_password", ""))


def rq_port(test) -> int:
    return int(test.get("db_port", 28015))


class DocumentCasClient(jclient.Client):
    _table_lock = threading.Lock()
    _table_made = False

    def __init__(self, write_acks: str = "majority",
                 read_mode: str = "majority",
                 conn: Optional[rq.RethinkClient] = None,
                 node: Optional[str] = None):
        self.write_acks = write_acks
        self.read_mode = read_mode
        self.conn = conn
        self.node = node

    def open(self, test, node):
        return DocumentCasClient(self.write_acks, self.read_mode,
                                 connect(test, node), node)

    def _reconnect(self, test):
        """A dead socket must not poison every later op on this worker —
        the interpreter only swaps clients after an INFO crash."""
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.conn = connect(test, self.node)
        except Exception:  # noqa: BLE001 — node may be down; retry later
            pass

    def setup(self, test):
        with DocumentCasClient._table_lock:
            if DocumentCasClient._table_made:
                return
            try:
                self.conn.run(rq.db_create(DB))
            except rq.ReqlError:
                pass  # exists
            try:
                self.conn.run(rq.table_create(
                    DB, TABLE, replicas=len(test.get("nodes", [])) or 1,
                    write_acks=self.write_acks))
            except rq.ReqlError:
                pass
            try:
                self.conn.run(rq.wait_table(DB, TABLE))
            except rq.ReqlError:
                pass
            DocumentCasClient._table_made = True

    def teardown(self, test):
        DocumentCasClient._table_made = False

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        tbl = rq.table(DB, TABLE, read_mode=self.read_mode)
        row = rq.get(tbl, k)
        try:
            if op.f == "read":
                val = self.conn.run(rq.get_field(row, "val"))
                return op.with_(type=OK, value=(k, val))
            if op.f == "write":
                self.conn.run(rq.insert(rq.table(DB, TABLE),
                                        {"id": k, "val": v},
                                        conflict="update"))
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                try:
                    res = self.conn.run(rq.update_cas(row, "val", old, new))
                except rq.ReqlError as e:
                    if "abort" in str(e):
                        return op.with_(type=FAIL, error="precondition")
                    raise
                ok = (res.get("errors", 1) == 0 and
                      res.get("replaced", 0) == 1)
                return op.with_(type=OK if ok else FAIL)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self._reconnect(test)
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except rq.ReqlError as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
