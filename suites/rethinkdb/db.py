"""RethinkDB install/config/start.

Parity: rethinkdb/src/jepsen/rethinkdb.clj:52-95 — apt install from the
rethinkdb repo, /etc/rethinkdb/instances.d/jepsen.conf with join= lines
for every node plus server-name/server-tag set to the node name (the
reconfigure nemesis addresses primaries by server tag), service start,
log at /var/log/rethinkdb.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

LOGFILE = "/var/log/rethinkdb"
CONF = "/etc/rethinkdb/instances.d/jepsen.conf"
CLIENT_PORT = 28015
CLUSTER_PORT = 29015


def config(test, node) -> str:
    joins = "\n".join(f"join={n}:{CLUSTER_PORT}" for n in test["nodes"])
    return (f"bind=all\nlog-file={LOGFILE}\n\n{joins}\n\n"
            f"server-name={node}\nserver-tag={node}\n")


class RethinkDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        if not cu.exists(s, "/usr/bin/rethinkdb"):
            s.exec("sh", "-c",
                   "echo 'deb https://download.rethinkdb.com/repository/"
                   "debian-bullseye bullseye main' "
                   "> /etc/apt/sources.list.d/rethinkdb.list")
            s.exec("sh", "-c",
                   "wget -qO- https://download.rethinkdb.com/repository/"
                   "raw/pubkey.gpg | apt-key add -")
            s.exec("apt-get", "update")
            s.exec("apt-get", "install", "-y", "rethinkdb")
        s.exec("sh", "-c", f"touch {LOGFILE} && "
                           f"chown rethinkdb:rethinkdb {LOGFILE} || true")
        cu.write_file(s, config(test, node), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, CLIENT_PORT, timeout_s=120)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "rethinkdb")
        s.exec("sh", "-c",
               f"rm -rf /var/lib/rethinkdb/jepsen {LOGFILE}")

    def start(self, test, node):
        session(test, node).sudo().exec("service", "rethinkdb", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "rethinkdb")

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "rethinkdb", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "rethinkdb", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
