"""MongoDB-RocksDB suite: logger perf workload.

Parity: mongodb-rocks/src/jepsen/mongodb_rocks.clj — mongod with a
pluggable storage engine (--storageEngine rocksdb), a 100 KiB-payload
insert + oldest-first find-and-remove workload at high concurrency, and
a latency/throughput (perf) verdict rather than a consistency checker.
"""

from __future__ import annotations

import random
import socket
import time as _time
from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.clients.mongo import MongoClient, MongoError
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu
from jepsen_tpu import db as jdb
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites import common

PORT = 27017
PAYLOAD = "x" * (100 * 1024)  # mongodb_rocks.clj:85's 100 KiB payload
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)


class MongoRocksDB(jdb.DB, jdb.Kill, jdb.LogFiles):
    """Single-node mongod with a selectable storage engine
    (mongodb_rocks.clj:29-70)."""

    DATA = "/var/mongodb-rocks"
    LOGFILE = "/var/log/mongodb-rocks.log"
    PIDFILE = "/var/run/mongod-rocks.pid"

    def __init__(self, engine: str = "wiredTiger"):
        self.engine = engine

    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "command -v mongod >/dev/null 2>&1 || "
               "apt-get install -y mongodb-server")
        s.exec("mkdir", "-p", self.DATA)
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=120)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "mongod")
        s.exec("sh", "-c", f"rm -rf {self.DATA}/* {self.LOGFILE} || true")

    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(s, "mongod", "--dbpath", self.DATA,
                        "--port", str(PORT), "--bind_ip_all",
                        "--storageEngine",
                        test.get("storage_engine", self.engine),
                        pidfile=self.PIDFILE, logfile=self.LOGFILE)

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "mongod")

    def log_files(self, test, node) -> List[str]:
        return [self.LOGFILE]


class LoggerClient(jclient.Client):
    """Insert timestamped payloads; delete = remove the oldest
    (mongodb_rocks.clj:86-123)."""

    COLL = "logger"

    def __init__(self, conn: Optional[MongoClient] = None,
                 node: Optional[str] = None):
        self.conn = conn
        self.node = node

    def open(self, test, node):
        return LoggerClient(
            MongoClient(node, int(test.get("db_port", PORT))).connect(),
            node)

    def close(self, test):
        if self.conn:
            self.conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                self.conn.insert(self.COLL,
                                 {"_id": op.value,
                                  # lint: disable=CONC01(DB document wall-clock timestamp)
                                  "time": int(_time.time() * 1000),
                                  "payload": PAYLOAD})
                return op.with_(type=OK)
            if op.f == "delete":
                r = self.conn.command({"findAndModify": self.COLL,
                                       "query": {},
                                       "sort": {"time": 1},
                                       "remove": True})
                doc = r.get("value")
                if doc is None:
                    return op.with_(type=FAIL)
                return op.with_(type=OK, value=doc.get("_id"))
            raise ValueError(op.f)
        except NET_ERRORS as e:
            try:
                self.conn.close()
                self.conn = MongoClient(
                    self.node, int(test.get("db_port", PORT))).connect()
            except Exception:  # noqa: BLE001
                pass
            if op.f == "delete":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except MongoError as e:
            if op.f == "delete":
                return op.with_(type=FAIL, error=str(e)[:200])
            return op.with_(type=INFO, error=str(e)[:200])


class ThroughputChecker(Checker):
    """Perf verdict: the logger test has no consistency model — it
    reports write/delete throughput (mongodb_rocks.clj:157-165)."""

    def check(self, test, history, opts=None):
        oks = [op for op in history if op.type == OK]
        if not oks:
            return {"valid": UNKNOWN, "error": "no completed ops"}
        t0 = min(op.time for op in oks)
        t1 = max(op.time for op in oks)
        dt = max((t1 - t0) / 1e9, 1e-9)
        return {"valid": True,
                "writes": sum(1 for o in oks if o.f == "write"),
                "deletes": sum(1 for o in oks if o.f == "delete"),
                "throughput-hz": round(len(oks) / dt, 2)}


def logger_workload(opts) -> Dict[str, Any]:
    def write():
        return {"f": "write",
                # lint: disable=CONC01(unique document id, not an interval)
                "value": f"{int(_time.time())}-oempa_"
                         f"{random.randrange(2**31)}"}

    g = gen.mix([gen.FnGen(write), gen.FnGen(write),
                 gen.repeat({"f": "delete"})])
    return {"client": LoggerClient(), "generator": g,
            "checker": ThroughputChecker()}


WORKLOADS = {"logger": logger_workload}


def mongodb_rocks_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(
        opts, suite="mongodb-rocks",
        db=MongoRocksDB(opts.get("storage_engine", "wiredTiger")),
        workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    """Engine comparison sweep (mongodb_rocks.clj's rocksdb-vs-wiredtiger
    point)."""
    return [mongodb_rocks_test({**opts, "storage_engine": e,
                                "nemesis": opts.get("nemesis", "none")})
            for e in opts.get("engines", ["wiredTiger", "rocksdb"])]


def _extra(parser):
    parser.add_argument("--storage-engine", default="wiredTiger")


if __name__ == "__main__":
    import sys
    sys.exit(common.main(mongodb_rocks_test, WORKLOADS,
                         prog="jepsen-tpu-mongodb-rocks",
                         extra_opts=_extra))
