"""MongoDB-with-RocksDB suite (reference: mongodb-rocks/ — a logger/queue
perf workload comparing storage engines)."""
