"""RabbitMQ suite (reference: rabbitmq/ — mirrored queue and
distributed-semaphore workloads over AMQP)."""
