"""RabbitMQ install/cluster.

Parity: rabbitmq/src/jepsen/rabbitmq.clj:24-101 — deb install with
erlang, shared erlang cookie "jepsen-rabbitmq", cluster join of every
node to node 1 via rabbitmqctl join_cluster, ha-maj mirroring policy on
jepsen.* queues, teardown nukes beam/epmd and the mnesia dir.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

COOKIE = "jepsen-rabbitmq"
COOKIE_FILE = "/var/lib/rabbitmq/.erlang.cookie"
LOGDIR = "/var/log/rabbitmq"
AMQP_PORT = 5672

HA_POLICY = ('{"ha-mode": "exactly", "ha-params": 3, '
             '"ha-sync-mode": "automatic"}')


class RabbitDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "dpkg-query -l rabbitmq-server >/dev/null 2>&1 || "
               "apt-get install -y erlang-nox rabbitmq-server")
        # shared cookie before clustering (rabbitmq.clj:42-50)
        s.exec("sh", "-c",
               f"[ -f {COOKIE_FILE} ] && "
               f"[ \"$(cat {COOKIE_FILE})\" = '{COOKIE}' ] || "
               f"{{ service rabbitmq-server stop || true; "
               f"echo '{COOKIE}' > {COOKIE_FILE}; "
               f"chown rabbitmq:rabbitmq {COOKIE_FILE}; "
               f"chmod 600 {COOKIE_FILE}; }}")
        self.start(test, node)
        cu.await_tcp_port(s, AMQP_PORT, timeout_s=120)
        first = test["nodes"][0]
        if node != first:
            s.exec("rabbitmqctl", "stop_app")
            s.exec("rabbitmqctl", "join_cluster", f"rabbit@{first}")
            s.exec("rabbitmqctl", "start_app")
        # mirror jepsen.* queues across 3 nodes (rabbitmq.clj:82-88)
        s.exec("rabbitmqctl", "set_policy", "ha-maj", "jepsen.",
               HA_POLICY)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c", "killall -9 beam.smp epmd || true")
        s.exec("rm", "-rf", "/var/lib/rabbitmq/mnesia/")
        s.exec("sh", "-c", "service rabbitmq-server stop || true")

    def start(self, test, node):
        session(test, node).sudo().exec(
            "sh", "-c",
            "service rabbitmq-server status >/dev/null 2>&1 || "
            "service rabbitmq-server start")

    def kill(self, test, node):
        session(test, node).sudo().exec(
            "sh", "-c", "killall -9 beam.smp epmd || true")

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "beam.smp", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "beam.smp", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [f"{LOGDIR}/rabbit@{node}.log"]
