"""RabbitMQ suite CLI.

Parity: rabbitmq/src/jepsen/rabbitmq.clj — queue workload (enqueue/
dequeue mix + drain, total-queue checker) and the distributed-semaphore
mutex workload (acquire/release, linearizable against the mutex model).
"""

from __future__ import annotations

from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import get_model
from jepsen_tpu.workloads import queue as queue_wl

from suites import common
from suites.rabbitmq.client import QueueClient, SemaphoreClient
from suites.rabbitmq.db import RabbitDB


def queue_workload(opts) -> Dict[str, Any]:
    wl = queue_wl.workload()
    return {**wl, "client": QueueClient()}


def mutex_workload(opts) -> Dict[str, Any]:
    """Each process alternates acquire/release
    (the reference's semaphore client drives exactly this shape)."""
    g = gen.each_thread(gen.cycle(gen.lift([
        {"f": "acquire"}, {"f": "release"}])))
    return {"client": SemaphoreClient(),
            "generator": gen.stagger(1 / 2, g),
            "checker": linearizable(get_model("mutex"),
                                    opts.get("algorithm"))}


WORKLOADS = {"queue": queue_workload, "mutex": mutex_workload}


def rabbitmq_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="rabbitmq", db=RabbitDB(),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, rabbitmq_test, WORKLOADS)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(rabbitmq_test, WORKLOADS,
                         prog="jepsen-tpu-rabbitmq",
                         default_workload="queue"))
