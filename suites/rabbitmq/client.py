"""RabbitMQ workload clients.

Parity: rabbitmq/src/jepsen/rabbitmq.clj:103-175 (QueueClient: publish
with confirms, basic.get auto-ack dequeue, drain loop) and 177-255
(Semaphore: one message as the mutex token; acquire = unacked basic.get,
release = basic.reject with requeue).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.amqp import AmqpClient, AmqpError
from jepsen_tpu.history import FAIL, INFO, OK, Op

QUEUE = "jepsen.queue"
SEM_QUEUE = "jepsen.semaphore"
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)


def connect(test, node) -> AmqpClient:
    return AmqpClient(node, port=int(test.get("db_port", 5672)))


class QueueClient(jclient.Client):
    def __init__(self, conn: Optional[AmqpClient] = None,
                 node: Optional[str] = None):
        self.conn = conn
        self.node = node

    def open(self, test, node):
        c = QueueClient(connect(test, node), node)
        # confirms must be on for the WORKER connection: setup() only runs
        # on throwaway per-node clients, and an unconfirmed publish
        # reported OK would fabricate data-loss verdicts
        c.conn.queue_declare(QUEUE, durable=True)
        c.conn.confirm_select()
        return c

    def setup(self, test):
        self.conn.queue_declare(QUEUE, durable=True)
        self.conn.confirm_select()

    def _reconnect(self, test):
        """The reference opens a fresh channel per op (with-ch,
        rabbitmq.clj:119-125); we reconnect lazily after failures."""
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.conn = connect(test, self.node)
            self.conn.confirm_select()
        except Exception:  # noqa: BLE001 — node may be down; retry next op
            pass

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _dequeue(self, op: Op) -> Op:
        # auto-ack: a crash after the get loses the message honestly
        # (rabbitmq.clj:106-117's dequeue semantics)
        got = self.conn.get(QUEUE, no_ack=True)
        if got is None:
            return op.with_(type=FAIL, error="empty")
        _tag, body = got
        return op.with_(type=OK, value=json.loads(body))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                ok = self.conn.publish(QUEUE,
                                       json.dumps(op.value).encode())
                return op.with_(type=OK if ok else FAIL)
            if op.f == "dequeue":
                return self._dequeue(op)
            if op.f == "drain":
                # Messages are consumed with no_ack=True: once fetched they
                # are gone from the queue, so an error mid-drain must NOT
                # discard what was already collected (the queue checker would
                # report false data loss).  The reference's drain! always
                # completes :ok with the accumulated values
                # (rabbitmq.clj:119-131, dequeue! converts errors inside).
                out = []
                while True:
                    try:
                        r = self._dequeue(op)
                    except (AmqpError, *NET_ERRORS) as e:
                        self._reconnect(test)
                        return op.with_(type=OK, value=out, error=str(e))
                    if r.type != OK:
                        return op.with_(type=OK, value=out)
                    out.append(r.value)
            raise ValueError(op.f)
        except (AmqpError, *NET_ERRORS) as e:
            self._reconnect(test)
            if op.f == "dequeue":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))


class SemaphoreClient(jclient.Client):
    """One persistent message is the lock token (rabbitmq.clj:177-255)."""

    _seed_lock = threading.Lock()
    _seeded = False

    def __init__(self, conn: Optional[AmqpClient] = None,
                 node: Optional[str] = None):
        self.conn = conn
        self.node = node
        self.tag: Optional[int] = None
        self.tag_lock = threading.Lock()

    def open(self, test, node):
        return SemaphoreClient(connect(test, node), node)

    def setup(self, test):
        self.conn.queue_declare(SEM_QUEUE, durable=True)
        with SemaphoreClient._seed_lock:
            if not SemaphoreClient._seeded:
                self.conn.confirm_select()
                self.conn.queue_purge(SEM_QUEUE)
                if not self.conn.publish(SEM_QUEUE, b""):
                    raise RuntimeError(
                        "couldn't enqueue initial semaphore message")
                SemaphoreClient._seeded = True

    def teardown(self, test):
        SemaphoreClient._seeded = False

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _reopen(self, test):
        # dropping the connection requeues any unacked token server-side
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        self.tag = None
        try:
            self.conn = connect(test, self.node)
        except Exception:  # noqa: BLE001 — node may be down
            pass

    def invoke(self, test, op: Op) -> Op:
        with self.tag_lock:
            try:
                if op.f == "acquire":
                    if self.tag is not None:
                        return op.with_(type=FAIL, error="already-held")
                    got = self.conn.get(SEM_QUEUE, no_ack=False)
                    if got is None:
                        return op.with_(type=FAIL)
                    self.tag = got[0]
                    return op.with_(type=OK)
                if op.f == "release":
                    if self.tag is None:
                        return op.with_(type=FAIL, error="not-held")
                    tag, self.tag = self.tag, None
                    try:
                        self.conn.reject(tag, requeue=True)
                    except (AmqpError, *NET_ERRORS):
                        # release succeeds either way: a broken channel
                        # requeues the unacked token (rabbitmq.clj:232-254)
                        self._reopen(test)
                    return op.with_(type=OK)
                raise ValueError(op.f)
            except (AmqpError, *NET_ERRORS) as e:
                self._reopen(test)
                return op.with_(type=FAIL, error=str(e))
