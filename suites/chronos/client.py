"""Chronos client: submit ISO8601 repeating jobs; read run records.

Parity: chronos/src/jepsen/chronos.clj:86-190 — add-job posts an
iso8601 job whose command logs its name/start/end into a tempfile under
job-dir; read collects those files from every node over the control
plane and parses them into run records {node, name, start, end}.
"""

from __future__ import annotations

import socket
import time
import urllib.error
from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu import control
from jepsen_tpu.clients.http import HttpClient, HttpError
from jepsen_tpu.history import FAIL, INFO, OK, Op

from suites.chronos.db import JOB_DIR, PORT

NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


def iso8601(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


def parse_time(s: str) -> Optional[float]:
    """date -u -Ins output (commas normalized, chronos.clj:143-149)."""
    if not s:
        return None
    s = s.replace(",", ".")
    base, _, rest = s.partition(".")
    try:
        t = time.mktime(time.strptime(base, "%Y-%m-%dT%H:%M:%S")) \
            - time.timezone
        frac = rest.split("+")[0].split("Z")[0]
        return t + (float(f"0.{frac}") if frac else 0.0)
    except ValueError:
        return None


def job_json(job: Dict[str, Any]) -> Dict[str, Any]:
    """chronos.clj:119-133's job->json: the command logs name and
    timestamps into a tempfile."""
    command = (f"MEW=$(mktemp -p {JOB_DIR}); "
               f"echo \"{job['name']}\" >> $MEW; "
               f"date -u -Ins >> $MEW; "
               f"sleep {job['duration']}; "
               f"date -u -Ins >> $MEW;")
    return {"name": str(job["name"]),
            "command": command,
            "schedule": (f"R{job['count']}/{iso8601(job['start'])}"
                         f"/PT{job['interval']}S"),
            "scheduleTimeZone": "UTC",
            "owner": "jepsen@jepsen.io",
            "epsilon": f"PT{job['epsilon']}S",
            "mem": 1, "disk": 1, "cpus": 0.001, "async": False}


def read_runs(test) -> List[Dict[str, Any]]:
    """Collect and parse every run file from every node
    (chronos.clj:151-170)."""
    def per_node(t, node):
        s = control.session(t, node)
        files = s.exec("sh", "-c",
                       f"ls {JOB_DIR} 2>/dev/null || true").split()
        out = []
        for f in files:
            body = s.exec("sh", "-c", f"cat {JOB_DIR}{f} || true")
            lines = body.split("\n")
            if not lines or not lines[0].strip():
                continue
            out.append({"node": node,
                        "name": int(lines[0]),
                        "start": parse_time(lines[1].strip()
                                            if len(lines) > 1 else ""),
                        "end": parse_time(lines[2].strip()
                                          if len(lines) > 2 else "")})
        return out

    runs: List[Dict[str, Any]] = []
    for vals in control.on_nodes(test, per_node).values():
        runs.extend(vals)
    return [r for r in runs if r["start"] is not None]


class ChronosClient(jclient.Client):
    def __init__(self, node: Optional[str] = None):
        self.node = node

    def open(self, test, node):
        return ChronosClient(node)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add-job":
                c = HttpClient(self.node,
                               int(test.get("db_port", PORT)),
                               timeout=20.0)
                c.post("/scheduler/iso8601", job_json(op.value))
                return op.with_(type=OK)
            if op.f == "read":
                runs = read_runs(test)
                return op.with_(type=OK, value=runs,
                                # lint: disable=CONC01(chronos protocol wall-clock read time)
                                extra={"read_time": time.time()})
            raise ValueError(op.f)
        except (HttpError, *NET_ERRORS) as e:
            return op.with_(type=FAIL if op.f == "read" else INFO,
                            error=str(e)[:200])
