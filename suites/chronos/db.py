"""Chronos + Mesos + Zookeeper install.

Parity: chronos/src/jepsen/chronos.clj:40-85 (chronos deb over the
mesosphere layer, schedule_horizon=1, job-dir) and jepsen.mesosphere
(zookeeper + mesos master/slave on every node).
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

PORT = 4400  # chronos.clj:25: "docs say 8080 but the package binds 4400"
JOB_DIR = "/tmp/chronos-test/"
MESOS_MASTER_PORT = 5050


def zk_connect(test) -> str:
    return "zk://" + ",".join(f"{n}:2181" for n in test["nodes"]) \
        + "/mesos"


class ChronosDB(jdb.DB, jdb.Kill, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "dpkg-query -l chronos >/dev/null 2>&1 || "
               "apt-get install -y zookeeper mesos chronos")
        # mesos zk coordination + quorum
        cu.write_file(s, zk_connect(test), "/etc/mesos/zk")
        cu.write_file(s, str(len(test["nodes"]) // 2 + 1),
                      "/etc/mesos-master/quorum")
        # lower the scheduler horizon (chronos.clj:40-45)
        s.exec("mkdir", "-p", "/etc/chronos/conf", JOB_DIR)
        cu.write_file(s, "1", "/etc/chronos/conf/schedule_horizon")
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=240)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        for svc in ("chronos", "mesos-master", "mesos-slave"):
            s.exec("sh", "-c", f"service {svc} stop || true")
        cu.grepkill(s, "chronos")
        s.exec("rm", "-rf", JOB_DIR)

    def start(self, test, node):
        s = session(test, node).sudo()
        for svc in ("zookeeper", "mesos-master", "mesos-slave",
                    "chronos"):
            s.exec("sh", "-c",
                   f"service {svc} status >/dev/null 2>&1 || "
                   f"service {svc} start")

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "chronos")
        cu.grepkill(s, "mesos-master")

    def log_files(self, test, node) -> List[str]:
        return ["/var/log/mesos/mesos-master.INFO",
                "/var/log/messages"]
