"""Chronos schedule checker: match actual runs to expected targets.

Parity: chronos/src/jepsen/chronos/checker.clj — each job implies a
sequence of target windows [start, start+epsilon+forgiveness]; every
target that must have begun before the final read needs a distinct
completed run starting inside its window.  The reference solves the
general case with the loco constraint solver (checker.clj:117-190); for
point-runs-in-interval-targets, greedy matching on targets sorted by
deadline (earliest-feasible run first) finds a perfect matching whenever
one exists, so no solver dependency is needed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History, OK

EPSILON_FORGIVENESS = 5.0  # checker.clj:26-28


def job_targets(read_time: float, job: Dict[str, Any]) -> List[Tuple]:
    """[(start, deadline)] for every run that must have begun by the
    read (checker.clj:30-47)."""
    finish = read_time - job["epsilon"] - job["duration"]
    out = []
    t = job["start"]
    for _ in range(job["count"]):
        if t >= finish:
            break
        out.append((t, t + job["epsilon"] + EPSILON_FORGIVENESS))
        t += job["interval"]
    return out


def match_targets(targets: List[Tuple], run_starts: List[float]):
    """Greedy bipartite matching: targets by deadline, each takes the
    earliest unused feasible run.  → (solution, unmatched_targets)."""
    runs = sorted(run_starts)
    used = [False] * len(runs)
    solution = []
    unmatched = []
    for start, deadline in sorted(targets, key=lambda t: t[1]):
        pick = None
        for i, r in enumerate(runs):
            if used[i] or r < start:
                continue
            if r > deadline:
                break
            pick = i
            break
        if pick is None:
            unmatched.append((start, deadline))
        else:
            used[pick] = True
            solution.append(((start, deadline), runs[pick]))
    return solution, unmatched


class ChronosChecker(Checker):
    """Checks every submitted job's runs against its schedule
    (checker.clj:192-240's solution map)."""

    def check(self, test, history: History, opts=None):
        jobs = [op.value for op in history
                if op.f == "add-job" and op.type == OK]
        reads = [op for op in history
                 if op.f == "read" and op.type == OK]
        if not reads:
            return {"valid": UNKNOWN, "error": "no final read"}
        read = reads[-1]
        read_time = read.extra.get("read_time") or (read.time or 0) / 1e9
        runs = read.value or []

        by_name: Dict[Any, List[Dict]] = {}
        for r in runs:
            by_name.setdefault(r["name"], []).append(r)

        results = {}
        valid = True
        extra_total, incomplete_total = 0, 0
        for job in jobs:
            jruns = by_name.get(job["name"], [])
            complete = [r for r in jruns if r.get("end") is not None]
            incomplete = [r for r in jruns if r.get("end") is None]
            targets = job_targets(read_time, job)
            sol, unmatched = match_targets(
                targets, [r["start"] for r in complete])
            ok = not unmatched
            valid = valid and ok
            extra_total += len(complete) - len(sol)
            incomplete_total += len(incomplete)
            results[job["name"]] = {
                "valid": ok,
                "targets": len(targets),
                "solved": len(sol),
                "unmatched": unmatched[:8],
                "extra-runs": len(complete) - len(sol),
                "incomplete-runs": len(incomplete)}
        return {"valid": valid if jobs else UNKNOWN,
                "job-count": len(jobs),
                "extra-runs": extra_total,
                "incomplete-runs": incomplete_total,
                "jobs": results}
