"""Chronos suite (reference: chronos/ — Mesos task scheduler: do
scheduled jobs actually run when promised?)."""
