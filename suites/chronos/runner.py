"""Chronos suite CLI.

Parity: chronos/src/jepsen/chronos.clj:174-270 — random repeating jobs
(non-overlapping intervals so runs can't collide), a resurrection-hub
nemesis that restarts crashed mesos/chronos daemons alongside
random-halves partitions, and a final read of the run logs checked
against the schedule.
"""

from __future__ import annotations

import random
import time
from typing import Any, Dict

from jepsen_tpu import control
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as jnem
from jepsen_tpu.nemesis import combined
from jepsen_tpu.nemesis.partition import (Partitioner,
                                          random_halves_grudge)

from suites import common
from suites.chronos.checker import EPSILON_FORGIVENESS, ChronosChecker
from suites.chronos.client import ChronosClient
from suites.chronos.db import ChronosDB


class ResurrectionHub(jnem.Nemesis):
    """Restart every mesos/chronos daemon (chronos.clj:220-240's
    resurrection-hub) and route partition ops to the partitioner."""

    def __init__(self, db: ChronosDB):
        self.db = db
        self.part = Partitioner(random_halves_grudge)

    def setup(self, test):
        self.part = self.part.setup(test)
        return self

    def invoke(self, test, op):
        if op.f == "resurrect":
            def revive(t, node):
                self.db.start(t, node)
                return "resurrected"
            return op.with_(type="info",
                            value=control.on_nodes(test, revive))
        return self.part.invoke(test, op)

    def teardown(self, test):
        self.part.teardown(test)

    def fs(self):
        return ["resurrect", *self.part.fs()]


def hub_package(opts: Dict[str, Any]) -> combined.Package:
    db = opts.get("_db") or ChronosDB()
    interval = float(opts.get("interval", 30.0))
    g = gen.stagger(interval, gen.cycle(gen.lift([
        {"f": "start-partition", "type": "info"},
        {"f": "stop-partition", "type": "info"},
        {"f": "resurrect", "type": "info"}])))
    return combined.Package(
        nemesis=ResurrectionHub(db), generator=g,
        final_generator=[{"f": "stop-partition", "type": "info"},
                         {"f": "resurrect", "type": "info"}])


NEMESES = dict(common.STANDARD_NEMESES)
NEMESES["hub"] = hub_package


def jobs_workload(opts) -> Dict[str, Any]:
    """Random non-overlapping repeating jobs (chronos.clj:174-196)."""
    counter = iter(range(1, 10 ** 9))
    head_start = float(opts.get("head_start", 10.0))

    def one():
        duration = random.randint(0, 9)
        epsilon = 10 + random.randint(0, 19)
        interval = (1 + duration + epsilon + EPSILON_FORGIVENESS
                    + random.randint(0, 29))
        return {"f": "add-job",
                "value": {"name": next(counter),
                          # lint: disable=CONC01(chronos schedules jobs by wall clock)
                          "start": time.time() + head_start,
                          "count": 1 + random.randint(0, 98),
                          "duration": duration,
                          "epsilon": epsilon,
                          "interval": int(interval)}}

    return {"client": ChronosClient(),
            "generator": gen.stagger(30.0, gen.FnGen(one)),
            "final_generator": gen.once({"f": "read"}),
            "checker": ChronosChecker()}


WORKLOADS = {"jobs": jobs_workload}


def chronos_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="chronos", db=ChronosDB(),
                             workloads=WORKLOADS, nemeses=NEMESES)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, chronos_test, WORKLOADS, NEMESES)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(chronos_test, WORKLOADS, NEMESES,
                         prog="jepsen-tpu-chronos"))
