"""CrateDB install/start.

Parity: crate/src/jepsen/crate/core.clj's db — release tarball, crate
service user (Crate refuses to run as root), unicast discovery over the
test nodes, data/log wipe on teardown.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "5.4.3"
URL = (f"https://cdn.crate.io/downloads/releases/cratedb/x64_linux/"
       f"crate-{VERSION}.tar.gz")
DIR = "/opt/crate"
DATA = "/opt/crate/data"
PIDFILE = f"{DIR}/crate.pid"  # written by the crate service user
LOGFILE = "/var/log/crate.log"
PG_PORT = 5432
HTTP_PORT = 4200
TRANSPORT_PORT = 4300
USER = "crate"


class CrateDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        cu.ensure_user(s, USER)
        s.exec("mkdir", "-p", DATA)
        s.exec("chown", "-R", f"{USER}:{USER}", DIR)
        self.start(test, node)
        cu.await_tcp_port(s, PG_PORT, timeout_s=120)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.stop_daemon(s, PIDFILE)
        s.exec("rm", "-rf", DATA, LOGFILE)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        seeds = ",".join(f"{n}:{TRANSPORT_PORT}" for n in test["nodes"])
        masters = ",".join(test["nodes"])
        cu.start_daemon(
            s, f"{DIR}/bin/crate",
            f"-Cnode.name={node}",
            f"-Cnetwork.host=0.0.0.0",
            f"-Cpath.data={DATA}",
            f"-Cpsql.port={PG_PORT}",
            f"-Chttp.port={HTTP_PORT}",
            f"-Ctransport.tcp.port={TRANSPORT_PORT}",
            f"-Cdiscovery.seed_hosts={seeds}",
            f"-Ccluster.initial_master_nodes={masters}",
            pidfile=PIDFILE, logfile=LOGFILE, user=USER)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "crate")
        s.exec("rm", "-f", PIDFILE)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "crate", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "crate", "CONT")

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
