"""crate suite — CrateDB lost-updates / dirty-read / version-divergence.

Parity: crate/src/jepsen/crate/{core,lost_updates,dirty_read,
version_divergence}.clj.  The reference drives CrateDB through the
Elasticsearch transport client; CrateDB also speaks the Postgres wire
protocol (psql.port 5432), which is the TPU-era transport here.
"""

from suites.crate.runner import WORKLOADS, all_tests, crate_test  # noqa: F401
