"""crate suite CLI.

Parity: crate/src/jepsen/crate/{lost_updates,dirty_read,
version_divergence}.clj — lost-updates (RMW set-add on one row, set
checker), dirty-read (failed writers' values must stay invisible), and the
standard SQL registry for register/set coverage.

    python -m suites.crate.runner test --node n1 ... --workload lost-updates
"""

from __future__ import annotations

from jepsen_tpu.clients.pgwire import PgClient

from suites import sqlextra, sqlsuite
from suites.crate.db import PG_PORT, CrateDB


def conn(node, test):
    return PgClient(node,
                    port=int(test.get("db_port", PG_PORT)),
                    user=test.get("db_user", "crate"),
                    database=test.get("db_name", "doc")).connect()


EXTRA = {
    "lost-updates": lambda opts: sqlextra.lost_updates_workload(conn),
    "dirty-read": lambda opts: sqlextra.dirty_reads_workload(conn),
}

WORKLOADS, crate_test, all_tests, main = sqlsuite.make_suite(
    "crate", CrateDB(), conn, extra_workloads=EXTRA,
    default_workload="lost-updates")


if __name__ == "__main__":
    import sys
    sys.exit(main())
