"""Database test suites — consumers of the whole framework.

Parity: the reference's per-database projects (zookeeper/, consul/, tidb/,
etc. — SURVEY.md §2.5): each suite provides a DB (install/start/stop),
clients, a workload registry, nemesis options, and a CLI entry point.
"""
