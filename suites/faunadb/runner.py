"""FaunaDB suite CLI.

Parity: faunadb/src/jepsen/faunadb/runner.clj:30-41's workload registry —
register, bank, set, monotonic, pages (paginated index reads racing
grouped adds), and multimonotonic (componentwise-monotonic register
vectors); g2/internal are covered by the shared transactional kits.
bank-index's serialized-indices flag is the index's `serialized`
option.
"""

from __future__ import annotations

from typing import Any, Dict, List

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, SetChecker
from jepsen_tpu.history import History, OK
from jepsen_tpu.workloads import bank as bank_wl
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.faunadb import client as fc
from suites.faunadb.db import FaunaDB


class MonotonicChecker(Checker):
    """Per-process counter reads must never go backwards
    (monotonic.clj's checker)."""

    def check(self, test, history: History, opts=None):
        last: Dict[Any, int] = {}
        bad = []
        for op in history:
            if op.type == OK and op.f in ("read", "inc") \
                    and op.value is not None:
                prev = last.get(op.process)
                if prev is not None and op.value < prev:
                    bad.append({**op.to_dict(), "prev": prev})
                last[op.process] = op.value
        return {"valid": not bad, "nonmonotonic": bad[:16]}


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 100)),
        threads_per_key=2)
    return {**wl, "client": fc.RegisterClient()}


def bank_workload(opts) -> Dict[str, Any]:
    wl = bank_wl.workload()
    return {**wl, "client": fc.BankClient()}


def set_workload(opts) -> Dict[str, Any]:
    box = {"n": 0}

    def add():
        v = box["n"]
        box["n"] += 1
        return {"f": "add", "value": v}

    def final_read():
        # the read probes refs [0, bound): it must track how far the
        # adds actually got, or acknowledged adds read as lost
        return {"f": "read", "value": box["n"]}

    return {"client": fc.SetClient(),
            "generator": gen.stagger(1 / 20, gen.FnGen(add)),
            "final_generator": gen.once(gen.FnGen(final_read)),
            "checker": SetChecker()}


def monotonic_workload(opts) -> Dict[str, Any]:
    g = gen.mix([gen.repeat({"f": "inc"}),
                 gen.repeat({"f": "read"})])
    return {"client": fc.MonotonicClient(),
            "generator": gen.stagger(1 / 20, g),
            "checker": MonotonicChecker()}


class PagesChecker(Checker):
    """Every ok read must be a union of complete add-groups: no torn
    groups, no duplicates (pages.clj:93-145)."""

    def check(self, test, history: History, opts=None):
        invoked, failed = {}, set()
        for op in history:
            if op.f != "add":
                continue
            if op.type == "invoke":
                for v in op.value:
                    invoked[v] = frozenset(op.value)
            elif op.type == "fail":
                failed.update(op.value)
        errs = []
        for op in history:
            if op.f != "read" or op.type != OK:
                continue
            seen = op.value or []
            if len(set(seen)) != len(seen):
                errs.append({**op.to_dict(), "error": "duplicates"})
                continue
            sset = set(seen)
            for v in seen:
                group = invoked.get(v)
                if group is None:
                    errs.append({**op.to_dict(),
                                 "error": f"phantom element {v}"})
                    break
                if v in failed:
                    errs.append({**op.to_dict(),
                                 "error": f"failed add {v} visible"})
                    break
                if not group <= sset:
                    errs.append({**op.to_dict(),
                                 "error": f"torn group {sorted(group)}"})
                    break
        return {"valid": not errs, "errors": errs[:16]}


class MultiMonotonicChecker(Checker):
    """Registers are increment-only, so two invariants hold
    (multimonotonic.clj:152-253): observed vectors must be mutually
    comparable (no fractured snapshots — one register ahead, another
    behind), and each process's successive reads must never go backwards
    in any component (no time-travel/stale reads)."""

    def check(self, test, history: History, opts=None):
        reads = [(op.process, tuple(op.value)) for op in history
                 if op.f == "read" and op.type == OK and op.value]
        # temporal: per-process monotonicity in completion order
        last: Dict[Any, tuple] = {}
        stale = []
        for proc, st in reads:
            prev = last.get(proc)
            if prev is not None and any(x < y
                                        for x, y in zip(st, prev)):
                stale.append({"process": proc, "earlier": list(prev),
                              "later": list(st)})
            last[proc] = st
        # spatial: all observed states form a chain (checking successive
        # sum-sorted pairs is complete: a total order exists iff every
        # such pair is componentwise ordered)
        ordered = sorted({st for _, st in reads}, key=sum)
        frac = []
        for a, b in zip(ordered, ordered[1:]):
            if not all(x <= y for x, y in zip(a, b)):
                frac.append({"earlier": list(a), "later": list(b)})
        return {"valid": not (stale or frac), "states": len(ordered),
                "nonmonotonic": stale[:16], "incomparable": frac[:16]}


def pages_workload(opts) -> Dict[str, Any]:
    counter = iter(range(0, 10 ** 9, 3))

    def add():
        base = next(counter)
        return {"f": "add", "value": [base, base + 1, base + 2]}

    g = gen.mix([gen.FnGen(add), gen.repeat({"f": "read"})])
    return {"client": fc.PagesClient(
                serialized=bool(opts.get("serialized_indices", True))),
            "generator": gen.stagger(1 / 10, g),
            "checker": PagesChecker()}


def multimonotonic_workload(opts) -> Dict[str, Any]:
    import random as _r
    g = gen.mix([
        gen.FnGen(lambda: {"f": "inc",
                           "value": _r.randrange(
                               fc.MultiRegisterClient.N)}),
        gen.repeat({"f": "read"})])
    return {"client": fc.MultiRegisterClient(),
            "generator": gen.stagger(1 / 20, g),
            "checker": MultiMonotonicChecker()}


WORKLOADS = {"register": register_workload, "bank": bank_workload,
             "set": set_workload, "monotonic": monotonic_workload,
             "pages": pages_workload,
             "multimonotonic": multimonotonic_workload}


def faunadb_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    t = common.build_test(opts, suite="faunadb", db=FaunaDB(),
                          workloads=WORKLOADS)
    if opts.get("workload") == "bank":
        t["bank"] = {"accounts": list(range(8)),
                     "total_amount": int(opts.get("total_amount", 100))}
    # set reads probe refs up to the add counter's bound
    t["set_read_upper"] = int(opts.get("set_read_upper", 2000))
    return t


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, faunadb_test, WORKLOADS)


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=100)
    parser.add_argument("--total-amount", type=int, default=100)
    parser.add_argument("--no-serialized-indices", dest="serialized_indices",
                        action="store_false", default=True,
                        help="build the pages index non-serialized "
                             "(runner.clj:46-52's sweep dimension)")


if __name__ == "__main__":
    import sys
    sys.exit(common.main(faunadb_test, WORKLOADS,
                         prog="jepsen-tpu-faunadb", extra_opts=_extra,
                         default_workload="register"))
