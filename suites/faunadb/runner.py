"""FaunaDB suite CLI.

Parity: faunadb/src/jepsen/faunadb/runner.clj:30-41's workload registry —
register, bank, set, monotonic implemented here (g2 / internal /
multimonotonic / pages are covered by the shared transactional kits or
queued for a later pass; bank-index's serialized-indices flag becomes
set's strong-read option), plus runner.clj:43-60's workload-option sweep
matrices.
"""

from __future__ import annotations

from typing import Any, Dict, List

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, SetChecker
from jepsen_tpu.history import History, OK
from jepsen_tpu.workloads import bank as bank_wl
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.faunadb import client as fc
from suites.faunadb.db import FaunaDB


class MonotonicChecker(Checker):
    """Per-process counter reads must never go backwards
    (monotonic.clj's checker)."""

    def check(self, test, history: History, opts=None):
        last: Dict[Any, int] = {}
        bad = []
        for op in history:
            if op.type == OK and op.f in ("read", "inc") \
                    and op.value is not None:
                prev = last.get(op.process)
                if prev is not None and op.value < prev:
                    bad.append({**op.to_dict(), "prev": prev})
                last[op.process] = op.value
        return {"valid": not bad, "nonmonotonic": bad[:16]}


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 100)),
        threads_per_key=2)
    return {**wl, "client": fc.RegisterClient()}


def bank_workload(opts) -> Dict[str, Any]:
    wl = bank_wl.workload()
    return {**wl, "client": fc.BankClient()}


def set_workload(opts) -> Dict[str, Any]:
    box = {"n": 0}

    def add():
        v = box["n"]
        box["n"] += 1
        return {"f": "add", "value": v}

    def final_read():
        # the read probes refs [0, bound): it must track how far the
        # adds actually got, or acknowledged adds read as lost
        return {"f": "read", "value": box["n"]}

    return {"client": fc.SetClient(),
            "generator": gen.stagger(1 / 20, gen.FnGen(add)),
            "final_generator": gen.once(gen.FnGen(final_read)),
            "checker": SetChecker()}


def monotonic_workload(opts) -> Dict[str, Any]:
    g = gen.mix([gen.repeat({"f": "inc"}),
                 gen.repeat({"f": "read"})])
    return {"client": fc.MonotonicClient(),
            "generator": gen.stagger(1 / 20, g),
            "checker": MonotonicChecker()}


WORKLOADS = {"register": register_workload, "bank": bank_workload,
             "set": set_workload, "monotonic": monotonic_workload}


def faunadb_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    t = common.build_test(opts, suite="faunadb", db=FaunaDB(),
                          workloads=WORKLOADS)
    if opts.get("workload") == "bank":
        t["bank"] = {"accounts": list(range(8)),
                     "total_amount": int(opts.get("total_amount", 100))}
    # set reads probe refs up to the add counter's bound
    t["set_read_upper"] = int(opts.get("set_read_upper", 2000))
    return t


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, faunadb_test, WORKLOADS)


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=100)
    parser.add_argument("--total-amount", type=int, default=100)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(faunadb_test, WORKLOADS,
                         prog="jepsen-tpu-faunadb", extra_opts=_extra,
                         default_workload="register"))
