"""FaunaDB workload clients — every op is one FQL transaction.

Parity: faunadb/src/jepsen/faunadb/register.clj (per-key register
instances, CAS via if/equals/abort), bank.clj:43-140 (account instances,
transfers as let + balance check + two updates), set.clj (element
instances, strong read = map get over refs), monotonic.clj (a register
incremented transactionally; reads return [ts, value] pairs that must be
monotonic together).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients import fauna as fq
from jepsen_tpu.clients.fauna import (AbortError, FaunaClient, FaunaError,
                                      NET_ERRORS)
from jepsen_tpu.history import FAIL, INFO, OK, Op

REGISTERS = "registers"
ACCOUNTS = "accounts"
ELEMENTS = "elements"
COUNTERS = "counters"


def connect(test, node) -> FaunaClient:
    return FaunaClient(node, int(test.get("db_port", fq.PORT)),
                       scheme=test.get("db_scheme", "http"))


class _FaunaBase(jclient.Client):
    CLASS: str = ""

    def __init__(self, conn: Optional[FaunaClient] = None):
        self.conn = conn

    def open(self, test, node):
        return type(self)(connect(test, node))

    def setup(self, test):
        try:
            self.conn.query(fq.create_class(self.CLASS))
        except (FaunaError, *NET_ERRORS):
            pass  # exists

    def _convert(self, op: Op, e: Exception) -> Op:
        if isinstance(e, AbortError):
            return op.with_(type=FAIL, error="abort")
        if op.f == "read":
            return op.with_(type=FAIL, error=str(e)[:200])
        return op.with_(type=INFO, error=str(e)[:200])


def _value_of(r, default=None):
    return fq.select(["data", "value"], fq.get(r), default=default)


class RegisterClient(_FaunaBase):
    CLASS = REGISTERS

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        r = fq.ref(self.CLASS, k)
        try:
            if op.f == "read":
                val = self.conn.query(
                    fq.if_(fq.exists(r), _value_of(r), None))
                return op.with_(type=OK, value=(k, val))
            if op.f == "write":
                self.conn.query(
                    fq.if_(fq.exists(r),
                           fq.update(r, {"value": v}),
                           fq.create(self.CLASS, k, {"value": v})))
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                self.conn.query(
                    fq.if_(fq.equals(
                        fq.if_(fq.exists(r), _value_of(r), None), old),
                        fq.update(r, {"value": new}),
                        fq.abort("cas failed")))
                return op.with_(type=OK)
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)


class BankClient(_FaunaBase):
    CLASS = ACCOUNTS

    def setup(self, test):
        super().setup(test)
        wl = test.get("bank", {})
        accounts = wl.get("accounts", list(range(8)))
        total = wl.get("total_amount", 100)
        per = total // len(accounts)
        for i, a in enumerate(accounts):
            amt = per + (total - per * len(accounts) if i == 0 else 0)
            try:
                self.conn.query(fq.if_(
                    fq.exists(fq.ref(self.CLASS, a)), None,
                    fq.create(self.CLASS, a, {"balance": amt})))
            except (FaunaError, *NET_ERRORS):
                pass

    def invoke(self, test, op: Op) -> Op:
        accounts = test.get("bank", {}).get("accounts", list(range(8)))
        try:
            if op.f == "read":
                vals = self.conn.query(
                    [fq.select(["data", "balance"],
                               fq.get(fq.ref(self.CLASS, a)))
                     for a in accounts])
                return op.with_(type=OK,
                                value=dict(zip(accounts, vals)))
            if op.f == "transfer":
                v = op.value
                frm = fq.ref(self.CLASS, v["from"])
                to = fq.ref(self.CLASS, v["to"])
                bal = fq.select(["data", "balance"], fq.get(frm))
                self.conn.query(fq.let(
                    {"b": bal},
                    fq.if_(fq.lt(fq.var("b"), v["amount"]),
                           fq.abort("insufficient funds"),
                           fq.do(
                               fq.update(frm, {"balance": fq.subtract(
                                   fq.var("b"), v["amount"])}),
                               fq.let({"b2": fq.select(
                                   ["data", "balance"], fq.get(to))},
                                   fq.update(to, {"balance": fq.add(
                                       fq.var("b2"), v["amount"])}))))))
                return op.with_(type=OK)
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)


class SetClient(_FaunaBase):
    CLASS = ELEMENTS

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.query(fq.create(self.CLASS, op.value,
                                          {"value": op.value}))
                return op.with_(type=OK)
            if op.f == "read":
                # strong read: one txn over all candidate refs
                # (set.clj's strong-read mode); the generator stamps the
                # add counter's bound into the op
                n = op.value if isinstance(op.value, int) \
                    else test.get("set_read_upper", 10_000)
                vals = self.conn.query(
                    [fq.if_(fq.exists(fq.ref(self.CLASS, i)),
                            _value_of(fq.ref(self.CLASS, i)), None)
                     for i in range(n)])
                return op.with_(type=OK,
                                value=sorted(v for v in vals
                                             if v is not None))
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)


class MonotonicClient(_FaunaBase):
    """A counter incremented by 1; reads return [register value] so the
    checker can demand that successive reads never go backwards
    (monotonic.clj)."""

    CLASS = COUNTERS
    KEY = 0

    def setup(self, test):
        super().setup(test)
        try:
            self.conn.query(fq.if_(
                fq.exists(fq.ref(self.CLASS, self.KEY)), None,
                fq.create(self.CLASS, self.KEY, {"value": 0})))
        except (FaunaError, *NET_ERRORS):
            pass

    def invoke(self, test, op: Op) -> Op:
        r = fq.ref(self.CLASS, self.KEY)
        try:
            if op.f == "inc":
                val = self.conn.query(fq.let(
                    {"v": _value_of(r)},
                    fq.do(fq.update(r, {"value": fq.add(fq.var("v"), 1)}),
                          fq.add(fq.var("v"), 1))))
                return op.with_(type=OK, value=val)
            if op.f == "read":
                return op.with_(type=OK, value=self.conn.query(
                    _value_of(r)))
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)


class PagesClient(_FaunaBase):
    """Grouped adds in one transaction; reads paginate the elements
    index in small pages while writes race (pages.clj:26-91): a read
    must be a union of complete add-groups.  ``serialized`` toggles the
    index's serialized flag — the reference's serialized-indices sweep
    dimension (runner.clj:46-52)."""

    CLASS = "pages"
    INDEX = "pages-values"
    PAGE_SIZE = 5

    def __init__(self, conn=None, serialized: bool = True):
        super().__init__(conn)
        self.serialized = serialized

    def open(self, test, node):
        return PagesClient(connect(test, node), self.serialized)

    def setup(self, test):
        super().setup(test)
        try:
            self.conn.query(fq.create_index(
                self.INDEX, self.CLASS, serialized=self.serialized))
        except (FaunaError, *NET_ERRORS):
            pass

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                group = op.value
                self.conn.query(fq.do(*[
                    fq.create(self.CLASS, v, {"value": v})
                    for v in group]))
                return op.with_(type=OK)
            if op.f == "read":
                out: List[int] = []
                after = None
                while True:
                    page = self.conn.query(
                        fq.paginate(fq.match(self.INDEX),
                                    self.PAGE_SIZE, after=after))
                    out.extend(page.get("data", []))
                    after = page.get("after")
                    if after is None:
                        break
                return op.with_(type=OK, value=out)
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)


class MultiRegisterClient(_FaunaBase):
    """Several registers; each increment bumps one register in its own
    transaction, reads snapshot all of them in one transaction
    (multimonotonic.clj:76-111).  Observed states must form a
    componentwise-monotonic chain."""

    CLASS = "multiregisters"
    N = 4

    def setup(self, test):
        super().setup(test)
        for i in range(self.N):
            try:
                self.conn.query(fq.if_(
                    fq.exists(fq.ref(self.CLASS, i)), None,
                    fq.create(self.CLASS, i, {"value": 0})))
            except (FaunaError, *NET_ERRORS):
                pass

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "inc":
                r = fq.ref(self.CLASS, op.value)
                self.conn.query(fq.update(r, {"value": fq.add(
                    fq.select(["data", "value"], fq.get(r)), 1)}))
                return op.with_(type=OK)
            if op.f == "read":
                vals = self.conn.query(
                    [fq.select(["data", "value"],
                               fq.get(fq.ref(self.CLASS, i)))
                     for i in range(self.N)])
                return op.with_(type=OK, value=vals)
            raise ValueError(op.f)
        except (AbortError, FaunaError, *NET_ERRORS) as e:
            return self._convert(op, e)
