"""FaunaDB Enterprise install.

Parity: faunadb/src/jepsen/faunadb/auto.clj — deb install from the
faunadb repo, faunadb.yml with the cluster's replicas and the shared
root key "secret", init on node 1 then join, log replication topology
(topology.clj's replica placement).
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.clients.fauna import PORT, SECRET
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

CONF = "/etc/faunadb.yml"
LOGFILE = "/var/log/faunadb/core.log"


def config(test, node) -> str:
    return (f"auth_root_key: {SECRET}\n"
            f"network_broadcast_address: {node}\n"
            f"network_listen_address: 0.0.0.0\n"
            f"network_coordinator_http_address: 0.0.0.0\n"
            f"storage_data_path: /var/lib/faunadb\n")


class FaunaDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "dpkg-query -l faunadb >/dev/null 2>&1 || "
               "apt-get install -y faunadb")
        cu.write_file(s, config(test, node), CONF)
        first = test["nodes"][0]
        if node == first:
            s.exec("faunadb-admin", "init")
        else:
            s.exec("faunadb-admin", "join", first)
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=300)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "faunadb")
        s.exec("sh", "-c", "rm -rf /var/lib/faunadb/* || true")

    def start(self, test, node):
        session(test, node).sudo().exec("service", "faunadb", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "faunadb")

    def pause(self, test, node):
        cu.grepkill(session(test, node).sudo(), "faunadb", signal="STOP")

    def resume(self, test, node):
        cu.grepkill(session(test, node).sudo(), "faunadb", signal="CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
