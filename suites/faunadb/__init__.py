"""FaunaDB suite (reference: faunadb/ — the largest reference suite:
register, bank, set, and monotonic workloads over single-query FQL
transactions)."""
