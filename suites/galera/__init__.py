"""galera suite — MariaDB Galera Cluster dirty-reads and bank.

Parity: galera/src/jepsen/{galera.clj,galera/dirty_reads.clj} — writers
race to set every row in one transaction while readers scan for values
from failed transactions (dirty_reads.clj:1-6).
"""

from suites.galera.runner import WORKLOADS, all_tests, galera_test  # noqa: F401
