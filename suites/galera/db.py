"""MariaDB Galera Cluster install/start.

Parity: galera/src/jepsen/galera.clj's db — mariadb + galera packages,
wsrep provider config with a gcomm:// address over the test nodes, first
node bootstraps the cluster, the rest join it.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

SQL_PORT = 3306
CONF = "/etc/mysql/conf.d/galera.cnf"
LOGFILE = "/var/log/mysql/error.log"
DATADIR = "/var/lib/mysql"


def cluster_address(test) -> str:
    return "gcomm://" + ",".join(test["nodes"])


def galera_conf(test, node) -> str:
    return f"""[mysqld]
bind-address=0.0.0.0
binlog_format=ROW
default-storage-engine=innodb
innodb_autoinc_lock_mode=2
wsrep_on=ON
wsrep_provider=/usr/lib/galera/libgalera_smm.so
wsrep_cluster_name=jepsen
wsrep_cluster_address={cluster_address(test)}
wsrep_node_name={node}
wsrep_node_address={node}
"""


class GaleraDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
               "-y", "mariadb-server", "galera-4", "rsync")
        s.exec("service", "mysql", "stop")
        cu.write_file(s, galera_conf(test, node), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, SQL_PORT, timeout_s=120)
        if node == test["nodes"][0]:
            s.exec("mysql", "-e",
                   "CREATE DATABASE IF NOT EXISTS jepsen; "
                   "CREATE USER IF NOT EXISTS 'jepsen'@'%' "
                   "IDENTIFIED BY 'jepsen'; "
                   "GRANT ALL ON jepsen.* TO 'jepsen'@'%'; "
                   "FLUSH PRIVILEGES;")

    def teardown(self, test, node):
        s = session(test, node).sudo()
        s.exec("bash", "-c", "service mysql stop || true")
        cu.grepkill(s, "mariadbd|mysqld")
        # drop workload state too, or the next run's tables start dirty
        s.exec("bash", "-c",
               f"rm -rf {DATADIR}/grastate.dat {DATADIR}/jepsen {LOGFILE}")

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        if node == test["nodes"][0]:
            # first node bootstraps a new cluster
            s.exec("bash", "-c",
                   "galera_new_cluster || service mysql start")
        else:
            s.exec("service", "mysql", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "mariadbd|mysqld")

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "mariadbd", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "mariadbd", "CONT")

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
