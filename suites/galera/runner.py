"""galera suite CLI — dirty-reads is the flagship workload.

Parity: galera/src/jepsen/galera/dirty_reads.clj (test- at 107) plus the
shared SQL registry (bank mirrors the reference's galera bank tests).

    python -m suites.galera.runner test --node n1 ... --workload dirty-reads
"""

from __future__ import annotations

from jepsen_tpu.clients.mysql import MysqlClient

from suites import sqlextra, sqlsuite
from suites.galera.db import SQL_PORT, GaleraDB


def conn(node, test):
    return MysqlClient(node,
                       port=int(test.get("db_port", SQL_PORT)),
                       user=test.get("db_user", "jepsen"),
                       password=test.get("db_password", "jepsen"),
                       database=test.get("db_name", "jepsen")).connect()


EXTRA = {"dirty-reads": lambda opts: sqlextra.dirty_reads_workload(conn)}

WORKLOADS, galera_test, all_tests, main = sqlsuite.make_suite(
    "galera", GaleraDB(), conn, extra_workloads=EXTRA,
    default_workload="dirty-reads")


if __name__ == "__main__":
    import sys
    sys.exit(main())
