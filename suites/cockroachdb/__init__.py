"""cockroachdb suite — register/bank/sets/monotonic/sequential/g2 + more.

Parity: cockroachdb/src/jepsen/cockroach.clj and cockroach/{bank,register,
sets,monotonic,sequential,comments,adya,nemesis}.clj — the reference's
largest-surface SQL suite, including its own Ubuntu OS layer
(cockroachdb/src/jepsen/os/ubuntu.clj) and clock-skew helpers.
"""

from suites.cockroachdb.runner import WORKLOADS, all_tests, cockroach_test  # noqa: F401
