"""cockroachdb suite CLI.

Parity: cockroachdb/src/jepsen/cockroach.clj's test registry — register,
bank, sets, monotonic, sequential, comments/adya (G2 anti-dependency
anomalies; covered by the g2/wr workloads here), plus the standard SQL
registry.  The reference's own clock nemeses (cockroach/nemesis.clj,
suite-local adjtime.c/bumptime.c) map to the framework clock package, whose
C helpers are compiled on the nodes (jepsen_tpu/nemesis/time.py).

    python -m suites.cockroachdb.runner test --node n1 ... \
        --workload monotonic --nemesis clock
"""

from __future__ import annotations

from jepsen_tpu import os as jos
from jepsen_tpu.clients.pgwire import PgClient

from suites import sqlextra, sqlsuite
from suites.cockroachdb.db import SQL_PORT, CockroachDB


def conn(node, test):
    return PgClient(node,
                    port=int(test.get("db_port", SQL_PORT)),
                    user=test.get("db_user", "root"),
                    database=test.get("db_name", "defaultdb")).connect()


EXTRA = {
    "monotonic": lambda opts: sqlextra.monotonic_workload(conn),
    "sequential": lambda opts: sqlextra.sequential_workload(
        conn, keys=int(opts.get("keys", 32))),
    # strict-serializability write precedence over sharded comment tables
    # (cockroach/comments.clj); adya G2 ships as the shared "g2" workload
    "comments": lambda opts: sqlextra.comments_workload(
        conn, keys=int(opts.get("keys", 4))),
}

WORKLOADS, cockroach_test, all_tests, main = sqlsuite.make_suite(
    "cockroachdb", CockroachDB(), conn, os=jos.Ubuntu(),
    extra_workloads=EXTRA, default_workload="register")


if __name__ == "__main__":
    import sys
    sys.exit(main())
