"""CockroachDB cluster install/start.

Parity: cockroachdb/src/jepsen/cockroach/auto.clj (binary install, start
with --join, cluster init once, kill/pause) and cockroach.clj's db.  The
reference runs on its own Ubuntu OS layer (os/ubuntu.clj); here the suite
defaults to jepsen_tpu.os.Ubuntu.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "23.1.11"
URL = (f"https://binaries.cockroachdb.com/"
       f"cockroach-v{VERSION}.linux-amd64.tgz")
DIR = "/opt/cockroach"
STORE = "/opt/cockroach/data"
PIDFILE = "/var/run/cockroach.pid"
LOGFILE = "/var/log/cockroach.log"
SQL_PORT = 26257
HTTP_PORT = 8080


class CockroachDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        # tarball unpacks cockroach-v*/cockroach; normalize to DIR/cockroach
        s.exec("bash", "-c",
               f"[ -x {DIR}/cockroach ] || "
               f"cp {DIR}/cockroach*/cockroach {DIR}/cockroach || true")
        self.start(test, node)
        if node == test["nodes"][0]:
            cu.await_tcp_port(s, SQL_PORT, timeout_s=90)
            s.exec("bash", "-c",
                   f"{DIR}/cockroach init --insecure "
                   f"--host={node}:{SQL_PORT} 2>&1 | "
                   f"grep -v 'already been initialized' || true")
        cu.await_tcp_port(s, SQL_PORT, timeout_s=90)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.stop_daemon(s, PIDFILE)
        cu.grepkill(s, "cockroach")
        s.exec("rm", "-rf", STORE, LOGFILE)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        join = ",".join(f"{n}:{SQL_PORT}" for n in test["nodes"])
        cu.start_daemon(
            s, f"{DIR}/cockroach", "start", "--insecure",
            "--store", STORE,
            "--listen-addr", f"0.0.0.0:{SQL_PORT}",
            "--advertise-addr", f"{node}:{SQL_PORT}",
            "--http-addr", f"0.0.0.0:{HTTP_PORT}",
            "--join", join,
            pidfile=PIDFILE, logfile=LOGFILE)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "cockroach")
        s.exec("rm", "-f", PIDFILE)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "cockroach", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "cockroach", "CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        return []  # ranges elect their own leaseholders; no single primary

    def setup_primary(self, test, node):
        pass

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [LOGFILE, f"{STORE}/logs/cockroach.log"]
