"""RobustIRC suite (reference: robustirc/ — Raft-replicated IRC network;
message-log set workload over the robustsession HTTP protocol)."""
