"""RobustIRC robustsession client.

Parity: robustirc/src/jepsen/robustirc.clj:103-180 — POST
/robustirc/v1/session for {Sessionid, Sessionauth}; NICK/USER/JOIN on
setup; :add posts "TOPIC #jepsen :<n>" with a random ClientMessageId;
:read streams /messages from lastseen 0.0 and extracts topic integers.
"""

from __future__ import annotations

import json
import random
import socket
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

PORT = 13001
NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


class RobustSession:
    def __init__(self, node: str, port: int = PORT, timeout: float = 8.0,
                 scheme: str = "https"):
        self.node = node
        self.base = f"{scheme}://{node}:{port}/robustirc/v1"
        self.timeout = timeout
        self.ctx = ssl.create_default_context()
        self.ctx.check_hostname = False
        self.ctx.verify_mode = ssl.CERT_NONE
        r = self._req("POST", "/session")
        self.sid = r["Sessionid"]
        self.auth = r["Sessionauth"]

    def _req(self, method: str, path: str, body: Optional[Dict] = None,
             auth: bool = False, raw: bool = False):
        req = urllib.request.Request(
            self.base + path, method=method,
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json",
                     **({"X-Session-Auth": self.auth} if auth else {})})
        with urllib.request.urlopen(req, timeout=self.timeout,
                                    context=self.ctx) as resp:
            data = resp.read()
        if raw:
            return data
        return json.loads(data) if data else {}

    def post_message(self, data: str) -> None:
        msgid = random.randrange(1, 2 ** 31)
        self._req("POST", f"/{self.sid}/message",
                  {"Data": data, "ClientMessageId": msgid}, auth=True)

    def read_messages(self) -> List[Dict[str, Any]]:
        """Stream /messages incrementally: the endpoint long-polls, so
        read until the backlog stops flowing and keep what arrived
        (robustirc.clj:126-137's read-all with a timeout)."""
        req = urllib.request.Request(
            self.base + f"/{self.sid}/messages?lastseen=0.0",
            headers={"X-Session-Auth": self.auth})
        raw = b""
        try:
            with urllib.request.urlopen(req, timeout=2.0,
                                        context=self.ctx) as resp:
                while True:
                    chunk = resp.read(65536)
                    if not chunk:
                        break
                    raw += chunk
        except (TimeoutError, socket.timeout, OSError):
            pass  # long-poll idle: the backlog is whatever we got
        out = []
        dec = json.JSONDecoder()
        s = raw.decode(errors="replace")
        i = 0
        while i < len(s):
            while i < len(s) and s[i] in " \r\n\t":
                i += 1
            if i >= len(s):
                break
            try:
                obj, j = dec.raw_decode(s, i)
            except ValueError:
                break  # trailing partial object from the cutoff
            out.append(obj)
            i = j
        return out


def topic_values(messages: List[Dict[str, Any]]) -> List[int]:
    """Extract ints from TOPIC lines (robustirc.clj:139-152)."""
    out = []
    for m in messages:
        parts = str(m.get("Data", "")).split(" ")
        if len(parts) > 1 and parts[1] == "TOPIC":
            tail = str(m["Data"]).rsplit(":", 1)[-1]
            try:
                out.append(int(tail))
            except ValueError:
                pass
    return out


class SetClient(jclient.Client):
    def __init__(self, sess: Optional[RobustSession] = None):
        self.sess = sess

    def open(self, test, node):
        sess = RobustSession(node, port=int(test.get("db_port", PORT)),
                             scheme=test.get("db_scheme", "https"))
        sess.post_message(f"NICK n{random.randrange(10**6)}")
        sess.post_message("USER j j j j")
        sess.post_message("JOIN #jepsen")
        return SetClient(sess)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.sess.post_message(f"TOPIC #jepsen :{op.value}")
                return op.with_(type=OK)
            if op.f == "read":
                vals = sorted(set(topic_values(
                    self.sess.read_messages())))
                return op.with_(type=OK, value=vals)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
