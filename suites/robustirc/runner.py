"""RobustIRC suite CLI.

Parity: robustirc/src/jepsen/robustirc.clj:186-217 — the set workload
(TOPIC adds, one final read of the message log) under random-halves
partitions.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import SetChecker

from suites import common
from suites.robustirc.client import SetClient
from suites.robustirc.db import RobustIrcDB


def set_workload(opts) -> Dict[str, Any]:
    counter = itertools.count()
    return {"client": SetClient(),
            "generator": gen.stagger(
                1 / 10, gen.FnGen(lambda: {"f": "add",
                                           "value": next(counter)})),
            "final_generator": gen.once({"f": "read"}),
            "checker": SetChecker()}


WORKLOADS = {"set": set_workload}


def robustirc_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="robustirc", db=RobustIrcDB(),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, robustirc_test, WORKLOADS)


def _extra(parser):
    parser.add_argument("--db-scheme", default="https",
                        choices=["https", "http"],
                        help="robustsession transport (real networks "
                             "are TLS)")


if __name__ == "__main__":
    import sys
    sys.exit(common.main(robustirc_test, WORKLOADS,
                         prog="jepsen-tpu-robustirc", extra_opts=_extra))
