"""stolon suite CLI — append (Elle) is the flagship workload.

Parity: stolon/src/jepsen/stolon/append.clj (list-append over jdbc with
serializable isolation) + nemesis.clj's standard package set.

    python -m suites.stolon.runner test --node n1 ... --workload append
"""

from __future__ import annotations

from jepsen_tpu.clients.pgwire import PgClient

from suites import sqlsuite
from suites.stolon import db as sdb
from suites.stolon.db import StolonDB


def conn(node, test):
    # clients go through the local stolon-proxy, which routes to the
    # elected master (stolon/client.clj:14-26)
    return PgClient(node,
                    port=int(test.get("db_port", sdb.PROXY_PORT)),
                    user=test.get("db_user", sdb.PG_USER),
                    password=test.get("db_password", sdb.PG_PASSWORD),
                    database=test.get("db_name", "postgres")).connect()


WORKLOADS, stolon_test, all_tests, main = sqlsuite.make_suite(
    "stolon", StolonDB(), conn, default_workload="append")


if __name__ == "__main__":
    import sys
    sys.exit(main())
