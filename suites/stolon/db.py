"""Stolon cluster install/start: postgres + keeper/sentinel/proxy + etcd.

Parity: stolon/src/jepsen/stolon/db.clj — postgres from the PGDG apt repo
(db.clj:45-60, service disabled so stolon owns the lifecycle), stolon
release tarball, ``--store-backend etcdv3`` (db.clj:85), three daemons with
their own pid/log files (db.clj:27-37), all running as the postgres user
(db.clj:24-25).  The etcd store reuses this repo's etcd suite DB.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

from suites.etcd.db import CLIENT_PORT as ETCD_PORT
from suites.etcd.db import EtcdDB

VERSION = "0.17.0"
URL = ("https://github.com/sorintlab/stolon/releases/download/"
       f"v{VERSION}/stolon-v{VERSION}-linux-amd64.tar.gz")
DIR = "/opt/stolon"
DATA = "/opt/stolon/data"
CLUSTER = "jepsen"
PG_PORT = 5433          # keeper-managed postgres
PROXY_PORT = 25432      # clients connect here
PG_USER = "postgres"
PG_PASSWORD = "pw"

SENTINEL_PID, SENTINEL_LOG = f"{DIR}/sentinel.pid", f"{DIR}/sentinel.log"
KEEPER_PID, KEEPER_LOG = f"{DIR}/keeper.pid", f"{DIR}/keeper.log"
PROXY_PID, PROXY_LOG = f"{DIR}/proxy.pid", f"{DIR}/proxy.log"


def store_endpoints(test) -> str:
    return ",".join(f"http://{n}:{ETCD_PORT}" for n in test["nodes"])


class StolonDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    """etcd (store) + postgres + stolon daemons on every node."""

    def __init__(self):
        self.etcd = EtcdDB()

    def _install_postgres(self, s):
        # PGDG repo install, then hand the service to stolon
        # (stolon/db.clj:45-60)
        cu.cached_wget(s, "https://www.postgresql.org/media/keys/ACCC4CF8.asc",
                       "/tmp/pgdg.asc")
        s.exec("apt-key", "add", "/tmp/pgdg.asc")
        cu.write_file(
            s, "deb http://apt.postgresql.org/pub/repos/apt/ "
               "bullseye-pgdg main",
            "/etc/apt/sources.list.d/pgdg.list")
        s.exec("apt-get", "update")
        s.exec("env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
               "-y", "postgresql-12", "postgresql-client-12")
        s.exec("service", "postgresql", "stop")
        s.exec("update-rc.d", "postgresql", "disable")

    def setup(self, test, node):
        s = session(test, node).sudo()
        self.etcd.setup(test, node)
        self._install_postgres(s)
        cu.install_archive(s, URL, DIR)
        cu.ensure_user(s, PG_USER)
        s.exec("mkdir", "-p", DATA)
        s.exec("chown", "-R", f"{PG_USER}:{PG_USER}", DIR)
        if node == test["nodes"][0]:
            s.exec(f"{DIR}/bin/stolonctl",
                   "--cluster-name", CLUSTER,
                   "--store-backend", "etcdv3",
                   "--store-endpoints", store_endpoints(test),
                   "init", "-y",
                   '{"initMode":"new","pgParameters":'
                   '{"max_connections":"300"},'
                   '"proxyCheckInterval":"1s","proxyTimeout":"3s"}')
        self.start(test, node)
        cu.await_tcp_port(s, PROXY_PORT, timeout_s=120)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        for pid in (PROXY_PID, SENTINEL_PID, KEEPER_PID):
            cu.stop_daemon(s, pid)
        self.etcd.teardown(test, node)
        s.exec("rm", "-rf", DATA, KEEPER_LOG, SENTINEL_LOG, PROXY_LOG)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        store = ["--cluster-name", CLUSTER, "--store-backend", "etcdv3",
                 "--store-endpoints", store_endpoints(test)]
        cu.start_daemon(s, f"{DIR}/bin/stolon-sentinel", *store,
                        pidfile=SENTINEL_PID, logfile=SENTINEL_LOG,
                        user=PG_USER)
        cu.start_daemon(s, f"{DIR}/bin/stolon-keeper", *store,
                        "--uid", f"keeper_{node.replace('.', '_')}",
                        "--data-dir", DATA,
                        "--pg-listen-address", node,
                        "--pg-port", str(PG_PORT),
                        "--pg-su-password", PG_PASSWORD,
                        "--pg-repl-username", "repl",
                        "--pg-repl-password", PG_PASSWORD,
                        pidfile=KEEPER_PID, logfile=KEEPER_LOG, user=PG_USER)
        cu.start_daemon(s, f"{DIR}/bin/stolon-proxy", *store,
                        "--listen-address", "0.0.0.0",
                        "--port", str(PROXY_PORT),
                        pidfile=PROXY_PID, logfile=PROXY_LOG, user=PG_USER)

    def kill(self, test, node):
        s = session(test, node).sudo()
        for pat in ("stolon-proxy", "stolon-sentinel", "stolon-keeper",
                    "postgres"):
            cu.grepkill(s, pat)
        for pid in (PROXY_PID, SENTINEL_PID, KEEPER_PID):
            s.exec("rm", "-f", pid)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        s = session(test, node).sudo()
        for pat in ("stolon-keeper", "postgres"):
            cu.signal(s, pat, "STOP")

    def resume(self, test, node):
        s = session(test, node).sudo()
        for pat in ("stolon-keeper", "postgres"):
            cu.signal(s, pat, "CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        s = session(test, test["nodes"][0]).sudo()
        try:
            out = s.exec(f"{DIR}/bin/stolonctl",
                         "--cluster-name", CLUSTER,
                         "--store-backend", "etcdv3",
                         "--store-endpoints", store_endpoints(test),
                         "status")
            for line in out.splitlines():
                if "master" in line.lower():
                    for n in test["nodes"]:
                        if n.replace(".", "_") in line or n in line:
                            return [n]
        except Exception:  # noqa: BLE001
            pass
        return []

    def setup_primary(self, test, node):
        pass  # sentinel elects the master

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [KEEPER_LOG, SENTINEL_LOG, PROXY_LOG]
