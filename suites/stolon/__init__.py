"""stolon suite — Postgres HA under stolon (keeper/sentinel/proxy).

Parity: stolon/src/jepsen/stolon/{db,client,append,nemesis}.clj — Elle
list-append is the flagship workload (append.clj); the DB layer installs
postgres + the stolon release and runs keeper/sentinel/proxy daemons backed
by an etcdv3 store (db.clj:85).
"""

from suites.stolon.runner import WORKLOADS, all_tests, stolon_test  # noqa: F401
