"""MongoDB-on-SmartOS suite CLI.

Parity: mongodb-smartos/src/jepsen/mongodb_smartos/document_cas.clj:
101-140's write-concern test matrix (majority / no-read-majority /
journaled / fsync-safe / unacknowledged-ish variants) and transfer.clj's
two-phase bank.  Runs on the SmartOS OS layer.
"""

from __future__ import annotations

from typing import Any, Dict

import random

from jepsen_tpu import generator as gen
from jepsen_tpu import os as jos
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models.base import Model, inconsistent
from jepsen_tpu.workloads import linearizable_register

from suites import common
from suites.mongodb_smartos.client import DocumentCasClient, TransferClient
from suites.mongodb_smartos.db import MongoSmartOSDB

WRITE_CONCERNS = ["majority", "1", "journaled"]


def register_workload(opts) -> Dict[str, Any]:
    wl = linearizable_register.workload(
        keys=range(int(opts.get("keys", 8))),
        ops_per_key=int(opts.get("ops_per_key", 100)),
        threads_per_key=2)
    return {**wl, "client": DocumentCasClient(
        write_concern=opts.get("write_concern", "majority"))}


def no_read_register_workload(opts) -> Dict[str, Any]:
    """Writes and CAS only — mongo without linearizable reads
    (document_cas.clj:108-115)."""
    wl = register_workload(opts)
    return {**wl, "generator": gen.gen_filter(
        lambda op: op.f != "read", wl["generator"])}


class AccountsModel(Model):
    """Transfers between a fixed account map; partial reads must agree
    with the modeled balances for the accounts they see
    (transfer.clj:190-220's Accounts model)."""

    def __init__(self, accts: Dict[int, int]):
        self.accts = dict(accts)

    def step(self, op):
        v = op.value
        if op.f == "read":
            if v == self.accts:
                return self
            return inconsistent(f"can't read {v} from {self.accts}")
        if op.f == "partial-read":
            for acct, balance in (v or {}).items():
                if self.accts.get(acct) != balance:
                    return inconsistent(
                        f"{v} isn't consistent with {self.accts}")
            return self
        if op.f == "transfer":
            next_ = dict(self.accts)
            next_[v["from"]] -= v["amount"]
            next_[v["to"]] += v["amount"]
            return AccountsModel(next_)
        return inconsistent(f"unknown f {op.f!r}")

    def __eq__(self, other):
        return isinstance(other, AccountsModel) and \
            self.accts == other.accts

    def __hash__(self):
        return hash(tuple(sorted(self.accts.items())))


def transfer_workload(opts) -> Dict[str, Any]:
    """partial-read + different-account transfers under the Accounts
    linearizability model (transfer.clj:255-281's partial-read and
    diff-account tests; raw reads are known-broken on mongo and kept as
    the transfer-read variant)."""
    accounts = list(range(int(opts.get("n_accounts", 3))))
    per = int(opts.get("starting_balance", 10))
    read_f = opts.get("transfer_read_f", "partial-read")

    def xfer():
        frm, to = random.sample(accounts, 2)
        return {"f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": random.randint(0, 4)}}

    g = gen.mix([gen.repeat({"f": read_f}), gen.FnGen(xfer)])
    model = AccountsModel({a: per for a in accounts})
    return {"client": TransferClient(
                write_concern=opts.get("write_concern", "majority")),
            "generator": gen.stagger(1 / 20, g),
            "checker": linearizable(model, opts.get("algorithm", "cpu")),
            "accounts": accounts,
            "total": per * len(accounts)}


WORKLOADS = {"document-cas": register_workload,
             "document-cas-no-read": no_read_register_workload,
             "transfer": transfer_workload}


def mongodb_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    t = common.build_test(opts, suite="mongodb-smartos",
                          db=MongoSmartOSDB(), workloads=WORKLOADS,
                          os=jos.Smartos())
    if opts.get("workload") == "transfer":
        n = int(opts.get("n_accounts", 3))
        per = int(opts.get("starting_balance", 10))
        t["bank"] = {"accounts": list(range(n)),
                     "total_amount": per * n}
    return t


def all_tests(opts: Dict[str, Any]):
    """Write-concern x workload matrix (document_cas.clj:101-140)."""
    out = []
    for wc in opts.get("write_concerns", WRITE_CONCERNS):
        for w in opts.get("workloads", sorted(WORKLOADS)):
            out.append(mongodb_test({**opts, "workload": w,
                                     "write_concern": wc,
                                     "nemesis": opts.get("nemesis",
                                                         "partition")}))
    return out


def _extra(parser):
    parser.add_argument("--keys", type=int, default=8)
    parser.add_argument("--ops-per-key", type=int, default=100)
    parser.add_argument("--write-concern", default="majority",
                        choices=WRITE_CONCERNS)
    parser.add_argument("--total-amount", type=int, default=100)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(mongodb_test, WORKLOADS,
                         prog="jepsen-tpu-mongodb-smartos",
                         extra_opts=_extra,
                         default_workload="document-cas"))
