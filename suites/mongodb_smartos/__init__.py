"""MongoDB-on-SmartOS suite (reference: mongodb-smartos/ — document CAS
across write-concern variants and the two-phase transfer workload)."""
