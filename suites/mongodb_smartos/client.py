"""MongoDB workload clients.

Parity: mongodb-smartos/src/jepsen/mongodb_smartos/document_cas.clj:40-84
(one document as a register: read by _id, write = update-by-id, CAS =
update with {_id, value: old} filter checking n) and transfer.clj:43-170
(the classic two-phase-commit transfer over txns + accounts collections
with pendingTxns guards).
"""

from __future__ import annotations

import random
import socket
from typing import Any, Dict, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.clients.mongo import MongoClient, MongoError
from jepsen_tpu.history import FAIL, INFO, OK, Op

PORT = 27017
NET_ERRORS = (ConnectionError, OSError, socket.timeout, TimeoutError)


def connect(test, node) -> MongoClient:
    return MongoClient(node, int(test.get("db_port", PORT))).connect()


class _MongoBase(jclient.Client):
    def __init__(self, conn: Optional[MongoClient] = None,
                 node: Optional[str] = None):
        self.conn = conn
        self.node = node

    def open(self, test, node):
        return type(self)(connect(test, node), node)

    def close(self, test):
        if self.conn:
            self.conn.close()

    def _reconnect(self, test):
        try:
            self.conn.close()
        except Exception:  # noqa: BLE001
            pass
        try:
            self.conn = connect(test, self.node)
        except Exception:  # noqa: BLE001 — node may be down
            pass


class DocumentCasClient(_MongoBase):
    """Per-key register documents (document_cas.clj:40-84), lifted over
    the independent keyspace."""

    COLL = "jepsen"

    def __init__(self, conn=None, node=None,
                 write_concern: str = "majority"):
        super().__init__(conn, node)
        self.write_concern = write_concern

    def open(self, test, node):
        return DocumentCasClient(connect(test, node), node,
                                 test.get("write_concern",
                                          self.write_concern))

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                doc = self.conn.find_one(self.COLL, {"_id": k})
                return op.with_(type=OK,
                                value=(k, doc.get("value")
                                       if doc else None))
            if op.f == "write":
                self.conn.update(self.COLL, {"_id": k},
                                 {"_id": k, "value": v}, upsert=True,
                                 write_concern=self.write_concern)
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                n = self.conn.update(self.COLL,
                                     {"_id": k, "value": old},
                                     {"_id": k, "value": new},
                                     write_concern=self.write_concern)
                return op.with_(type=OK if n == 1 else FAIL)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self._reconnect(test)
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except MongoError as e:
            if op.f == "read":
                return op.with_(type=FAIL, error=str(e)[:200])
            return op.with_(type=INFO, error=str(e)[:200])


READ_FS = ("read", "partial-read")


class TransferClient(_MongoBase):
    """Two-phase-commit transfers (transfer.clj:43-170): create a txn
    doc, apply $inc to both accounts guarded by pendingTxns, then clear.
    Reads sum the accounts collection."""

    ACCTS = "accounts"
    TXNS = "txns"

    def __init__(self, conn=None, node=None,
                 write_concern: str = "majority"):
        super().__init__(conn, node)
        self.write_concern = write_concern

    def setup(self, test):
        wl = test.get("bank", {})
        accounts = wl.get("accounts", list(range(8)))
        total = wl.get("total_amount", 100)
        per = total // len(accounts)
        for i, a in enumerate(accounts):
            amt = per + (total - per * len(accounts) if i == 0 else 0)
            try:
                self.conn.insert(self.ACCTS,
                                 {"_id": a, "balance": amt,
                                  "pendingTxns": []})
            except MongoError:
                pass  # another node seeded it

    def _transfer(self, v: Dict[str, Any]) -> None:
        wc = self.write_concern
        txn_id = f"t{random.randrange(16**12):012x}"
        self.conn.insert(self.TXNS,
                         {"_id": txn_id, "state": "pending",
                          "from": v["from"], "to": v["to"],
                          "amount": v["amount"]}, write_concern=wc)
        self.conn.update(self.ACCTS,
                         {"_id": v["from"],
                          "pendingTxns": {"$ne": txn_id}},
                         {"$inc": {"balance": -v["amount"]},
                          "$push": {"pendingTxns": txn_id}},
                         write_concern=wc)
        self.conn.update(self.ACCTS,
                         {"_id": v["to"],
                          "pendingTxns": {"$ne": txn_id}},
                         {"$inc": {"balance": v["amount"]},
                          "$push": {"pendingTxns": txn_id}},
                         write_concern=wc)
        self.conn.update(self.TXNS, {"_id": txn_id, "state": "pending"},
                         {"$set": {"state": "applied"}},
                         write_concern=wc)
        for acct in (v["from"], v["to"]):
            self.conn.update(self.ACCTS,
                             {"_id": acct, "pendingTxns": txn_id},
                             {"$pull": {"pendingTxns": txn_id}},
                             write_concern=wc)
        self.conn.update(self.TXNS, {"_id": txn_id, "state": "applied"},
                         {"$set": {"state": "done"}}, write_concern=wc)

    def invoke(self, test, op: Op) -> Op:
        accounts = test.get("bank", {}).get("accounts", list(range(8)))
        try:
            if op.f in ("read", "partial-read"):
                # partial-read only sees accounts with no transaction in
                # flight (transfer.clj:159-165) — the sound read mode
                flt = {"pendingTxns": {"$size": 0}} \
                    if op.f == "partial-read" else {}
                r = self.conn.command({"find": self.ACCTS, "filter": flt,
                                       "limit": len(accounts) + 1})
                docs = r.get("cursor", {}).get("firstBatch", [])
                return op.with_(type=OK,
                                value={d["_id"]: d["balance"]
                                       for d in docs})
            if op.f == "transfer":
                self._transfer(op.value)
                return op.with_(type=OK)
            raise ValueError(op.f)
        except NET_ERRORS as e:
            self._reconnect(test)
            if op.f in READ_FS:
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
        except MongoError as e:
            if op.f in READ_FS:
                return op.with_(type=FAIL, error=str(e)[:200])
            return op.with_(type=INFO, error=str(e)[:200])
