"""MongoDB replica-set install on SmartOS.

Parity: mongodb-smartos/src/jepsen/mongodb_smartos/core.clj:40-250 —
pkgin install, mongod --replSet over the test's nodes, replica-set
initiate from node 1 with all members, wait for a primary.  Runs on the
SmartOS OS layer (jepsen_tpu.os.SmartOS).
"""

from __future__ import annotations

import json
import time
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.clients.mongo import MongoClient, MongoError
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

PORT = 27017
REPLSET = "jepsen"
DATA = "/var/mongodb"
LOGFILE = "/var/log/mongodb.log"
PIDFILE = "/var/run/mongod.pid"


class MongoSmartOSDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary,
                     jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("sh", "-c",
               "command -v mongod >/dev/null 2>&1 || "
               "pkgin -y install mongodb")
        s.exec("mkdir", "-p", DATA)
        self.start(test, node)
        cu.await_tcp_port(s, PORT, timeout_s=120)

    def setup_primary(self, test, node):
        """replSetInitiate with every member, then wait for a primary
        (core.clj:128-250)."""
        members = [{"_id": i, "host": f"{n}:{PORT}"}
                   for i, n in enumerate(test["nodes"])]
        c = MongoClient(node, int(test.get("db_port", PORT)))
        try:
            try:
                c.command({"replSetInitiate": {"_id": REPLSET,
                                               "members": members}},
                          database="admin")
            except MongoError as e:
                if "already initialized" not in str(e):
                    raise
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                st = c.command({"replSetGetStatus": 1}, database="admin")
                if any(m.get("stateStr") == "PRIMARY"
                       for m in st.get("members", [])):
                    return
                time.sleep(1)
            raise RuntimeError("no primary elected")
        finally:
            c.close()

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "mongod")
        s.exec("sh", "-c", f"rm -rf {DATA}/* {LOGFILE} || true")

    def start(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(s, "mongod",
                        "--dbpath", DATA, "--port", str(PORT),
                        "--bind_ip_all", "--replSet", REPLSET,
                        pidfile=PIDFILE, logfile=LOGFILE)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "mongod")
        s.exec("rm", "-f", PIDFILE)

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "mongod", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "mongod", "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
