"""Demo suite — a complete, in-process test target that runs anywhere.

Plays the role of the reference's canonical noop/tutorial tests
(jepsen/src/jepsen/tests.clj:13-26 noop-test, doc/tutorial): a mock
replicated register with injectable consistency bugs, so the whole pipeline
(generator -> interpreter -> history -> TPU checker -> store) runs with no
cluster, and seeded bugs are provably caught.
"""
