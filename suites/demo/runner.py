"""Demo suite: mock replicated register, optional seeded bugs.

    python -m suites.demo.runner test --dummy-ssh --time-limit 5
    python -m suites.demo.runner test --dummy-ssh --bug stale-reads

The mock "database" is an in-process register per key with a configurable
consistency bug; the checker must return valid for the honest store and
invalid when a bug is seeded.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, Optional

from jepsen_tpu import cli, client as jclient, generator as gen
from jepsen_tpu.checker import Stats, compose
from jepsen_tpu.checker.perf import Perf
from jepsen_tpu.checker.timeline import Timeline
from jepsen_tpu.history import FAIL, OK
from jepsen_tpu.workloads import linearizable_register


class MockStore:
    """Shared 'replicated' register map with injectable bugs.  The bug
    trigger uses its own seeded RNG so demo runs are reproducible."""

    def __init__(self, bug: Optional[str] = None, seed: int = 1):
        self.regs: Dict[Any, Any] = {}
        self.lock = threading.Lock()
        self.bug = bug
        self.rng = random.Random(seed)
        self.history_of: Dict[Any, list] = {}

    def apply(self, op):
        k, v = op.value
        with self.lock:
            cur = self.regs.get(k)
            if op.f == "read":
                out = cur
                if self.bug == "stale-reads" and self.rng.random() < 0.1:
                    past = self.history_of.get(k) or [None]
                    out = past[max(0, len(past) - 4)]
                return op.with_(type=OK, value=(k, out))
            if op.f == "write":
                self.regs[k] = v
                self.history_of.setdefault(k, []).append(v)
                return op.with_(type=OK)
            old, new = v
            if self.bug == "phantom-cas" and self.rng.random() < 0.05:
                return op.with_(type=OK)  # claims success, did nothing
            if cur == old:
                self.regs[k] = new
                self.history_of.setdefault(k, []).append(new)
                return op.with_(type=OK)
            return op.with_(type=FAIL)


class MockClient(jclient.Client):
    def __init__(self, store: MockStore):
        self.store = store
        self.reusable = True

    def open(self, test, node):
        return self

    def invoke(self, test, op):
        return self.store.apply(op)


def demo_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    bug = opts.get("bug") or None
    if bug == "none":
        bug = None
    store = MockStore(bug=bug, seed=int(opts.get("seed", 1)))
    keys = int(opts.get("keys", 4))
    wl = linearizable_register.workload(
        keys=range(keys),
        ops_per_key=int(opts.get("ops_per_key", 150)),
        threads_per_key=2,
        algorithm=opts.get("algorithm"))
    time_limit = float(opts.get("time_limit", 30.0))
    return {**opts,
            "name": f"demo-register{'-' + bug if bug else ''}",
            "client": MockClient(store),
            "generator": gen.time_limit(time_limit,
                                        gen.clients(wl["generator"])),
            "checker": compose({"stats": Stats(),
                                "workload": wl["checker"],
                                "perf": Perf(),
                                "timeline": Timeline()})}


def _suite_opts(parser):
    parser.add_argument("--bug", default="none",
                        choices=["none", "stale-reads", "phantom-cas"])
    parser.add_argument("--keys", type=int, default=4)
    parser.add_argument("--ops-per-key", type=int, default=150)
    parser.add_argument("--algorithm", default=None,
                        choices=[None, "tpu", "cpu", "competition"])


if __name__ == "__main__":
    import sys
    sys.exit(cli.single_test_cmd(demo_test, opt_fn=_suite_opts,
                                 prog="jepsen-tpu-demo"))
