"""Shared SQL suite clients: bank, register, sets, append.

The reference's SQL-family suites (postgres-rds, stolon, cockroachdb, tidb,
galera, percona, mysql-cluster) repeat the same client shapes over jdbc
(e.g. cockroachdb/src/jepsen/cockroach/bank.clj, stolon/src/jepsen/stolon/
append.clj, tidb/src/tidb/sql.clj); here they are factored once over any
driver exposing ``query(sql) -> rows`` with a ``retryable`` error
classification (clients/pgwire.py, clients/mysql.py).

All statements are plain standard SQL so the same clients run against real
servers and the in-process fakes.
"""

from __future__ import annotations

import socket
from typing import Any, Callable, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

ConnFactory = Callable[[str, Dict[str, Any]], Any]


class _SqlClient(jclient.Client):
    """Common connect/teardown and error conversion."""

    def __init__(self, conn_factory: ConnFactory, conn=None):
        self.conn_factory = conn_factory
        self.conn = conn

    def _clone(self, conn):
        return type(self)(self.conn_factory, conn)

    def open(self, test, node):
        return self._clone(self.conn_factory(node, test))

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def _indeterminate(self, op: Op, e: Exception) -> Op:
        if op.f == "read":
            return op.with_(type=FAIL, error=str(e))
        return op.with_(type=INFO, error=str(e))

    def _definite(self, op: Op, e: Exception) -> Op:
        return op.with_(type=FAIL, error=str(e))

    def _upsert_kv(self, k, v) -> None:
        """UPDATE-then-INSERT upsert on the kv table (shared by the
        register and txn clients so the two stay in lockstep)."""
        self.conn.query(f"UPDATE kv SET val = {v} WHERE k = {k}")
        if self.conn.rowcount == 0:
            self.conn.query(f"INSERT INTO kv VALUES ({k}, {v})")

    def _convert(self, op: Op, e: Exception) -> Op:
        retryable = getattr(e, "retryable", False)
        if retryable:
            # conflict aborts definitely didn't commit
            return self._definite(op, e)
        if isinstance(e, (ConnectionError, OSError, socket.timeout,
                          TimeoutError)):
            try:
                self.conn.close()
            except Exception:  # noqa: BLE001
                pass
        return self._indeterminate(op, e)


class BankClient(_SqlClient):
    """Transfers between account rows in one transaction; reads select the
    whole table (jepsen.tests.bank semantics, cockroach/bank.clj)."""

    def setup(self, test):
        wl = test.get("bank", {})
        accounts = wl.get("accounts", list(range(8)))
        # default must agree with bank.workload's checker total (100)
        total = wl.get("total_amount", 100)
        per = total // len(accounts)
        self.conn.query("CREATE TABLE IF NOT EXISTS accounts "
                        "(id INT PRIMARY KEY, balance INT)")
        for i, a in enumerate(accounts):
            amt = per + (total - per * len(accounts) if i == 0 else 0)
            try:
                self.conn.query(f"INSERT INTO accounts VALUES ({a}, {amt})")
            except Exception:  # noqa: BLE001 — exists from another node
                pass

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query("SELECT id, balance FROM accounts")
                return op.with_(type=OK,
                                value={int(r[0]): int(r[1]) for r in rows})
            v = op.value
            frm, to, amt = v["from"], v["to"], v["amount"]
            self.conn.query("BEGIN")
            try:
                rows = self.conn.query(
                    f"SELECT balance FROM accounts WHERE id = {frm}")
                if not rows or int(rows[0][0]) < amt:
                    self.conn.query("ROLLBACK")
                    return op.with_(type=FAIL, error="insufficient")
                self.conn.query(f"UPDATE accounts SET balance = balance - "
                                f"{amt} WHERE id = {frm}")
                self.conn.query(f"UPDATE accounts SET balance = balance + "
                                f"{amt} WHERE id = {to}")
                self.conn.query("COMMIT")
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
            return op.with_(type=OK)
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class RegisterClient(_SqlClient):
    """Per-key int register row; CAS via conditional UPDATE returning its
    row count (cockroach/register.clj shape).  Values are (k, v) tuples
    from the independent lift."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS kv "
                        "(k INT PRIMARY KEY, val INT)")

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value
        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM kv WHERE k = {k}")
                val = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                return op.with_(type=OK, value=(k, val))
            if op.f == "write":
                self._upsert_kv(k, v)
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = v
                self.conn.query(f"UPDATE kv SET val = {new} "
                                f"WHERE k = {k} AND val = {old}")
                return op.with_(type=OK if self.conn.rowcount else FAIL)
            raise ValueError(op.f)
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class SetClient(_SqlClient):
    """Unique-row inserts, final full read (cockroach/sets.clj shape)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS sets (val INT)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.query(f"INSERT INTO sets VALUES ({op.value})")
                return op.with_(type=OK)
            rows = self.conn.query("SELECT val FROM sets")
            return op.with_(type=OK, value=[int(r[0]) for r in rows])
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class TxnClient(_SqlClient):
    """Generic read/write transactions over the kv table: mops are
    ``["r", k, None]`` / ``["w", k, v]``, the whole txn in BEGIN..COMMIT.
    Drives the Elle rw-register, long-fork, and Adya G2/dirty-update
    workloads (cockroachdb's comments/g2 tests, jepsen.tests.long-fork)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS kv "
                        "(k INT PRIMARY KEY, val INT)")

    def invoke(self, test, op: Op) -> Op:
        try:
            self.conn.query("BEGIN")
            try:
                out = []
                for f, k, v in op.value:
                    if f == "r":
                        rows = self.conn.query(
                            f"SELECT val FROM kv WHERE k = {k}")
                        val = int(rows[0][0]) if rows and rows[0][0] is not \
                            None else None
                        out.append(["r", k, val])
                    else:  # w
                        self._upsert_kv(k, v)
                        out.append(["w", k, v])
                self.conn.query("COMMIT")
                return op.with_(type=OK, value=out)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


class AppendClient(_SqlClient):
    """Elle list-append transactions: each mop reads or appends to a
    text-encoded list row, the whole txn in BEGIN..COMMIT
    (stolon/src/jepsen/stolon/append.clj shape)."""

    def setup(self, test):
        self.conn.query("CREATE TABLE IF NOT EXISTS append "
                        "(k INT PRIMARY KEY, vals TEXT)")

    def invoke(self, test, op: Op) -> Op:
        try:
            self.conn.query("BEGIN")
            try:
                out = []
                for f, k, v in op.value:
                    if f == "r":
                        rows = self.conn.query(
                            f"SELECT vals FROM append WHERE k = {k}")
                        cur = (rows[0][0] or "") if rows else ""
                        out.append(
                            ["r", k,
                             [int(x) for x in cur.split(",") if x] or None])
                    else:  # append
                        rows = self.conn.query(
                            f"SELECT vals FROM append WHERE k = {k}")
                        if rows:
                            cur = rows[0][0] or ""
                            new = f"{cur},{v}" if cur else str(v)
                            self.conn.query(
                                f"UPDATE append SET vals = '{new}' "
                                f"WHERE k = {k}")
                        else:
                            self.conn.query(
                                f"INSERT INTO append VALUES ({k}, '{v}')")
                        out.append([f, k, v])
                self.conn.query("COMMIT")
                return op.with_(type=OK, value=out)
            except Exception:
                try:
                    self.conn.query("ROLLBACK")
                except Exception:  # noqa: BLE001
                    pass
                raise
        except Exception as e:  # noqa: BLE001
            return self._convert(op, e)


