"""Percona XtraDB Cluster install/start.

Parity: percona/src/jepsen/percona.clj's db — percona-xtradb-cluster
packages, wsrep config over the test nodes, bootstrap-first-node.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

SQL_PORT = 3306
CONF = "/etc/mysql/mysql.conf.d/wsrep.cnf"
LOGFILE = "/var/log/mysql/error.log"


def wsrep_conf(test, node) -> str:
    addrs = ",".join(test["nodes"])
    return f"""[mysqld]
bind-address=0.0.0.0
binlog_format=ROW
default-storage-engine=innodb
innodb_autoinc_lock_mode=2
wsrep_on=ON
wsrep_provider=/usr/lib/galera4/libgalera_smm.so
wsrep_cluster_name=jepsen
wsrep_cluster_address=gcomm://{addrs}
wsrep_node_name={node}
wsrep_node_address={node}
wsrep_sst_method=rsync
pxc_strict_mode=PERMISSIVE
"""


class PerconaDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("env", "DEBIAN_FRONTEND=noninteractive", "apt-get", "install",
               "-y", "percona-xtradb-cluster-server", "rsync")
        s.exec("bash", "-c", "service mysql stop || true")
        cu.write_file(s, wsrep_conf(test, node), CONF)
        self.start(test, node)
        cu.await_tcp_port(s, SQL_PORT, timeout_s=180)
        if node == test["nodes"][0]:
            s.exec("mysql", "-e",
                   "CREATE DATABASE IF NOT EXISTS jepsen; "
                   "CREATE USER IF NOT EXISTS 'jepsen'@'%' "
                   "IDENTIFIED BY 'jepsen'; "
                   "GRANT ALL ON jepsen.* TO 'jepsen'@'%'; "
                   "FLUSH PRIVILEGES;")

    def teardown(self, test, node):
        s = session(test, node).sudo()
        s.exec("bash", "-c", "service mysql stop || true")
        cu.grepkill(s, "mysqld")
        # drop workload state too, or the next run's tables start dirty
        s.exec("bash", "-c", f"rm -rf /var/lib/mysql/jepsen {LOGFILE}")

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        s = session(test, node).sudo()
        if node == test["nodes"][0]:
            s.exec("bash", "-c",
                   "service mysql bootstrap-pxc || service mysql start")
        else:
            s.exec("service", "mysql", "start")

    def kill(self, test, node):
        cu.grepkill(session(test, node).sudo(), "mysqld")

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "mysqld", "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "mysqld", "CONT")

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [LOGFILE]
