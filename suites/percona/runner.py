"""percona suite CLI.

Parity: percona/src/jepsen/percona.clj — bank + dirty-reads over XtraDB.

    python -m suites.percona.runner test --node n1 ... --workload bank
"""

from __future__ import annotations

from jepsen_tpu.clients.mysql import MysqlClient

from suites import sqlextra, sqlsuite
from suites.percona.db import SQL_PORT, PerconaDB


def conn(node, test):
    return MysqlClient(node,
                       port=int(test.get("db_port", SQL_PORT)),
                       user=test.get("db_user", "jepsen"),
                       password=test.get("db_password", "jepsen"),
                       database=test.get("db_name", "jepsen")).connect()


EXTRA = {"dirty-reads": lambda opts: sqlextra.dirty_reads_workload(conn)}

WORKLOADS, percona_test, all_tests, main = sqlsuite.make_suite(
    "percona", PerconaDB(), conn, extra_workloads=EXTRA,
    default_workload="bank")


if __name__ == "__main__":
    import sys
    sys.exit(main())
