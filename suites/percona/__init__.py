"""percona suite — Percona XtraDB Cluster bank / dirty-reads.

Parity: percona/src/jepsen/{percona.clj,percona/dirty_reads.clj} — same
anomaly battery as galera over Percona's Galera-based XtraDB Cluster.
"""

from suites.percona.runner import WORKLOADS, all_tests, percona_test  # noqa: F401
