"""Hazelcast suite (reference: hazelcast/ — CP-subsystem locks,
semaphores, atomics, CRDT maps, and queues; the richest lock-model
family in the reference)."""
