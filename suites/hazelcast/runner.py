"""Hazelcast suite CLI — the reference's full workload registry.

Parity: hazelcast/src/jepsen/hazelcast.clj:652-760 — map/crdt-map sets,
plain and no-quorum locks (mutex model), non-reentrant/reentrant CP and
fenced locks (the owner-aware / reentrant / fenced / reentrant-fenced
mutex models), CP semaphore (acquired-permits model), unique-id
generators, CAS long/reference registers, and the queue.
"""

from __future__ import annotations

import random
from typing import Any, Dict

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import SetChecker, UniqueIds
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.models import get_model
from jepsen_tpu.workloads import queue as queue_wl

from suites import common
from suites.hazelcast import client as hc
from suites.hazelcast.db import HazelcastDB


def _adds():
    state = iter(range(10 ** 9))
    return gen.FnGen(lambda: {"f": "add", "value": next(state)})


def _map_workload(opts, crdt: bool) -> Dict[str, Any]:
    return {"client": hc.MapSetClient(crdt=crdt),
            "generator": gen.stagger(1 / 10, _adds()),
            "final_generator": gen.each_thread(gen.once({"f": "read"})),
            "checker": SetChecker()}


def _lock_gen(stagger_s: float):
    return gen.stagger(stagger_s, gen.each_thread(gen.cycle(gen.lift(
        [{"f": "acquire"}, {"f": "release"}]))))


def _reentrant_gen(stagger_s: float):
    return gen.stagger(stagger_s, gen.each_thread(gen.cycle(gen.lift(
        [{"f": "acquire"}, {"f": "acquire"},
         {"f": "release"}, {"f": "release"}]))))


def _lock_workload(opts, name: str, model: str, reentrant: bool = False,
                   fenced: bool = False,
                   stagger_s: float = 0.5) -> Dict[str, Any]:
    client = hc.FencedLockClient(name=name) if fenced \
        else hc.LockClient(name=name)
    g = _reentrant_gen(stagger_s) if reentrant else _lock_gen(stagger_s)
    return {"client": client, "generator": g,
            "checker": linearizable(get_model(model),
                                    opts.get("algorithm"))}


def _register_gen():
    return gen.mix([
        gen.FnGen(lambda: {"f": "read"}),
        gen.FnGen(lambda: {"f": "write", "value": random.randrange(5)}),
        gen.FnGen(lambda: {"f": "cas",
                           "value": [random.randrange(5),
                                     random.randrange(5)]})])


WORKLOADS = {
    "map": lambda o: _map_workload(o, crdt=False),
    "crdt-map": lambda o: _map_workload(o, crdt=True),
    "lock": lambda o: _lock_workload(
        o, "jepsen.lock", "mutex", stagger_s=0.1),
    "lock-no-quorum": lambda o: _lock_workload(
        o, "jepsen.lock.no-quorum", "mutex", stagger_s=0.1),
    "non-reentrant-cp-lock": lambda o: _lock_workload(
        o, "jepsen.cpLock1", "owner-aware-mutex"),
    "reentrant-cp-lock": lambda o: _lock_workload(
        o, "jepsen.cpLock2", "reentrant-mutex", reentrant=True),
    "non-reentrant-fenced-lock": lambda o: _lock_workload(
        o, "jepsen.cpLock1", "fenced-mutex", fenced=True, stagger_s=1.0),
    "reentrant-fenced-lock": lambda o: _lock_workload(
        o, "jepsen.cpLock2", "reentrant-fenced-mutex", reentrant=True,
        fenced=True, stagger_s=1.0),
    "cp-semaphore": lambda o: {
        "client": hc.SemaphoreClient(),
        "generator": _lock_gen(0.5),
        "checker": linearizable(get_model("acquired-permits"),
                                o.get("algorithm"))},
    "cp-cas-long": lambda o: {
        # IAtomicLong starts at 0, not nil (hazelcast.clj:163-167)
        "client": hc.CasLongClient(),
        "generator": gen.stagger(1 / 10, _register_gen()),
        "checker": linearizable(get_model("cas-register", init=0),
                                o.get("algorithm"))},
    "cp-cas-reference": lambda o: {
        "client": hc.CasReferenceClient(),
        "generator": gen.stagger(1 / 10, _register_gen()),
        "checker": linearizable(get_model("cas-register"),
                                o.get("algorithm"))},
    "cp-id-gen-long": lambda o: {
        "client": hc.IdGenClient(kind="along"),
        "generator": gen.stagger(0.5, gen.repeat({"f": "generate"})),
        "checker": UniqueIds()},
    "id-gen": lambda o: {
        "client": hc.IdGenClient(kind="flake"),
        "generator": gen.stagger(0.5, gen.repeat({"f": "generate"})),
        "checker": UniqueIds()},
    "queue": lambda o: {**queue_wl.workload(),
                        "client": hc.QueueClient()},
}


def hazelcast_test(opts: Dict[str, Any]) -> Dict[str, Any]:
    return common.build_test(opts, suite="hazelcast", db=HazelcastDB(),
                             workloads=WORKLOADS)


def all_tests(opts: Dict[str, Any]):
    return common.sweep(opts, hazelcast_test, WORKLOADS)


if __name__ == "__main__":
    import sys
    sys.exit(common.main(hazelcast_test, WORKLOADS,
                         prog="jepsen-tpu-hazelcast",
                         default_workload="lock"))
