"""Hazelcast server + bridge install.

Parity: hazelcast/src/jepsen/hazelcast.clj:34-117 — the reference builds
a custom server uberjar (build-server!) with the suite's Java merge
policy, uploads it, and runs it with a per-node config.  Here: install
the Hazelcast distribution, render hazelcast.xml (tcp-ip members, CP
subsystem sized to the cluster, crdt-map with the suite's
SetUnionMergePolicy), compile the suite's Java sources on-node against
the distribution jars (the same strategy nemesis.time uses for its C
helpers), and run server + HTTP bridge as daemons.
"""

from __future__ import annotations

from os import path
from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "5.3.6"
URL = (f"https://repo1.maven.org/maven2/com/hazelcast/hazelcast-distribution/"
       f"{VERSION}/hazelcast-distribution-{VERSION}.tar.gz")
DIR = "/opt/hazelcast"
CONF = f"{DIR}/config/jepsen.xml"
LOGFILE = "/var/log/hazelcast.log"
PIDFILE = "/var/run/hazelcast.pid"
BRIDGE_LOG = "/var/log/hz-bridge.log"
BRIDGE_PID = "/var/run/hz-bridge.pid"
MEMBER_PORT = 5701
BRIDGE_PORT = 5801

RESOURCES = path.join(path.dirname(__file__), "resources")

XML = """\
<?xml version="1.0" encoding="UTF-8"?>
<hazelcast xmlns="http://www.hazelcast.com/schema/config">
  <cluster-name>jepsen</cluster-name>
  <network>
    <port auto-increment="false">{port}</port>
    <join>
      <multicast enabled="false"/>
      <tcp-ip enabled="true">
{members}
      </tcp-ip>
    </join>
  </network>
  <cp-subsystem>
    <cp-member-count>{cp_members}</cp-member-count>
  </cp-subsystem>
  <map name="jepsen.crdt-map">
    <merge-policy batch-size="100">\
jepsen.hazelcast_server.SetUnionMergePolicy</merge-policy>
  </map>
</hazelcast>
"""
# NB the reference's 3.x <lock><quorum-ref> config
# (hazelcast/resources/hazelcast.xml) has no 5.x equivalent: ILock was
# removed in 4.0 and CP locks always require a CP-group majority, so the
# lock-no-quorum workload exercises the same CP lock under a different
# name rather than a quorum-free lock.


def config(test) -> str:
    members = "\n".join(f"        <member>{n}</member>"
                        for n in test["nodes"])
    return XML.format(port=MEMBER_PORT, members=members,
                      cp_members=min(len(test["nodes"]), 7) if
                      len(test["nodes"]) >= 3 else 0)


class HazelcastDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        s.exec("bash", "-c",
               f"[ -d {DIR}/lib ] || "
               f"cp -r {DIR}/hazelcast-{VERSION}/* {DIR}/ "
               f"2>/dev/null || true")
        cu.write_file(s, config(test), CONF)
        # compile the suite's Java against the distribution jars
        s.exec("mkdir", "-p", f"{DIR}/jepsen-classes")
        s.upload([path.join(RESOURCES, "JepsenBridge.java"),
                  path.join(RESOURCES, "SetUnionMergePolicy.java")],
                 f"{DIR}/jepsen-classes/")
        s.exec("bash", "-c",
               f"cd {DIR}/jepsen-classes && mkdir -p jepsen/hazelcast_server"
               f" && cp SetUnionMergePolicy.java jepsen/hazelcast_server/"
               f" && javac -cp '{DIR}/lib/*' JepsenBridge.java "
               f"jepsen/hazelcast_server/SetUnionMergePolicy.java")
        self.start(test, node)
        cu.await_tcp_port(s, MEMBER_PORT, timeout_s=180)
        cu.await_tcp_port(s, BRIDGE_PORT, timeout_s=120)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        cu.stop_daemon(s, BRIDGE_PID)
        cu.stop_daemon(s, PIDFILE)
        s.exec("sh", "-c", f"rm -rf {LOGFILE} {BRIDGE_LOG} "
                           f"{DIR}/cp-data || true")

    def start(self, test, node):
        s = session(test, node).sudo()
        cp = f"{DIR}/lib/*:{DIR}/jepsen-classes"
        cu.start_daemon(s, "java", "-cp", cp,
                        f"-Dhazelcast.config={CONF}",
                        "com.hazelcast.core.server.HazelcastMemberStarter",
                        pidfile=PIDFILE, logfile=LOGFILE)
        cu.start_daemon(s, "java", "-cp", cp, "JepsenBridge",
                        f"{node}:{MEMBER_PORT}", str(BRIDGE_PORT),
                        pidfile=BRIDGE_PID, logfile=BRIDGE_LOG)

    def kill(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "HazelcastMemberStarter")
        s.exec("rm", "-f", PIDFILE)

    def pause(self, test, node):
        cu.signal(session(test, node).sudo(), "HazelcastMemberStarter",
                  "STOP")

    def resume(self, test, node):
        cu.signal(session(test, node).sudo(), "HazelcastMemberStarter",
                  "CONT")

    def log_files(self, test, node) -> List[str]:
        return [LOGFILE, BRIDGE_LOG]
