"""Hazelcast workload clients over the node-side HTTP bridge.

Parity: hazelcast/src/jepsen/hazelcast.clj's client zoo — map/crdt-map
CAS-loop sets (453-493), CP locks plain and fenced (334-448), CP
semaphore (373-410), atomic long/reference CAS registers (146-231),
flake-id/atomic-long unique-id generators (146-264), and the queue client
(266-317).  Lock/semaphore ops stamp the bridge connection's client UUID
(and fence, when the lock is fenced) into op.value — the shape the lock
model family keys on (jepsen_tpu/models/locks.py).
"""

from __future__ import annotations

import socket
import urllib.error
import urllib.request
from typing import Any, Optional, Tuple

from jepsen_tpu import client as jclient
from jepsen_tpu.history import FAIL, INFO, OK, Op

BRIDGE_PORT = 5801
NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


class Bridge:
    """One bridge session = one HazelcastInstance on its own thread
    node-side, so lock/semaphore ownership is per harness client — the
    same topology as the reference's one-instance-per-client
    (hazelcast.clj:119-144)."""

    def __init__(self, node: str, port: int, timeout: float = 35.0):
        self.base = f"http://{node}:{port}"
        self.timeout = timeout
        self.session = None
        _, payload = self.call("/connect")
        self.session, self.uid = payload.split(",", 1)

    def call(self, path: str, **params) -> Tuple[bool, str]:
        """→ (ok?, payload); raises on transport errors and bridge
        exceptions ("err:" responses)."""
        if self.session is not None:
            params["session"] = self.session
        q = "&".join(f"{k}={v}" for k, v in params.items())
        url = f"{self.base}{path}" + (f"?{q}" if q else "")
        with urllib.request.urlopen(url, timeout=self.timeout) as r:
            body = r.read().decode()
        if body.startswith("ok:"):
            return True, body[3:]
        if body.startswith("fail:"):
            return False, body[5:]
        raise BridgeError(body)


class BridgeError(Exception):
    pass


def connect(test, node) -> Bridge:
    return Bridge(node, int(test.get("db_port", BRIDGE_PORT)))


class _BridgeClient(jclient.Client):
    def __init__(self, conn: Optional[Bridge] = None):
        self.conn = conn

    def open(self, test, node):
        return type(self)(connect(test, node))

    def _fail_or_info(self, op: Op, e: Exception) -> Op:
        if op.f == "read":
            return op.with_(type=FAIL, error=str(e))
        return op.with_(type=INFO, error=str(e))


class MapSetClient(_BridgeClient):
    """CAS-loop grow-only set in one map entry (hazelcast.clj:453-493)."""

    def __init__(self, conn=None, crdt: bool = False):
        super().__init__(conn)
        self.crdt = crdt
        self.name = "jepsen.crdt-map" if crdt else "jepsen.map"

    def open(self, test, node):
        return MapSetClient(connect(test, node), self.crdt)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                ok, why = self.conn.call("/map/add", name=self.name,
                                         v=op.value)
                return op.with_(type=OK if ok else FAIL,
                                error=None if ok else why)
            if op.f == "read":
                ok, payload = self.conn.call("/map/read", name=self.name)
                vals = [int(x) for x in payload.split(",") if x]
                return op.with_(type=OK, value=vals)
            raise ValueError(op.f)
        except (BridgeError, *NET_ERRORS) as e:
            return self._fail_or_info(op, e)


class LockClient(_BridgeClient):
    """Plain CP lock; op values carry the client uid
    (hazelcast.clj:412-448)."""

    def __init__(self, conn=None, name: str = "jepsen.lock"):
        super().__init__(conn)
        self.name = name

    def open(self, test, node):
        return LockClient(connect(test, node), self.name)

    def invoke(self, test, op: Op) -> Op:
        val = {"client": self.conn.uid}
        try:
            if op.f == "acquire":
                ok, why = self.conn.call("/lock/acquire", name=self.name)
                return op.with_(type=OK if ok else FAIL, value=val,
                                error=None if ok else why)
            if op.f == "release":
                ok, why = self.conn.call("/lock/release", name=self.name)
                return op.with_(type=OK if ok else FAIL, value=val,
                                error=None if ok else why)
            raise ValueError(op.f)
        except BridgeError as e:
            # IllegalMonitorState etc.: definite failures
            return op.with_(type=FAIL, value=val, error=str(e))
        except NET_ERRORS as e:
            return op.with_(type=INFO, value=val, error=str(e))


class FencedLockClient(_BridgeClient):
    """CP fenced lock: acquires return fencing tokens
    (hazelcast.clj:334-371)."""

    def __init__(self, conn=None, name: str = "jepsen.cpLock1"):
        super().__init__(conn)
        self.name = name

    def open(self, test, node):
        return FencedLockClient(connect(test, node), self.name)

    def invoke(self, test, op: Op) -> Op:
        val = {"client": self.conn.uid}
        try:
            if op.f == "acquire":
                ok, payload = self.conn.call("/fencedlock/acquire",
                                             name=self.name)
                if not ok:
                    return op.with_(type=FAIL, value=val, error=payload)
                return op.with_(type=OK,
                                value={**val, "fence": int(payload)})
            if op.f == "release":
                ok, why = self.conn.call("/fencedlock/release",
                                         name=self.name)
                return op.with_(type=OK if ok else FAIL, value=val,
                                error=None if ok else why)
            raise ValueError(op.f)
        except BridgeError as e:
            return op.with_(type=FAIL, value=val, error=str(e))
        except NET_ERRORS as e:
            return op.with_(type=INFO, value=val, error=str(e))


class SemaphoreClient(_BridgeClient):
    """CP semaphore with 2 permits (hazelcast.clj:373-410)."""

    NAME = "jepsen.semaphore"

    def setup(self, test):
        try:
            self.conn.call("/sem/init", name=self.NAME, permits=2)
        except (BridgeError, *NET_ERRORS):
            pass

    def invoke(self, test, op: Op) -> Op:
        val = {"client": self.conn.uid}
        try:
            ok, why = self.conn.call(f"/sem/{op.f}", name=self.NAME)
            return op.with_(type=OK if ok else FAIL, value=val,
                            error=None if ok else why)
        except BridgeError as e:
            return op.with_(type=FAIL, value=val, error=str(e))
        except NET_ERRORS as e:
            return op.with_(type=INFO, value=val, error=str(e))


class CasLongClient(_BridgeClient):
    """IAtomicLong as a CAS register (hazelcast.clj:190-209)."""

    NAME = "jepsen.cas-long"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                _, v = self.conn.call("/along/read", name=self.NAME)
                return op.with_(type=OK, value=int(v))
            if op.f == "write":
                self.conn.call("/along/set", name=self.NAME, v=op.value)
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = op.value
                ok, _ = self.conn.call("/along/cas", name=self.NAME,
                                       old=old, new=new)
                return op.with_(type=OK if ok else FAIL)
            raise ValueError(op.f)
        except (BridgeError, *NET_ERRORS) as e:
            return self._fail_or_info(op, e)


class CasReferenceClient(_BridgeClient):
    """IAtomicReference as a CAS register over strings
    (hazelcast.clj:211-231)."""

    NAME = "jepsen.cas-ref"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                _, v = self.conn.call("/aref/read", name=self.NAME)
                return op.with_(type=OK, value=int(v) if v else None)
            if op.f == "write":
                _, cur = self.conn.call("/aref/read", name=self.NAME)
                # write via cas loop on the reference (211-231 uses .set;
                # a blind set is fine through the bridge)
                ok, _ = self.conn.call("/aref/cas", name=self.NAME,
                                       old=cur, new=op.value)
                if not ok:
                    return op.with_(type=INFO, error="write-race")
                return op.with_(type=OK)
            if op.f == "cas":
                old, new = op.value
                ok, _ = self.conn.call("/aref/cas", name=self.NAME,
                                       old=old, new=new)
                return op.with_(type=OK if ok else FAIL)
            raise ValueError(op.f)
        except (BridgeError, *NET_ERRORS) as e:
            return self._fail_or_info(op, e)


class IdGenClient(_BridgeClient):
    """Unique-id generation via IAtomicLong or FlakeIdGenerator
    (hazelcast.clj:146-264)."""

    def __init__(self, conn=None, kind: str = "flake"):
        super().__init__(conn)
        self.kind = kind

    def open(self, test, node):
        return IdGenClient(connect(test, node), self.kind)

    def invoke(self, test, op: Op) -> Op:
        assert op.f == "generate"
        try:
            if self.kind == "flake":
                _, v = self.conn.call("/idgen/next", name="jepsen.idgen")
            else:
                _, v = self.conn.call("/along/inc", name="jepsen.along-id")
            return op.with_(type=OK, value=int(v))
        except (BridgeError, *NET_ERRORS) as e:
            return op.with_(type=INFO, error=str(e))


class QueueClient(_BridgeClient):
    """IQueue offer/poll + drain (hazelcast.clj:266-317)."""

    NAME = "jepsen.queue"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                ok, why = self.conn.call("/queue/offer", name=self.NAME,
                                         v=op.value)
                return op.with_(type=OK if ok else FAIL,
                                error=None if ok else why)
            if op.f == "dequeue":
                ok, v = self.conn.call("/queue/poll", name=self.NAME)
                if not ok:
                    return op.with_(type=FAIL, error=v)
                return op.with_(type=OK, value=int(v))
            if op.f == "drain":
                out = []
                while True:
                    ok, v = self.conn.call("/queue/poll", name=self.NAME,
                                           timeout=100)
                    if not ok:
                        return op.with_(type=OK, value=out)
                    out.append(int(v))
            raise ValueError(op.f)
        except (BridgeError, *NET_ERRORS) as e:
            if op.f in ("dequeue", "drain"):
                return op.with_(type=FAIL, error=str(e))
            return op.with_(type=INFO, error=str(e))
