// HTTP bridge exposing Hazelcast client operations to the test harness.
//
// Parity note: the reference suite drives Hazelcast through the official
// Java client in-process (hazelcast/src/jepsen/hazelcast.clj:119-448) and
// already ships suite-local Java (SetUnionMergePolicy).  This bridge is the
// same idea taken one step further: the harness is not JVM-hosted, so each
// db node runs this sidecar (compiled on-node against the distribution
// jars, like the reference compiles its C helpers on-node) and the Python
// client speaks plain HTTP to it.  One endpoint per operation the
// reference's clients perform.
//
// Endpoints (all GET, query params; response "ok:<value>" or "fail:<why>"):
//   /map/add?name=&v=        CAS-loop add of v into a sorted long-array set
//   /map/read?name=          comma-separated sorted values
//   /lock/acquire?name=&wait=  ILock/CP lock tryLock
//   /lock/release?name=
//   /fencedlock/acquire?name=  -> ok:<fence>
//   /fencedlock/release?name=
//   /sem/init?name=&permits=
//   /sem/acquire?name=   /sem/release?name=
//   /along/inc?name=     IAtomicLong incrementAndGet -> ok:<v>
//   /along/read?name=    /along/cas?name=&old=&new=
//   /aref/cas?name=&old=&new=   IAtomicReference (string payloads)
//   /aref/read?name=
//   /idgen/next?name=    FlakeIdGenerator newId -> ok:<v>
//   /queue/offer?name=&v=   /queue/poll?name=&timeout=ms
//   /uid                 client UUID (models key on it)

import com.hazelcast.client.HazelcastClient;
import com.hazelcast.client.config.ClientConfig;
import com.hazelcast.core.HazelcastInstance;
import com.hazelcast.cp.IAtomicLong;
import com.hazelcast.cp.IAtomicReference;
import com.hazelcast.cp.lock.FencedLock;
import com.hazelcast.collection.IQueue;
import com.hazelcast.cp.ISemaphore;
import com.hazelcast.flakeidgen.FlakeIdGenerator;
import com.hazelcast.map.IMap;
import com.sun.net.httpserver.HttpExchange;
import com.sun.net.httpserver.HttpServer;

import java.io.IOException;
import java.io.OutputStream;
import java.net.InetSocketAddress;
import java.util.Arrays;
import java.util.HashMap;
import java.util.Map;
import java.util.concurrent.Callable;
import java.util.concurrent.ConcurrentHashMap;
import java.util.concurrent.ExecutorService;
import java.util.concurrent.Executors;
import java.util.concurrent.TimeUnit;

public class JepsenBridge {
    // Lock ownership in Hazelcast is per client instance + thread, so each
    // harness client gets its own HazelcastInstance pinned to a dedicated
    // thread (the reference gives every Jepsen client its own instance,
    // hazelcast.clj:119-144).
    static final class Session {
        final HazelcastInstance hz;
        final ExecutorService exec;
        Session(HazelcastInstance hz) {
            this.hz = hz;
            this.exec = Executors.newSingleThreadExecutor();
        }
    }

    static final Map<String, Session> sessions = new ConcurrentHashMap<>();
    static String memberAddr;

    public static void main(String[] args) throws Exception {
        memberAddr = args[0];
        int port = Integer.parseInt(args[1]);
        HttpServer srv = HttpServer.create(new InetSocketAddress(port), 64);
        srv.createContext("/", JepsenBridge::handle);
        srv.setExecutor(Executors.newFixedThreadPool(32));
        srv.start();
        System.out.println("bridge listening on " + port);
    }

    static Session connectSession() {
        ClientConfig config = new ClientConfig();
        config.getNetworkConfig().addAddress(memberAddr);
        config.getConnectionStrategyConfig().getConnectionRetryConfig()
              .setClusterConnectTimeoutMillis(30000);
        return new Session(HazelcastClient.newHazelcastClient(config));
    }

    static <T> T onSession(Session s, Callable<T> task) throws Exception {
        return s.exec.submit(task).get(30, TimeUnit.SECONDS);
    }

    static Map<String, String> params(HttpExchange ex) {
        Map<String, String> out = new HashMap<>();
        String q = ex.getRequestURI().getRawQuery();
        if (q == null) return out;
        for (String kv : q.split("&")) {
            int i = kv.indexOf('=');
            if (i > 0) out.put(kv.substring(0, i), kv.substring(i + 1));
        }
        return out;
    }

    static void reply(HttpExchange ex, int code, String body)
            throws IOException {
        byte[] b = body.getBytes();
        ex.sendResponseHeaders(code, b.length);
        try (OutputStream os = ex.getResponseBody()) { os.write(b); }
    }

    static void handle(HttpExchange ex) throws IOException {
        String path = ex.getRequestURI().getPath();
        Map<String, String> p = params(ex);
        String name = p.get("name");
        try {
            if (path.equals("/connect")) {
                Session s = connectSession();
                String sid = java.util.UUID.randomUUID().toString();
                sessions.put(sid, s);
                reply(ex, 200, "ok:" + sid + ","
                      + s.hz.getLocalEndpoint().getUuid());
                return;
            }
            final Session s = sessions.get(p.get("session"));
            if (s == null) {
                reply(ex, 400, "err:unknown session");
                return;
            }
            switch (path) {
                case "/map/add": {
                    final long v = Long.parseLong(p.get("v"));
                    final String mapName = name;
                    boolean won = onSession(s, () -> {
                        IMap<String, long[]> m = s.hz.getMap(mapName);
                        long[] cur = m.get("hi");
                        if (cur == null)
                            return m.putIfAbsent("hi", new long[]{v}) == null;
                        long[] next = Arrays.copyOf(cur, cur.length + 1);
                        next[cur.length] = v;
                        Arrays.sort(next);
                        return m.replace("hi", cur, next);
                    });
                    reply(ex, 200, won ? "ok:" : "fail:cas");
                    return;
                }
                case "/map/read": {
                    final String mapName = name;
                    long[] cur = onSession(s, () -> {
                        IMap<String, long[]> m = s.hz.getMap(mapName);
                        return m.get("hi");
                    });
                    StringBuilder sb = new StringBuilder("ok:");
                    if (cur != null)
                        for (int i = 0; i < cur.length; i++) {
                            if (i > 0) sb.append(',');
                            sb.append(cur[i]);
                        }
                    reply(ex, 200, sb.toString());
                    return;
                }
                case "/lock/acquire": {
                    final long wait = Long.parseLong(
                        p.getOrDefault("wait", "5000"));
                    final String lockName = name;
                    boolean got = onSession(s, () ->
                        s.hz.getCPSubsystem().getLock(lockName)
                         .tryLock(wait, TimeUnit.MILLISECONDS));
                    reply(ex, 200, got ? "ok:" : "fail:timeout");
                    return;
                }
                case "/lock/release": {
                    final String lockName = name;
                    onSession(s, () -> {
                        s.hz.getCPSubsystem().getLock(lockName).unlock();
                        return null;
                    });
                    reply(ex, 200, "ok:");
                    return;
                }
                case "/fencedlock/acquire": {
                    final long wait = Long.parseLong(
                        p.getOrDefault("wait", "5000"));
                    final String lockName = name;
                    long fence = onSession(s, () ->
                        s.hz.getCPSubsystem().getLock(lockName)
                         .tryLockAndGetFence(wait, TimeUnit.MILLISECONDS));
                    if (fence == FencedLock.INVALID_FENCE)
                        reply(ex, 200, "fail:timeout");
                    else reply(ex, 200, "ok:" + fence);
                    return;
                }
                case "/fencedlock/release": {
                    final String lockName = name;
                    onSession(s, () -> {
                        s.hz.getCPSubsystem().getLock(lockName).unlock();
                        return null;
                    });
                    reply(ex, 200, "ok:");
                    return;
                }
                case "/sem/init": {
                    final int permits = Integer.parseInt(p.get("permits"));
                    final String semName = name;
                    onSession(s, () -> {
                        s.hz.getCPSubsystem().getSemaphore(semName)
                         .init(permits);
                        return null;
                    });
                    reply(ex, 200, "ok:");
                    return;
                }
                case "/sem/acquire": {
                    final long wait = Long.parseLong(
                        p.getOrDefault("wait", "5000"));
                    final String semName = name;
                    boolean got = onSession(s, () ->
                        s.hz.getCPSubsystem().getSemaphore(semName)
                         .tryAcquire(1, wait, TimeUnit.MILLISECONDS));
                    reply(ex, 200, got ? "ok:" : "fail:timeout");
                    return;
                }
                case "/sem/release": {
                    final String semName = name;
                    onSession(s, () -> {
                        s.hz.getCPSubsystem().getSemaphore(semName)
                         .release();
                        return null;
                    });
                    reply(ex, 200, "ok:");
                    return;
                }
                case "/along/inc": {
                    final String aName = name;
                    long v = onSession(s, () ->
                        s.hz.getCPSubsystem().getAtomicLong(aName)
                         .incrementAndGet());
                    reply(ex, 200, "ok:" + v);
                    return;
                }
                case "/along/read": {
                    final String aName = name;
                    long v = onSession(s, () ->
                        s.hz.getCPSubsystem().getAtomicLong(aName).get());
                    reply(ex, 200, "ok:" + v);
                    return;
                }
                case "/along/set": {
                    final String aName = name;
                    final long v = Long.parseLong(p.get("v"));
                    onSession(s, () -> {
                        s.hz.getCPSubsystem().getAtomicLong(aName).set(v);
                        return null;
                    });
                    reply(ex, 200, "ok:");
                    return;
                }
                case "/along/cas": {
                    final String aName = name;
                    final long oldV = Long.parseLong(p.get("old"));
                    final long newV = Long.parseLong(p.get("new"));
                    boolean ok = onSession(s, () ->
                        s.hz.getCPSubsystem().getAtomicLong(aName)
                         .compareAndSet(oldV, newV));
                    reply(ex, 200, ok ? "ok:" : "fail:cas");
                    return;
                }
                case "/aref/read": {
                    final String aName = name;
                    String v = onSession(s, () -> {
                        IAtomicReference<String> a =
                            s.hz.getCPSubsystem().getAtomicReference(aName);
                        return a.get();
                    });
                    reply(ex, 200, "ok:" + (v == null ? "" : v));
                    return;
                }
                case "/aref/cas": {
                    final String aName = name;
                    final String oldV = p.getOrDefault("old", "");
                    final String newV = p.get("new");
                    boolean ok = onSession(s, () -> {
                        IAtomicReference<String> a =
                            s.hz.getCPSubsystem().getAtomicReference(aName);
                        return a.compareAndSet(
                            oldV.isEmpty() ? null : oldV, newV);
                    });
                    reply(ex, 200, ok ? "ok:" : "fail:cas");
                    return;
                }
                case "/idgen/next": {
                    final String gName = name;
                    long v = onSession(s, () ->
                        s.hz.getFlakeIdGenerator(gName).newId());
                    reply(ex, 200, "ok:" + v);
                    return;
                }
                case "/queue/offer": {
                    final String qName = name;
                    final long v = Long.parseLong(p.get("v"));
                    boolean ok = onSession(s, () -> {
                        IQueue<Long> q = s.hz.getQueue(qName);
                        return q.offer(v, 5000, TimeUnit.MILLISECONDS);
                    });
                    reply(ex, 200, ok ? "ok:" : "fail:full");
                    return;
                }
                case "/queue/poll": {
                    final String qName = name;
                    final long timeout = Long.parseLong(
                        p.getOrDefault("timeout", "10"));
                    Long v = onSession(s, () -> {
                        IQueue<Long> q = s.hz.getQueue(qName);
                        return q.poll(timeout, TimeUnit.MILLISECONDS);
                    });
                    reply(ex, 200, v == null ? "fail:empty" : "ok:" + v);
                    return;
                }
                default:
                    reply(ex, 404, "fail:unknown " + path);
            }
        } catch (Exception e) {
            try {
                Throwable cause = e.getCause() != null ? e.getCause() : e;
                reply(ex, 500, "err:" + cause.getClass().getSimpleName()
                      + ": " + cause.getMessage());
            } catch (IOException ignored) { }
        }
    }
}
