// Split-brain merge policy that unions long-array "sets" instead of
// picking a winner — divergent map halves merge losslessly.
//
// Parity: the reference ships the same idea against the legacy
// MapMergePolicy SPI (hazelcast/server/java/jepsen/hazelcast/server/
// SetUnionMergePolicy.java); this is an independent implementation
// against the Hazelcast 5.x SplitBrainMergePolicy SPI.

package jepsen.hazelcast_server;

import com.hazelcast.spi.merge.MergingValue;
import com.hazelcast.spi.merge.SplitBrainMergePolicy;
import com.hazelcast.nio.ObjectDataInput;
import com.hazelcast.nio.ObjectDataOutput;

import java.io.IOException;
import java.util.TreeSet;

public class SetUnionMergePolicy
        implements SplitBrainMergePolicy<long[], MergingValue<long[]>,
                                         long[]> {

    @Override
    public long[] merge(MergingValue<long[]> merging,
                        MergingValue<long[]> existing) {
        TreeSet<Long> union = new TreeSet<>();
        if (merging != null && merging.getDeserializedValue() != null)
            for (long v : merging.getDeserializedValue()) union.add(v);
        if (existing != null && existing.getDeserializedValue() != null)
            for (long v : existing.getDeserializedValue()) union.add(v);
        long[] out = new long[union.size()];
        int i = 0;
        for (long v : union) out[i++] = v;
        return out;
    }

    @Override
    public void writeData(ObjectDataOutput out) throws IOException { }

    @Override
    public void readData(ObjectDataInput in) throws IOException { }
}
