"""YugabyteDB cluster install/start: yb-master quorum + yb-tserver per node.

Parity: yugabyte/src/yugabyte/auto.clj — masters on the first (up to) 3
nodes (master-nodes 57-67), master_addresses strings (74-82), separate
master/tserver daemons with their own log dirs (25-26), YSQL proxy on the
tservers.
"""

from __future__ import annotations

from typing import List

from jepsen_tpu import db as jdb
from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu

VERSION = "2.20.0.0"
BUILD = "b76"
URL = (f"https://downloads.yugabyte.com/releases/{VERSION}/"
       f"yugabyte-{VERSION}-{BUILD}-linux-x86_64.tar.gz")
DIR = "/opt/yugabyte"
DATA = "/opt/yugabyte/data"
MASTER_PID, MASTER_LOG = "/var/run/yb-master.pid", "/var/log/yb-master.log"
TSERVER_PID, TSERVER_LOG = ("/var/run/yb-tserver.pid",
                            "/var/log/yb-tserver.log")
MASTER_RPC_PORT = 7100
TSERVER_RPC_PORT = 9100
YSQL_PORT = 5433


def master_nodes(test) -> List[str]:
    """Replication-factor-many masters on the first nodes (auto.clj:57)."""
    rf = min(3, len(test["nodes"]))
    return list(test["nodes"])[:rf]


def master_addresses(test) -> str:
    return ",".join(f"{n}:{MASTER_RPC_PORT}" for n in master_nodes(test))


class YugabyteDB(jdb.DB, jdb.Kill, jdb.Pause, jdb.Primary, jdb.LogFiles):
    def setup(self, test, node):
        s = session(test, node).sudo()
        cu.install_archive(s, URL, DIR)
        s.exec("bash", "-c",
               f"[ -x {DIR}/bin/yb-master ] || "
               f"cp -r {DIR}/yugabyte-*/* {DIR}/ 2>/dev/null || true")
        s.exec("bash", "-c",
               f"{DIR}/bin/post_install.sh >/dev/null 2>&1 || true")
        s.exec("mkdir", "-p", DATA)
        self.start(test, node)
        cu.await_tcp_port(s, TSERVER_RPC_PORT, timeout_s=180)
        cu.await_tcp_port(s, YSQL_PORT, timeout_s=180)

    def teardown(self, test, node):
        s = session(test, node).sudo()
        for pid in (TSERVER_PID, MASTER_PID):
            cu.stop_daemon(s, pid)
        s.exec("rm", "-rf", DATA, MASTER_LOG, TSERVER_LOG)

    # -- role-specific lifecycle (auto.clj:51-54) --------------------------
    def start_master(self, test, node):
        if node not in master_nodes(test):
            return
        s = session(test, node).sudo()
        cu.start_daemon(
            s, f"{DIR}/bin/yb-master",
            "--master_addresses", master_addresses(test),
            "--rpc_bind_addresses", f"{node}:{MASTER_RPC_PORT}",
            "--fs_data_dirs", f"{DATA}/master",
            "--replication_factor", str(len(master_nodes(test))),
            pidfile=MASTER_PID, logfile=MASTER_LOG)

    def start_tserver(self, test, node):
        s = session(test, node).sudo()
        cu.start_daemon(
            s, f"{DIR}/bin/yb-tserver",
            "--tserver_master_addrs", master_addresses(test),
            "--rpc_bind_addresses", f"{node}:{TSERVER_RPC_PORT}",
            "--fs_data_dirs", f"{DATA}/tserver",
            "--start_pgsql_proxy",
            "--pgsql_proxy_bind_address", f"0.0.0.0:{YSQL_PORT}",
            pidfile=TSERVER_PID, logfile=TSERVER_LOG)

    def stop_master(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "yb-master")
        s.exec("rm", "-f", MASTER_PID)

    def stop_tserver(self, test, node):
        s = session(test, node).sudo()
        cu.grepkill(s, "yb-tserver")
        s.exec("rm", "-f", TSERVER_PID)

    # -- Kill capability ---------------------------------------------------
    def start(self, test, node):
        self.start_master(test, node)
        self.start_tserver(test, node)

    def kill(self, test, node):
        self.stop_master(test, node)
        self.stop_tserver(test, node)

    # -- Pause capability --------------------------------------------------
    def pause(self, test, node):
        s = session(test, node).sudo()
        for pat in ("yb-master", "yb-tserver"):
            cu.signal(s, pat, "STOP")

    def resume(self, test, node):
        s = session(test, node).sudo()
        for pat in ("yb-master", "yb-tserver"):
            cu.signal(s, pat, "CONT")

    # -- Primary capability ------------------------------------------------
    def primaries(self, test) -> List[str]:
        s = session(test, test["nodes"][0]).sudo()
        try:
            out = s.exec(f"{DIR}/bin/yb-admin",
                         "--master_addresses", master_addresses(test),
                         "list_all_masters")
            for line in out.splitlines():
                if "LEADER" in line:
                    for n in master_nodes(test):
                        if n in line:
                            return [n]
        except Exception:  # noqa: BLE001
            pass
        return []

    def setup_primary(self, test, node):
        pass

    # -- LogFiles capability -----------------------------------------------
    def log_files(self, test, node) -> List[str]:
        return [MASTER_LOG, TSERVER_LOG]
