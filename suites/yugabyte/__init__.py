"""yugabyte suite — YSQL workload registry with role-aware nemeses.

Parity: yugabyte/src/yugabyte/{core,auto,nemesis,runner}.clj plus the
ycql/ysql workload dirs (append, bank, counter, set, single/multi-key
acid, long-fork).  The reference's nemesis registry distinguishes master
vs tserver kills (nemesis.clj); mirrored here as suite-specific packages.
"""

from suites.yugabyte.runner import WORKLOADS, all_tests, yugabyte_test  # noqa: F401
