"""yugabyte suite CLI — YSQL registry + role-aware nemesis registry.

Parity: yugabyte/src/yugabyte/nemesis.clj's registry (kill/pause split by
master vs tserver role, partitions, clock) and core.clj's workload table
(append, bank, set, long-fork, single/multi-key acid ≈ register/wr here).
The reference's CI sweep driver (yugabyte/run-jepsen.py:34-59) maps to
``all_tests`` + ``jepsen_tpu.cli.test_all_cmd``.

    python -m suites.yugabyte.runner test --node n1 ... \
        --workload append --nemesis kill-master
"""

from __future__ import annotations

import random

from jepsen_tpu import generator as gen
from jepsen_tpu.clients.pgwire import PgClient
from jepsen_tpu.nemesis import combined
from jepsen_tpu.nemesis.faults import NodeStartStopper

from suites import common, sqlsuite
from suites.yugabyte import db as ydb
from suites.yugabyte.db import YSQL_PORT, YugabyteDB


def conn(node, test):
    return PgClient(node,
                    port=int(test.get("db_port", YSQL_PORT)),
                    user=test.get("db_user", "yugabyte"),
                    database=test.get("db_name", "yugabyte")).connect()


def _role_package(opts, role: str) -> combined.Package:
    """Kill-and-restart one process role on a random node
    (yugabyte/nemesis.clj's kill-master / kill-tserver packages)."""
    db = YugabyteDB()
    stop = getattr(db, f"stop_{role}")
    start = getattr(db, f"start_{role}")

    def targeter(test, nodes):
        pool = ydb.master_nodes(test) if role == "master" else nodes
        return [random.choice(pool)]

    nem = NodeStartStopper(targeter=targeter, stop_fn=stop, start_fn=start)
    interval = opts.get("interval", 10.0)
    g = gen.stagger(interval, gen.cycle(gen.lift([
        {"f": "start", "type": "info"},
        {"f": "stop", "type": "info"}])))
    return combined.Package(nemesis=nem, generator=g,
                            final_generator=[{"f": "stop", "type": "info"}])


NEMESES = dict(common.STANDARD_NEMESES)
NEMESES["kill-master"] = lambda opts: _role_package(opts, "master")
NEMESES["kill-tserver"] = lambda opts: _role_package(opts, "tserver")

WORKLOADS, yugabyte_test, all_tests, main = sqlsuite.make_suite(
    "yugabyte", YugabyteDB(), conn, nemeses=NEMESES,
    default_workload="append")


if __name__ == "__main__":
    import sys
    sys.exit(main())
