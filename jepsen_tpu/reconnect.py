"""Auto-reconnecting connection wrapper.

Parity: jepsen.reconnect (jepsen/src/jepsen/reconnect.clj:17-151): wraps a
flaky connection with an RW lock; operations share the connection, errors
close it, and the next caller reopens.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional


class Wrapper:
    def __init__(self, open_fn: Callable[[], Any],
                 close_fn: Callable[[Any], None] = lambda c: None,
                 log_name: str = "conn"):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.log_name = log_name
        self._conn: Optional[Any] = None
        self._lock = threading.RLock()

    def conn(self) -> Any:
        with self._lock:
            if self._conn is None:
                self._conn = self.open_fn()
            return self._conn

    def reopen(self) -> None:
        with self._lock:
            self.close()
            self._conn = self.open_fn()

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                try:
                    self.close_fn(self._conn)
                finally:
                    self._conn = None

    def with_conn(self, f: Callable[[Any], Any], retries: int = 1,
                  backoff_s: float = 0.0) -> Any:
        """Run ``f(conn)``; on error, drop the connection so the next call
        reconnects, optionally retrying here."""
        attempts = retries + 1
        last: Optional[Exception] = None
        for i in range(attempts):
            try:
                return f(self.conn())
            except Exception as e:  # noqa: BLE001
                last = e
                self.close()
                if backoff_s and i + 1 < attempts:
                    time.sleep(backoff_s)
        raise last
