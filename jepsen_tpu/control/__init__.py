"""Control facade: per-node sessions, command sugar, and parallel fan-out.

Parity: jepsen.control (jepsen/src/jepsen/control.clj).  Where the reference
uses dynamic vars (*host*, *session*, *sudo*...) rebound per node
(control.clj:43-57), this facade is explicit and immutable: a
:class:`Session` binds a connected Remote to one node, and ``cd``/``sudo``/
``env`` return derived session views.  ``on_nodes`` is the parallel fan-out
(control.clj:299-315, via real-pmap).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Optional, Sequence

from jepsen_tpu.control.core import (  # noqa: F401
    CmdResult, Lit, Remote, RemoteCommandFailed, RemoteConnectError, build_cmd,
    env_str, escape,
)
from jepsen_tpu.control.remotes import (  # noqa: F401
    DockerExec, DummyRemote, K8sExec, SshRemote, list_pods,
)
from jepsen_tpu.control.retry import (  # noqa: F401
    RetryPolicy, RetryRemote, policy_for, retrying,
)


@dataclass
class Session:
    """A connected control channel to one node, plus execution context."""

    remote: Remote
    node: str
    ctx: Dict[str, Any] = field(default_factory=dict)
    trace: bool = False

    # -- context derivation (control.clj:207-228 cd/sudo/su macros) -------
    def cd(self, d: str) -> "Session":
        return replace(self, ctx={**self.ctx, "dir": d})

    def sudo(self, user: Any = True) -> "Session":
        return replace(self, ctx={**self.ctx, "sudo": user})

    def env(self, **env) -> "Session":
        return replace(self, ctx={**self.ctx,
                                  "env": {**self.ctx.get("env", {}), **env}})

    def with_trace(self) -> "Session":
        return replace(self, trace=True)

    # -- execution (control.clj:142-161 exec/exec*) -----------------------
    def exec_result(self, *parts, stdin: Optional[str] = None) -> CmdResult:
        cmd = build_cmd(*parts)
        if self.trace:
            import logging
            logging.getLogger("jepsen.control").info(
                "[%s] %s", self.node, cmd)
        return self.remote.execute(self.ctx, cmd, stdin=stdin)

    def exec(self, *parts, stdin: Optional[str] = None) -> str:
        res = self.exec_result(*parts, stdin=stdin)
        res.throw_on_nonzero(f"on {self.node}")
        return res.out.strip()

    def upload(self, local_paths, remote_path: str) -> None:
        if isinstance(local_paths, str):
            local_paths = [local_paths]
        self.remote.upload(self.ctx, local_paths, remote_path)

    def download(self, remote_paths, local_path: str) -> None:
        if isinstance(remote_paths, str):
            remote_paths = [remote_paths]
        self.remote.download(self.ctx, remote_paths, local_path)

    def disconnect(self) -> None:
        self.remote.disconnect()


def conn_spec(test: Dict[str, Any], node: str) -> Dict[str, Any]:
    """Connection spec for a node from the test's ssh options
    (control.clj's conn-spec)."""
    ssh = test.get("ssh", {})
    return {"host": node,
            "port": ssh.get("port", 22),
            "user": ssh.get("username", "root"),
            "password": ssh.get("password"),
            "private_key_path": ssh.get("private_key_path"),
            "strict_host_key_checking":
                ssh.get("strict_host_key_checking", False),
            "namespace": ssh.get("namespace", "default")}


def remote_for(test: Dict[str, Any]) -> Remote:
    """Choose the Remote prototype for a test: test["remote"] wins; dummy
    mode (ssh {dummy: true}) routes everything to the local dummy.  The
    default SSH transport is wrapped in the retrying proxy under the test's
    setup-phase policy (control/retry.clj parity — see control.retry)."""
    r = test.get("remote")
    if r is not None:
        return r
    dummy = test.get("ssh", {}).get("dummy")
    if dummy == "record":
        return DummyRemote(record_only=True)
    if dummy:
        return DummyRemote()
    return RetryRemote(SshRemote(), policy=policy_for(test, "setup"))


def setup_sessions(test: Dict[str, Any]) -> Dict[str, Session]:
    """Connect a session per node, in parallel (core.clj with-sessions)."""
    proto = remote_for(test)
    nodes = list(test.get("nodes") or [])

    def conn(node):
        return Session(remote=proto.connect(conn_spec(test, node)), node=node)

    with ThreadPoolExecutor(max_workers=max(1, len(nodes))) as ex:
        sessions = dict(zip(nodes, ex.map(conn, nodes)))
    test["sessions"] = sessions
    return sessions


def teardown_sessions(test: Dict[str, Any]) -> None:
    for s in (test.get("sessions") or {}).values():
        try:
            s.disconnect()
        except Exception:  # noqa: BLE001
            pass
    test.pop("sessions", None)


def session(test: Dict[str, Any], node: str) -> Session:
    sessions = test.get("sessions")
    if not sessions or node not in sessions:
        raise RuntimeError(f"no session for node {node!r}; "
                           "run inside setup_sessions")
    return sessions[node]


def on_nodes(test: Dict[str, Any],
             f: Callable[[Dict[str, Any], str], Any],
             nodes: Optional[Sequence[str]] = None,
             phase: Optional[str] = None) -> Dict[str, Any]:
    """Run ``f(test, node)`` on each node concurrently, with that node's
    session reachable via ``session(test, node)``; returns {node: result}
    (control.clj:299-315).

    With ``phase`` given ("setup"/"run"/"teardown"), each node's closure is
    wrapped in :func:`~jepsen_tpu.control.retry.retrying` under the test's
    policy for that phase: a node that flaps mid-setup gets its whole
    per-node closure replayed after the transport reconnects, instead of
    failing the fan-out (control/retry.clj parity above the session layer —
    the reference retries per command; replaying the idempotent setup
    closure also covers multi-command sequences that died halfway)."""
    ns = list(nodes if nodes is not None else test.get("nodes") or [])
    if not ns:
        return {}
    if phase is not None:
        policy = policy_for(test, phase)
        inner = f

        def f(t, node):  # noqa: F811 - deliberate retrying shadow
            return retrying(lambda: inner(t, node), policy)

    with ThreadPoolExecutor(max_workers=len(ns)) as ex:
        futs = {n: ex.submit(f, test, n) for n in ns}
        return {n: fut.result() for n, fut in futs.items()}
