"""Remote implementations: local-exec dummy, OpenSSH subprocess, docker exec,
kubectl exec, and the auto-retry wrapper.

Parity map (SURVEY.md §5.8):
- DummyRemote  — the reference's :dummy no-op session
  (control/clj_ssh.clj:55-56): full-pipeline tests with no cluster.
  Ours actually executes locally (sandboxed to a scratch dir) so control
  utilities are testable for real.
- SshRemote    — the default transport (control/sshj.clj).  Uses the
  OpenSSH client with ControlMaster connection sharing: one authenticated
  connection per node, multiplexed channels per command — the same design
  point as the reference's one-SSHJ-connection + bounded channels
  (control/sshj.clj:181-187).
- DockerExec   — `docker exec` remote (control/docker.clj:30-76).
- K8sExec      — `kubectl exec` remote (control/k8s.clj:14-95).

The reconnect/backoff wrapper (RetryRemote, control/retry.clj parity) lives
in jepsen_tpu.control.retry and is re-exported here for compatibility.
"""

from __future__ import annotations

import os
import re
import subprocess
import tempfile
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu.control.core import (
    CmdResult, Remote, RemoteConnectError, wrap_context,
)
from jepsen_tpu.control.retry import RetryPolicy, RetryRemote  # noqa: F401

DEFAULT_TIMEOUT = 600.0


def _run(argv: Sequence[str], stdin: Optional[str] = None,
         timeout: float = DEFAULT_TIMEOUT) -> CmdResult:
    try:
        p = subprocess.run(list(argv), input=stdin, capture_output=True,
                           text=True, timeout=timeout)
    except FileNotFoundError as e:
        raise RemoteConnectError(str(e)) from e
    except subprocess.TimeoutExpired as e:
        return CmdResult(cmd=" ".join(argv), exit=124,
                         out=(e.stdout or ""), err=f"timeout after {timeout}s")
    return CmdResult(cmd=" ".join(argv), exit=p.returncode,
                     out=p.stdout, err=p.stderr)


class DummyRemote(Remote):
    """Executes commands locally under bash, or records them without running
    (``record_only=True``) — both modes unlock full-pipeline tests with no
    cluster, like the reference's dummy session."""

    def __init__(self, record_only: bool = False,
                 responses: Optional[Dict[str, str]] = None):
        # responses: regex -> canned stdout for record-only runs whose DB
        # setup parses command output (roster waits, version probes, …)
        self.record_only = record_only
        self.responses = responses or {}
        self.log: List[str] = []
        self.host: Optional[str] = None

    def connect(self, conn_spec):
        r = DummyRemote(self.record_only, self.responses)
        r.log = self.log  # shared command journal across nodes
        r.host = conn_spec.get("host")
        return r

    def execute(self, ctx, cmd, stdin=None):
        full = wrap_context(dict(ctx, sudo=None), cmd)  # no sudo locally
        self.log.append(f"{self.host}: {full}")
        if self.record_only:
            out = ""
            for pattern, canned in self.responses.items():
                if re.search(pattern, full):
                    out = canned
                    break
            return CmdResult(cmd=full, exit=0, out=out, err="")
        return _run(["bash", "-c", full], stdin=stdin)

    def upload(self, ctx, local_paths, remote_path):
        self.log.append(f"{self.host}: upload {local_paths} -> {remote_path}")
        if not self.record_only:
            import shutil
            for lp in local_paths:
                shutil.copy(lp, remote_path)

    def download(self, ctx, remote_paths, local_path):
        self.log.append(f"{self.host}: download {remote_paths} -> {local_path}")
        if not self.record_only:
            import shutil
            for rp in remote_paths:
                if os.path.exists(rp):
                    shutil.copy(rp, local_path)


class SshRemote(Remote):
    """OpenSSH with ControlMaster multiplexing: connect() establishes the
    master; each execute is a cheap multiplexed channel."""

    def __init__(self):
        self.spec: Dict[str, Any] = {}
        self.ctrl_path: Optional[str] = None

    # -- connection -------------------------------------------------------
    def connect(self, conn_spec):
        r = SshRemote()
        r.spec = dict(conn_spec)
        d = tempfile.mkdtemp(prefix="jt-ssh-")
        r.ctrl_path = os.path.join(d, "ctl")
        res = _run(r._ssh_argv(master=True) + ["true"],
                   timeout=conn_spec.get("connect_timeout", 30))
        if res.exit != 0:
            raise RemoteConnectError(
                f"ssh to {r._dest()} failed: {res.err.strip()}")
        return r

    def _dest(self) -> str:
        user = self.spec.get("user", "root")
        return f"{user}@{self.spec.get('host')}"

    def _common_opts(self) -> List[str]:
        """Shared -o options for ssh AND scp.  Default: keys unchecked and
        the user's known_hosts untouched (the reference's default,
        cli.clj:82-84).  With strict checking requested, the known-hosts
        override must NOT apply — /dev/null knows no keys, and with
        BatchMode forbidding the accept prompt the connection could never
        succeed."""
        opts = ["-o", "BatchMode=yes", "-o", "LogLevel=ERROR"]
        if self.spec.get("strict_host_key_checking"):
            opts += ["-o", "StrictHostKeyChecking=yes"]
        else:
            opts += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null"]
        return opts

    def _ssh_argv(self, master: bool = False) -> List[str]:
        argv = (["ssh"] + self._common_opts()
                + ["-p", str(self.spec.get("port", 22))])
        if self.ctrl_path:
            argv += ["-o", f"ControlPath={self.ctrl_path}"]
            if master:
                argv += ["-o", "ControlMaster=auto",
                         "-o", "ControlPersist=600"]
        pk = self.spec.get("private_key_path")
        if pk:
            argv += ["-i", pk]
        argv.append(self._dest())
        return argv

    def disconnect(self):
        if self.ctrl_path and os.path.exists(self.ctrl_path):
            _run(["ssh", "-o", f"ControlPath={self.ctrl_path}",
                  "-O", "exit", self._dest()], timeout=10)

    # -- operations -------------------------------------------------------
    def execute(self, ctx, cmd, stdin=None):
        full = wrap_context(ctx, cmd)
        return _run(self._ssh_argv() + [full], stdin=stdin)

    def _scp_base(self) -> List[str]:
        argv = (["scp"] + self._common_opts()
                + ["-P", str(self.spec.get("port", 22))])
        if self.ctrl_path:
            argv += ["-o", f"ControlPath={self.ctrl_path}"]
        pk = self.spec.get("private_key_path")
        if pk:
            argv += ["-i", pk]
        return argv

    def upload(self, ctx, local_paths, remote_path):
        res = _run(self._scp_base() + list(local_paths)
                   + [f"{self._dest()}:{remote_path}"])
        res.throw_on_nonzero("upload")

    def download(self, ctx, remote_paths, local_path):
        res = _run(self._scp_base()
                   + [f"{self._dest()}:{p}" for p in remote_paths]
                   + [local_path])
        res.throw_on_nonzero("download")


class DockerExec(Remote):
    """Runs commands in a container via docker exec
    (control/docker.clj:30-76)."""

    def __init__(self, container_prefix: str = ""):
        self.container_prefix = container_prefix
        self.container: Optional[str] = None

    def connect(self, conn_spec):
        r = DockerExec(self.container_prefix)
        r.container = self.container_prefix + conn_spec["host"]
        return r

    def execute(self, ctx, cmd, stdin=None):
        full = wrap_context(ctx, cmd)
        return _run(["docker", "exec", "-i", self.container,
                     "bash", "-c", full], stdin=stdin)

    def upload(self, ctx, local_paths, remote_path):
        for lp in local_paths:
            _run(["docker", "cp", lp,
                  f"{self.container}:{remote_path}"]).throw_on_nonzero()

    def download(self, ctx, remote_paths, local_path):
        for rp in remote_paths:
            _run(["docker", "cp", f"{self.container}:{rp}",
                  local_path]).throw_on_nonzero()


class K8sExec(Remote):
    """Runs commands in a pod via kubectl exec (control/k8s.clj:14-95)."""

    def __init__(self, namespace: str = "default"):
        self.namespace = namespace
        self.pod: Optional[str] = None

    def connect(self, conn_spec):
        r = K8sExec(conn_spec.get("namespace", self.namespace))
        r.pod = conn_spec["host"]
        return r

    def execute(self, ctx, cmd, stdin=None):
        full = wrap_context(ctx, cmd)
        return _run(["kubectl", "-n", self.namespace, "exec", "-i", self.pod,
                     "--", "bash", "-c", full], stdin=stdin)

    def upload(self, ctx, local_paths, remote_path):
        for lp in local_paths:
            _run(["kubectl", "-n", self.namespace, "cp", lp,
                  f"{self.pod}:{remote_path}"]).throw_on_nonzero()

    def download(self, ctx, remote_paths, local_path):
        for rp in remote_paths:
            _run(["kubectl", "-n", self.namespace, "cp",
                  f"{self.pod}:{rp}", local_path]).throw_on_nonzero()


def list_pods(namespace: str = "default") -> List[str]:
    res = _run(["kubectl", "-n", namespace, "get", "pods",
                "-o", "jsonpath={.items[*].metadata.name}"])
    res.throw_on_nonzero()
    return res.out.split()
