"""Retrying control plane — policies, the ``retrying`` combinator, and the
reconnect-on-failure Remote wrapper.

Parity: jepsen.control.retry (jepsen/src/jepsen/control/retry.clj): the
reference wraps every control-plane session in a retrying proxy that
catches connection-level failures, tears the dead connection down, backs
off, reconnects, and replays the operation — so a transient node flap
during OS/DB setup (or a mid-run log snarf) costs a pause, not the run.
Our :class:`RetryRemote` is that proxy; :func:`retrying` is the underlying
combinator (usable around any control-plane call, e.g. a whole per-node
setup closure in ``on_nodes``); :class:`RetryPolicy` makes the reference's
hard-coded 5-tries/1-s loop configurable per phase.

Only :class:`~jepsen_tpu.control.core.RemoteConnectError` (and whatever a
policy adds) is retried: a command that *ran* and exited nonzero is a
result, not a flap — replaying it could double-apply side effects.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, Optional, Tuple, Type

from jepsen_tpu.control.core import Remote, RemoteConnectError

logger = logging.getLogger("jepsen.control.retry")


@dataclass(frozen=True)
class RetryPolicy:
    """How hard to try: ``tries`` total attempts; exponential backoff
    starting at ``backoff_s`` and doubling up to ``max_backoff_s``; each
    delay jittered by ±``jitter`` (a fraction) so a cluster-wide flap
    doesn't have every node's session reconnect in lockstep.  ``retry_on``
    is the exception allowlist (connection-level failures only, by
    default — see module docstring).

    ``decorrelated=True`` switches to decorrelated jitter: each delay is
    drawn uniformly from ``[backoff_s, 3 * previous_delay]`` (capped at
    ``max_backoff_s``) instead of a jittered deterministic ladder.  The
    fleet's hedge/reroute loop uses this: with plain ±25% jitter, N
    workers that all saw the same sibling die retry inside one narrow
    band and arrive as a synchronized storm on the survivor; the
    decorrelated draw spreads the whole interval."""

    tries: int = 5
    backoff_s: float = 1.0
    max_backoff_s: float = 30.0
    jitter: float = 0.25
    decorrelated: bool = False
    retry_on: Tuple[Type[BaseException], ...] = (RemoteConnectError,)

    def delay(self, attempt: int, rng=random,
              prev: Optional[float] = None) -> float:
        """The pause before retry ``attempt + 1``.  ``prev`` (the delay
        actually slept last time) only matters to the decorrelated mode;
        callers that don't thread it through still get valid — merely
        less spread-out — delays."""
        if self.decorrelated:
            lo = max(0.0, self.backoff_s)
            hi = max(lo, 3.0 * (prev if prev is not None else lo))
            return min(rng.uniform(lo, hi), self.max_backoff_s)
        d = min(self.backoff_s * (2 ** attempt), self.max_backoff_s)
        if self.jitter:
            d *= 1.0 + rng.uniform(-self.jitter, self.jitter)
        return max(0.0, d)


#: Per-phase defaults.  Setup is patient (a rebooting node can take a
#: while to accept connections); the run phase is tight (a worker stuck
#: replaying control commands distorts the history's timing); teardown
#: sits between (heal MUST eventually land, but shouldn't hang exit).
DEFAULT_POLICIES: Dict[str, RetryPolicy] = {
    "setup": RetryPolicy(tries=8, backoff_s=1.0),
    "run": RetryPolicy(tries=3, backoff_s=0.25, max_backoff_s=2.0),
    "teardown": RetryPolicy(tries=5, backoff_s=0.5, max_backoff_s=8.0),
}


def policy_for(test: Optional[Dict[str, Any]], phase: str = "run") \
        -> RetryPolicy:
    """The retry policy for a phase.  ``test["retry"]`` may be a
    :class:`RetryPolicy` (applies to every phase), or a dict of
    phase -> policy (or kwargs dict), with ``"default"`` as the fallback
    key; absent, the module defaults apply."""
    spec = (test or {}).get("retry")
    if isinstance(spec, RetryPolicy):
        return spec
    if isinstance(spec, dict):
        sub = spec.get(phase, spec.get("default"))
        if isinstance(sub, RetryPolicy):
            return sub
        if isinstance(sub, dict):
            return RetryPolicy(**sub)
    return DEFAULT_POLICIES.get(phase, RetryPolicy())


def retrying(f: Callable[[], Any], policy: Optional[RetryPolicy] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             sleep: Callable[[float], None] = time.sleep) -> Any:
    """Call ``f()`` under ``policy``: on a retriable exception, back off
    and try again, up to ``policy.tries`` attempts total.  ``on_retry``
    runs between attempts (the reconnect hook); its own retriable failures
    are swallowed — the next attempt will surface them."""
    policy = policy or RetryPolicy()
    last: Optional[BaseException] = None
    prev_delay: Optional[float] = None
    for attempt in range(max(1, policy.tries)):
        try:
            return f()
        except policy.retry_on as e:  # type: ignore[misc]
            last = e
            if attempt + 1 >= max(1, policy.tries):
                break
            logger.warning("retriable failure (attempt %d/%d): %s",
                           attempt + 1, policy.tries, e)
            prev_delay = policy.delay(attempt, prev=prev_delay)
            sleep(prev_delay)
            if on_retry is not None:
                try:
                    on_retry(attempt, e)
                except policy.retry_on:  # type: ignore[misc]
                    pass
    raise last  # type: ignore[misc]


class RetryRemote(Remote):
    """Reconnect-and-retry proxy around a Remote (control/retry.clj:15-67).

    Every operation retries under the policy; between attempts the (likely
    dead) connection is dropped so the next attempt dials fresh.  Connect
    itself retries too, which is what lets ``setup_sessions``'s fan-out
    survive a node that flaps during cluster bring-up.

    ``tries``/``backoff_s`` kwargs are accepted for compatibility with the
    original fixed-loop wrapper and fold into the policy."""

    def __init__(self, inner: Remote, policy: Optional[RetryPolicy] = None,
                 tries: Optional[int] = None,
                 backoff_s: Optional[float] = None):
        p = policy or RetryPolicy()
        if tries is not None:
            p = replace(p, tries=tries)
        if backoff_s is not None:
            p = replace(p, backoff_s=backoff_s)
        self.proto = inner
        self.policy = p
        self.inner: Optional[Remote] = None
        self.spec: Dict[str, Any] = {}
        # One connection per RetryRemote, but retries may race a concurrent
        # caller's reconnect (on_nodes fans out over *sessions*, each with
        # its own RetryRemote, so this lock is rarely contended).
        self._lock = threading.Lock()

    def connect(self, conn_spec):
        r = RetryRemote(self.proto, self.policy)
        r.spec = dict(conn_spec)
        r.inner = retrying(lambda: self.proto.connect(r.spec), r.policy)
        return r

    def _drop_conn(self, attempt: int, exc: BaseException) -> None:
        with self._lock:
            old, self.inner = self.inner, None
        if old is not None:
            try:
                old.disconnect()
            except Exception:  # noqa: BLE001 - it's already dead
                pass

    def _with_conn(self, f: Callable[[Remote], Any]) -> Any:
        def attempt():
            with self._lock:
                if self.inner is None:
                    self.inner = self.proto.connect(self.spec)
                conn = self.inner
            return f(conn)

        return retrying(attempt, self.policy, on_retry=self._drop_conn)

    def disconnect(self):
        with self._lock:
            old, self.inner = self.inner, None
        if old is not None:
            old.disconnect()

    def execute(self, ctx, cmd, stdin=None):
        return self._with_conn(lambda c: c.execute(ctx, cmd, stdin))

    def upload(self, ctx, local_paths, remote_path):
        return self._with_conn(
            lambda c: c.upload(ctx, local_paths, remote_path))

    def download(self, ctx, remote_paths, local_path):
        return self._with_conn(
            lambda c: c.download(ctx, remote_paths, local_path))
