"""Remote protocol — the pluggable command/file transport to cluster nodes.

Parity: jepsen.control.core (jepsen/src/jepsen/control/core.clj:7-58): a
Remote connects to a node and can execute commands and move files.  The
shell-escaping, env-var, and sudo-wrapping helpers (core.clj:67-155) live
here too; everything above (the facade, fan-out) is jepsen_tpu.control.

This is the *control plane* backend (SURVEY.md §5.8): host-side I/O over
SSH/exec — deliberately not device code.  The data plane (history analysis)
talks XLA collectives instead.
"""

from __future__ import annotations

import shlex
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union


@dataclass
class CmdResult:
    cmd: str
    exit: int
    out: str
    err: str

    def throw_on_nonzero(self, context: str = ""):
        if self.exit != 0:
            raise RemoteCommandFailed(self, context)
        return self

    @property
    def ok(self) -> bool:
        return self.exit == 0


class RemoteError(Exception):
    pass


class RemoteConnectError(RemoteError):
    """Connection-level failure — retriable (control/retry.clj:15-67)."""


class RemoteCommandFailed(RemoteError):
    """Command ran but exited nonzero (core.clj:155's throw+)."""

    def __init__(self, result: CmdResult, context: str = ""):
        super().__init__(
            f"command failed ({result.exit}): {result.cmd!r}"
            + (f" [{context}]" if context else "")
            + (f"\nstdout: {result.out.strip()}" if result.out.strip() else "")
            + (f"\nstderr: {result.err.strip()}" if result.err.strip() else ""))
        self.result = result


class Remote:
    """Transport to one node.  Implementations are context managers."""

    def connect(self, conn_spec: Dict[str, Any]) -> "Remote":
        """Open a connection per the spec {host, port, user, ...}; returns
        the connected remote (often self)."""
        return self

    def disconnect(self) -> None:
        pass

    def execute(self, ctx: Dict[str, Any], cmd: str,
                stdin: Optional[str] = None) -> CmdResult:
        """Run a shell command; ctx may carry {dir, sudo, env}."""
        raise NotImplementedError

    def upload(self, ctx: Dict[str, Any], local_paths: Sequence[str],
               remote_path: str) -> None:
        raise NotImplementedError

    def download(self, ctx: Dict[str, Any], remote_paths: Sequence[str],
                 local_path: str) -> None:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.disconnect()


# ---------------------------------------------------------------------------
# Command construction helpers
# ---------------------------------------------------------------------------


def escape(arg: Any) -> str:
    """Shell-escape one argument (core.clj:67-110)."""
    return shlex.quote(str(arg))


class Lit:
    """A literal command fragment that must NOT be escaped (the reference's
    jepsen.control/lit)."""

    def __init__(self, s: str):
        self.s = s

    def __str__(self):
        return self.s


def build_cmd(*parts: Any) -> str:
    """Join command parts, escaping everything but Lit fragments."""
    out = []
    for p in parts:
        if isinstance(p, Lit):
            out.append(str(p))
        else:
            out.append(escape(p))
    return " ".join(out)


def env_str(env: Dict[str, Any]) -> str:
    """KEY=val prefix string (core.clj:112)."""
    return " ".join(f"{k}={escape(v)}" for k, v in sorted(env.items()))


def wrap_context(ctx: Dict[str, Any], cmd: str) -> str:
    """Apply {env, dir, sudo, su} context to a command string
    (core.clj:142's wrap-sudo + the facade's cd/su)."""
    env = ctx.get("env")
    if env:
        cmd = f"env {env_str(env)} {cmd}"
    d = ctx.get("dir")
    if d:
        cmd = f"cd {escape(d)} && {cmd}"
    user = ctx.get("sudo")
    if user is True:
        user = "root"
    if user:
        cmd = f"sudo -S -u {escape(user)} bash -c {escape(cmd)}"
    return cmd
