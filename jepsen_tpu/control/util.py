"""Node-side helpers built on the control facade.

Parity: jepsen.control.util (jepsen/src/jepsen/control/util.clj): daemon
management with pidfiles, package download/installation with a control-side
cache, process signalling, and small file utilities.  All functions take a
:class:`~jepsen_tpu.control.Session`.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

from jepsen_tpu.clock import mono_now
from jepsen_tpu.control import Lit, RemoteCommandFailed, Session


def exists(s: Session, path: str) -> bool:
    return s.exec_result("test", "-e", path).ok


def await_tcp_port(s: Session, port: int, timeout_s: float = 60,
                   interval_s: float = 0.5) -> None:
    """Block until something listens on ``port`` (util.clj:14)."""
    deadline = mono_now() + timeout_s
    while mono_now() < deadline:
        if s.exec_result("bash", "-c",
                         f"exec 3<>/dev/tcp/localhost/{port}").ok:
            return
        time.sleep(interval_s)
    raise TimeoutError(f"port {port} on {s.node} not open "
                       f"after {timeout_s}s")


def tmp_file(s: Session, suffix: str = "") -> str:
    return s.exec("mktemp", f"--suffix={suffix}" if suffix else "--suffix=")


def tmp_dir(s: Session) -> str:
    return s.exec("mktemp", "-d")


def write_file(s: Session, content: str, path: str) -> None:
    """Write a string to a node-side file (util.clj:88)."""
    s.exec("tee", path, stdin=content)


def wget(s: Session, url: str, dest: Optional[str] = None,
         force: bool = False) -> str:
    """Download a URL on the node (util.clj:133)."""
    name = dest or url.rstrip("/").rsplit("/", 1)[-1]
    if force or not exists(s, name):
        s.exec("wget", "-q", "-O", name, url)
    return name


def cached_wget(s: Session, url: str,
                cache_dir: str = "/tmp/jepsen/cache") -> str:
    """Download once per node, keyed by URL hash (util.clj:167)."""
    import hashlib
    h = hashlib.sha256(url.encode()).hexdigest()[:16]
    path = f"{cache_dir}/{h}"
    if not exists(s, path):
        s.exec("mkdir", "-p", cache_dir)
        s.exec("wget", "-q", "-O", path + ".tmp", url)
        s.exec("mv", path + ".tmp", path)
    return path


def install_archive(s: Session, url: str, dest: str,
                    force: bool = False) -> str:
    """Download and unpack a tarball/zip into ``dest``, stripping a single
    top-level directory if present (util.clj:199)."""
    if exists(s, dest) and not force:
        return dest
    local = cached_wget(s, url)
    tmp = tmp_dir(s)
    if url.endswith(".zip"):
        s.exec("unzip", "-q", local, "-d", tmp)
    else:
        s.exec("tar", "-xf", local, "-C", tmp)
    entries = s.exec("ls", "-A", tmp).split()
    s.exec("rm", "-rf", dest)
    s.exec("mkdir", "-p", Lit(f"$(dirname {dest})"))
    if len(entries) == 1:
        s.exec("mv", f"{tmp}/{entries[0]}", dest)
        s.exec("rm", "-rf", tmp)
    else:
        s.exec("mv", tmp, dest)
    return dest


def ensure_user(s: Session, username: str) -> None:
    """Create a user if absent (util.clj:277)."""
    if not s.exec_result("id", username).ok:
        s.exec("useradd", "--create-home", username)


def self_safe_pattern(pattern: str) -> str:
    """Bracket the first alphanumeric char of every ``|``-branch
    ("a|b" -> "[a]|[b]") so no branch of the pkill regex can match the
    wrapper shell whose own cmdline contains the pattern — otherwise
    `bash -c 'pkill -f asd'` SIGKILLs itself.  Branches already starting
    with a character class are left alone."""

    def safe_branch(b: str) -> str:
        for i, c in enumerate(b):
            if c == "[":
                return b  # already bracketed
            if c.isalnum():
                return f"{b[:i]}[{c}]{b[i + 1:]}"
        return b

    # Split only on top-level "|": a "|" inside a character class (e.g.
    # "[a|b]c") is a literal, and splitting there would corrupt the regex.
    # Classes don't nest — a "[" inside a class is a literal — so track a
    # boolean, not a depth counter.
    branches, in_class, start = [], False, 0
    for i, c in enumerate(pattern):
        if c == "[" and not in_class:
            in_class = True
        elif c == "]" and in_class:
            in_class = False
        elif c == "|" and not in_class:
            branches.append(pattern[start:i])
            start = i + 1
    branches.append(pattern[start:])
    return "|".join(safe_branch(b) for b in branches)


def grepkill(s: Session, pattern: str, signal: str = "KILL") -> None:
    """Kill processes whose cmdline matches a pattern (util.clj:286)."""
    s.exec_result("pkill", f"-{signal}", "-f", self_safe_pattern(pattern))


def signal(s: Session, process_name: str, sig: str) -> None:
    """Send a signal by process name (util.clj:403)."""
    s.exec_result("killall", f"-{sig}", process_name)


def start_daemon(s: Session, binary: str, *args,
                 pidfile: str, logfile: str, chdir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 user: Optional[str] = None) -> None:
    """Start a long-running process detached with a pidfile
    (util.clj:311's start-stop-daemon pattern, without requiring the
    start-stop-daemon binary).  ``user`` runs the daemon as a service
    account.

    The daemon is launched under ``setsid`` as its own session leader, so
    its pid doubles as the process-group id and stop_daemon can signal the
    whole group (``kill -- -$pid``) — a daemon that forked workers can't
    leave orphans behind (start-stop-daemon's --make-pidfile semantics;
    util.clj:370's stop-daemon! kills by group for the same reason).  The
    inner shell writes its *own* pid (the group leader's, preserved across
    ``exec``) before exec'ing the real binary, so the pidfile never records
    a wrapper."""
    import shlex

    from jepsen_tpu.control.core import build_cmd, env_str
    cmd = build_cmd(binary, *args)
    if env:
        cmd = f"env {env_str(env)} {cmd}"
    # The session-leader shell records its own pid, then becomes the daemon
    # via exec: pidfile pid == daemon pid == pgid.  ($! in the outer shell
    # would record setsid's short-lived fork-parent instead.)
    inner = f"echo $$ > {pidfile}; exec {cmd}"
    if user:
        launch = f"sudo -n -u {user} setsid bash -c {shlex.quote(inner)}"
    else:
        launch = f"setsid bash -c {shlex.quote(inner)}"
    # chdir runs as its own foreground statement: `nohup cd X && cmd` tries
    # to exec the `cd` builtin and short-circuits; `cd X && nohup cmd &`
    # backgrounds the whole list, so $! would be a wrapper subshell instead
    # of the daemon and signals would never reach it.
    prefix = f"cd {chdir} || exit 1; " if chdir else ""
    script = (f"if [ -f {pidfile} ] && kill -0 $(cat {pidfile}) 2>/dev/null; "
              f"then echo already-running; else "
              f"{prefix}nohup {launch} >> {logfile} 2>&1 & "
              # the inner echo races the outer shell's return; don't let
              # stop_daemon see a missing pidfile for a started daemon
              f"for i in 1 2 3 4 5 6 7 8 9 10; do "
              f"[ -s {pidfile} ] && break; sleep 0.1; done; "
              f"fi")
    s.exec("bash", "-c", script)


def stop_daemon(s: Session, pidfile: str, timeout_s: float = 10) -> None:
    """Kill the pidfile's process *group* and remove the pidfile
    (util.clj:370 stop-daemon!, which also signals the group).  Signalling
    ``-$pid`` reaches every worker the daemon forked; the bare-pid kill is
    the fallback for daemons started by an older start_daemon whose pid
    isn't a group leader."""
    group_kill = (f"kill -{{sig}} -- -$pid 2>/dev/null || "
                  f"kill -{{sig}} $pid 2>/dev/null || true")
    script = (f"if [ -f {pidfile} ]; then pid=$(cat {pidfile}); "
              + group_kill.format(sig="TERM") + "; fi")
    s.exec("bash", "-c", script)
    deadline = mono_now() + timeout_s
    while mono_now() < deadline:
        if not daemon_running(s, pidfile):
            break
        time.sleep(0.25)
    script = (f"if [ -f {pidfile} ]; then pid=$(cat {pidfile}); "
              + group_kill.format(sig="KILL") + f"; rm -f {pidfile}; fi")
    s.exec("bash", "-c", script)


def daemon_running(s: Session, pidfile: str) -> bool:
    """Is the pidfile's process alive? (util.clj:390)"""
    return s.exec_result(
        "bash", "-c",
        f"[ -f {pidfile} ] && kill -0 $(cat {pidfile})").ok
