"""Node-side network introspection helpers.

Parity: jepsen.control.net (jepsen/src/jepsen/control/net.clj): reachability
probes, hostname→IP resolution via getent, and the control node's IP as seen
from a DB node (used e.g. by the tcpdump DB's clients-only filter,
db.clj:107-110).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from jepsen_tpu.control import Session

# (node-identity, hostname) -> ip; getent is stable for a run
# (control/net.clj:38-40 memoizes the same way).
_ip_cache: Dict[Tuple[int, str], str] = {}


def reachable(s: Session, node: str) -> bool:
    """Can the session's node ping ``node``? (control/net.clj:8-12)."""
    return s.exec_result("ping", "-c", "1", "-w", "1", node).ok


def local_ip(s: Session) -> Optional[str]:
    """The node's own IP address (control/net.clj:14-17)."""
    out = s.exec("hostname", "-I").split()
    return out[0] if out else None


def ip_of(s: Session, host: str, memo: bool = True) -> str:
    """Resolve ``host`` to an IP from the session's node via
    ``getent ahosts`` (control/net.clj:19-36).  Raises on blank results the
    same way the reference throws :blank-getent-ip."""
    key = (id(s.remote), host)
    if memo and key in _ip_cache:
        return _ip_cache[key]
    res = s.exec("getent", "ahosts", host)
    lines = res.splitlines()
    ip = lines[0].split()[0] if lines and lines[0].split() else ""
    if not ip:
        raise RuntimeError(f"blank getent ip for {host!r}: {res!r}")
    if memo:
        _ip_cache[key] = ip
    return ip


def control_ip(s: Session) -> Optional[str]:
    """The control node's IP as perceived by the DB node, from $SSH_CLIENT
    (control/net.clj:41-53).  None when the transport isn't SSH (docker/k8s
    exec, dummy)."""
    out = s.exec_result("bash", "-c", "echo $SSH_CLIENT")
    if out.ok and out.out.split():
        return out.out.split()[0]
    return None
