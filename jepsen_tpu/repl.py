"""REPL conveniences for poking at stored runs.

Parity: jepsen.repl (jepsen/src/jepsen/repl.clj) + jepsen.report: load the
latest run, re-check histories interactively.
"""

from __future__ import annotations

import contextlib
import sys
from typing import Any, Dict, Optional, Tuple

from jepsen_tpu import store
from jepsen_tpu.history import History


def latest_test(base: str = "store") -> Optional[str]:
    """Directory of the most recent run (repl.clj's latest-test)."""
    runs = store.runs(base)
    if not runs:
        return None
    return max(runs, key=lambda r: r["time"])["dir"]


def load_latest(base: str = "store") -> Tuple[Dict[str, Any], History]:
    d = latest_test(base)
    if d is None:
        raise FileNotFoundError(f"no runs under {base}")
    return store.load_test(d), store.load_history(d)


@contextlib.contextmanager
def to_file(path: str):
    """Redirect stdout into a file (jepsen.report's with-out-file)."""
    old = sys.stdout
    with open(path, "w") as f:
        sys.stdout = f
        try:
            yield
        finally:
            sys.stdout = old


def recheck(checker, base: str = "store") -> Dict[str, Any]:
    """Re-run a checker over the latest stored history."""
    test, history = load_latest(base)
    from jepsen_tpu.checker.core import check_safe
    return check_safe(checker, test, history,
                      {"store_dir": test.get("store_dir")})
