"""SVG rendering of a linearizability failure.

Parity: knossos.linear.report/render-analysis! (invoked by the reference at
jepsen/src/jepsen/checker.clj:207-211 to write ``linear.svg`` next to the
results).  The drawing is the same idea re-done from scratch: the
neighborhood of the failing operation as a per-process timeline — one row
per process, one bar per op spanning invocation→completion, the crashed
(info) ops open-ended, the failing op outlined in red — plus the surviving
configurations ("final configs") the search held just before it ran out of
legal linearizations.

Pure-stdlib SVG emission; no plotting dependency.
"""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional

from jepsen_tpu.history import FAIL, History, INFO, INVOKE, OK

_FILL = {OK: "#a6d9a1", INFO: "#f5d06c", FAIL: "#f0a58f", None: "#d8d8d8"}

ROW_H = 26
BAR_H = 18
LEFT = 90
WIDTH = 960
TOP = 34
CONTEXT_OPS = 24  # completed ops of context drawn before the failing op


def render_analysis(history: History, analysis: Dict[str, Any],
                    path: str) -> Optional[str]:
    """Write an SVG for a failed analysis; returns the path (None if the
    analysis has no failing op to draw)."""
    bad = analysis.get("op")
    if analysis.get("valid") is True or not bad:
        return None
    svg = render_svg(history, analysis)
    with open(path, "w") as f:
        f.write(svg)
    return path


def render_svg(history: History, analysis: Dict[str, Any]) -> str:
    bad = analysis["op"]
    h = history.client_ops()
    pairs = h.pair_index()

    # Collect (invoke, complete) spans; remember the failing one.  Handmade
    # histories may lack times — fall back to history position.
    def t_of(op, i):
        return op.time if op.time is not None else i

    spans: List[Dict[str, Any]] = []
    for i, op in enumerate(h):
        if op.type != INVOKE:
            continue
        j = int(pairs[i]) if pairs[i] is not None else -1
        comp = h[j] if j >= 0 else None
        spans.append({
            "op": op, "comp": comp,
            "t0": t_of(op, i),
            "t1": t_of(comp, j) if comp is not None else None,
            "bad": bad is not None and op.index == bad.get("index"),
        })
    bad_k = next((k for k, s in enumerate(spans) if s["bad"]), None)
    if bad_k is None:
        # fall back: draw the tail of the history
        bad_k = len(spans) - 1
    lo = max(0, bad_k - CONTEXT_OPS)
    view = [s for s in spans[lo:bad_k + 1]]
    # plus any still-pending ops invoked before the failing op completes
    t_end = view[-1]["t1"] or view[-1]["t0"]
    for s in spans[:lo]:
        if s["t1"] is None or s["t1"] >= view[0]["t0"]:
            view.append(s)

    times = [s["t0"] for s in view] + [s["t1"] for s in view if s["t1"]]
    t_min, t_max = min(times), max(max(times), t_end)
    t_span = max(t_max - t_min, 1)

    def x(t):
        return LEFT + (WIDTH - LEFT - 20) * (t - t_min) / t_span

    procs = sorted({s["op"].process for s in view}, key=str)
    rows = {p: i for i, p in enumerate(procs)}
    height = TOP + ROW_H * len(procs) + 30

    parts = []
    for p in procs:
        y = TOP + rows[p] * ROW_H
        parts.append(f'<text x="4" y="{y + BAR_H - 4}" font-size="11" '
                     f'font-family="monospace">{html.escape(str(p))}</text>')
        parts.append(f'<line x1="{LEFT}" y1="{y + BAR_H / 2}" '
                     f'x2="{WIDTH - 10}" y2="{y + BAR_H / 2}" '
                     f'stroke="#eee"/>')
    for s in view:
        op, comp = s["op"], s["comp"]
        y = TOP + rows[op.process] * ROW_H
        x0 = x(s["t0"])
        x1 = x(s["t1"]) if s["t1"] is not None else WIDTH - 12
        ctype = comp.type if comp is not None else INFO
        fill = _FILL.get(ctype, _FILL[None])
        stroke = "#d62728" if s["bad"] else "#666"
        sw = 2.5 if s["bad"] else 0.75
        label = f"{op.f} {_short(op.value)}"
        if comp is not None and comp.value is not None and ctype == OK:
            label = f"{op.f} {_short(comp.value)}"
        parts.append(
            f'<rect x="{x0:.1f}" y="{y}" width="{max(x1 - x0, 3):.1f}" '
            f'height="{BAR_H}" rx="3" fill="{fill}" stroke="{stroke}" '
            f'stroke-width="{sw}"/>')
        parts.append(
            f'<text x="{x0 + 3:.1f}" y="{y + BAR_H - 5}" font-size="10" '
            f'font-family="monospace">{html.escape(label)}</text>')

    # Final-configs panel (from the search / witness)
    finals = (analysis.get("final-configs")
              or (analysis.get("witness") or {}).get("final-configs") or [])
    fy = height
    lines = []
    for c in finals[:6]:
        pend = ", ".join(o.get("f", "?") + "=" + _short(o.get("value"))
                         for o in c.get("linearized-pending", []))
        lines.append(f"state {c.get('model')}"
                     + (f"  after linearizing [{pend}]" if pend else ""))
    if lines:
        height += 16 * (len(lines) + 1) + 8
        parts.append(f'<text x="8" y="{fy + 12}" font-size="12" '
                     f'font-weight="bold" font-family="monospace">'
                     f'Surviving configurations before '
                     f'{html.escape(str(bad.get("f")))} completed:</text>')
        for i, ln in enumerate(lines):
            parts.append(f'<text x="16" y="{fy + 28 + 16 * i}" font-size="11" '
                         f'font-family="monospace">{html.escape(ln)}</text>')

    title = (f'not linearizable: {bad.get("f")} '
             f'{_short(bad.get("value"))} by process {bad.get("process")}')
    head = (f'<svg xmlns="http://www.w3.org/2000/svg" width="{WIDTH}" '
            f'height="{height}">'
            f'<rect width="100%" height="100%" fill="white"/>'
            f'<text x="8" y="18" font-size="13" font-weight="bold" '
            f'font-family="monospace">{html.escape(title)}</text>')
    return head + "".join(parts) + "</svg>"


def _short(v: Any, n: int = 24) -> str:
    s = "nil" if v is None else str(v)
    return s if len(s) <= n else s[:n - 1] + "…"
