"""Host-tier linearizability engine — the correctness oracle.

A breadth-first configuration search in the style of knossos's WGL solver
(the reference races knossos.linear / knossos.wgl / knossos.competition at
jepsen/src/jepsen/checker.clj:185-216).  Configurations are
(pending-window bitmask, model state) pairs per the compression argument in
:mod:`jepsen_tpu.checker.prep`; the search:

  - at an ENTER event, adds the op to the pending window (no expansion —
    linearizing it now or at the next RETURN closure is equivalent);
  - at a RETURN event for op i, computes the closure of the configuration set
    under linearizing any pending ops (model permitting), then prunes to
    configurations that linearized i, then retires i's window bit;
  - reports not-linearizable with the offending op and the surviving
    configurations just before pruning (knossos-style final configs).

Works with any host-tier :class:`~jepsen_tpu.models.base.Model` (hashable,
immutable).  This is also the measured "CPU knossos" baseline for BENCH runs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Set, Tuple

from jepsen_tpu.history import History, Op
from jepsen_tpu.models.base import Inconsistent, Model
from jepsen_tpu.checker.prep import EV_ENTER, EV_RETURN, PreparedHistory, prepare

Config = Tuple[int, Model]  # (pending-window bitmask, model state)


def check(model: Model, history: History,
          prepared: Optional[PreparedHistory] = None,
          max_configs: int = 2_000_000,
          cancel=None) -> Dict[str, Any]:
    """Decide linearizability of ``history`` against ``model``.

    Returns a knossos-shaped analysis map: ``{"valid": bool, ...}`` with the
    failing op and a sample of final configurations on refutation.

    ``cancel`` is an optional :class:`threading.Event`; when another solver
    in a competition race has already produced a definite verdict, the losing
    search aborts at the next RETURN event by raising :class:`Cancelled`
    (knossos.competition cancels the losing future, checker.clj:199-202)."""
    p = prepared if prepared is not None else prepare(history)
    window: Dict[int, Op] = {}         # slot -> pending op
    configs: Set[Config] = {(0, model)}
    ghost_mask = 0                     # slots held by ops that never return
    gclasses: Dict[int, List[int]] = {}  # class id -> member slots, in order
    n_explored = 0

    for e in range(len(p)):
        kind, slot, op_id = int(p.kind[e]), int(p.slot[e]), int(p.op_id[e])
        if kind == EV_ENTER:
            window[slot] = p.ops[op_id]
            if int(p.ghost[e]):
                ghost_mask |= 1 << slot
                gclasses.setdefault(int(p.gcls[e]), []).append(slot)
            continue
        # RETURN: expand closure, then prune on the returning op's bit.
        configs = _closure(configs, window, max_configs, cancel,
                           ghost_mask, gclasses)
        n_explored += len(configs)
        bit = 1 << slot
        survivors = {(mask & ~bit, m) for (mask, m) in configs if mask & bit}
        if not survivors:
            return {
                "valid": False,
                "analyzer": "wgl-cpu",
                "op": p.ops[op_id].to_dict(),
                "previous-ok": True,
                "final-configs": _render_configs(configs, window, limit=10),
                "pending": [o.to_dict() for o in window.values()],
                "configs-explored": n_explored,
            }
        del window[slot]
        configs = survivors

    # Any surviving configuration witnesses a legal linearization: info ops
    # still pending are optional, and every ok op was pruned on at a RETURN.
    return {"valid": True, "analyzer": "wgl-cpu",
            "configs-explored": n_explored,
            "final-configs-count": len(configs)}


def _closure(configs: Set[Config], window: Dict[int, Op],
             max_configs: int, cancel=None,
             ghost_mask: int = 0,
             gclasses: Optional[Dict[int, List[int]]] = None) -> Set[Config]:
    """BFS closure with ghost-bit subsumption: a config is skipped when the
    set already holds one with the same non-ghost mask and state whose
    ghost bitset is a subset — ghost ops (crashed, never returning) are
    never consulted by pruning, and the kept config can re-derive the
    skipped one at any later closure.  Same-encoding ghosts are further
    canonicalized to per-class counts (they are interchangeable).
    Collapses the 2^crashes blowup to O(crashes) (mirrors the device
    engine's subsumption dedup)."""
    # (non-ghost mask, model) -> kept ghost bitsets (approximate antichain)
    groups: Dict[Tuple[int, Model], List[int]] = {}
    n = 0

    def canonical(g: int) -> int:
        for members in (gclasses or {}).values():
            cnt = sum(1 for s in members if g & (1 << s))
            for i, s in enumerate(members):
                if i < cnt:
                    g |= 1 << s
                else:
                    g &= ~(1 << s)
        return g

    def try_add(mask: int, m: Model) -> bool:
        nonlocal n
        g = canonical(mask & ghost_mask)
        key = (mask & ~ghost_mask, m)
        kept = groups.get(key)
        if kept is None:
            groups[key] = [g]
            n += 1
            return True
        for k in kept:
            if k & ~g == 0:  # k ⊆ g: subsumed (or exact duplicate)
                return False
        kept.append(g)
        n += 1
        return True

    frontier: List[Config] = []
    for mask, m in configs:
        if try_add(mask, m):
            frontier.append((mask, m))
    while frontier:
        # Closure is the dominant cost (up to max_configs states), so a
        # cancelled race must abort here, not just at RETURN boundaries.
        if cancel is not None and cancel.is_set():
            raise Cancelled()
        new: List[Config] = []
        for mask, m in frontier:
            for slot, op in window.items():
                bit = 1 << slot
                if mask & bit:
                    continue
                m2 = m.step(op)
                if isinstance(m2, Inconsistent):
                    continue
                if try_add(mask | bit, m2):
                    new.append((mask | bit, m2))
                    if n > max_configs:
                        raise SearchExploded(n)
        frontier = new
    return {(bm | g, m) for (bm, m), gs in groups.items() for g in gs}


class SearchExploded(Exception):
    """Configuration set exceeded the budget; verdict is unknown."""

    def __init__(self, n):
        super().__init__(f"configuration set exceeded budget at {n}")
        self.n = n


class Cancelled(Exception):
    """Search aborted because a competing solver already won the race."""


def _render_configs(configs: Set[Config], window: Dict[int, Op], limit: int):
    out = []
    for mask, m in list(configs)[:limit]:
        out.append({
            "model": repr(m),
            "linearized-pending": [window[s].to_dict() for s in window
                                   if mask & (1 << s)],
        })
    return out
