"""HTML timeline of per-process operations.

Parity: jepsen.checker.timeline (jepsen/src/jepsen/checker/timeline.clj):
renders every process's ops as positioned bars in an HTML page, capped at
10k ops for browser sanity (timeline.clj:12-14).
"""

from __future__ import annotations

import html
import os
from typing import Any, Dict, Optional

from jepsen_tpu.checker.core import Checker
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, NEMESIS, OK

MAX_OPS = 10_000  # timeline.clj:12

_COLORS = {OK: "#6DB6FE", INFO: "#FEB95F", FAIL: "#FFAA8F",
           None: "#DDDDDD"}

_STYLE = """
body { font-family: monospace; }
.op { position: absolute; padding: 1px 3px; border-radius: 2px;
      font-size: 9px; overflow: hidden; white-space: nowrap;
      border: 1px solid #888; }
.proc-label { position: absolute; top: 0; font-weight: bold; }
"""


class Timeline(Checker):
    def check(self, test, history: History, opts=None):
        d = (opts or {}).get("store_dir") or test.get("store_dir")
        if not d:
            return {"valid": True, "note": "no store dir; skipped"}
        path = os.path.join(d, "timeline.html")
        with open(path, "w") as f:
            f.write(self.render(history))
        return {"valid": True, "file": path}

    def render(self, history: History) -> str:
        pairs = history.pair_index()
        procs = []
        seen = set()
        for op in history:
            if op.process not in seen:
                seen.add(op.process)
                procs.append(op.process)
        col_of = {p: i for i, p in enumerate(procs)}
        col_w, scale = 220, 1e-6  # 1 ms/px

        cells = []
        n = 0
        for i, op in enumerate(history):
            if op.type != INVOKE and not (op.process == NEMESIS
                                          and op.type == INFO
                                          and pairs[i] < 0):
                continue
            n += 1
            if n > MAX_OPS:
                break
            j = pairs[i]
            comp = history[j] if j >= 0 else None
            t0 = (op.time or 0) * scale
            t1 = (comp.time * scale) if comp and comp.time else t0 + 10
            color = _COLORS.get(comp.type if comp else None, "#DDDDDD")
            label = f"{op.process} {op.f} {op.value!r}"
            if comp is not None and comp.value is not None and \
                    comp.value != op.value:
                label += f" → {comp.value!r}"
            title = html.escape(
                f"{op.type} {label} [{op.time}..{comp.time if comp else '?'}]")
            cells.append(
                f"<div class='op' title='{title}' style='"
                f"left:{col_of[op.process] * col_w}px;"
                f"top:{20 + t0:.1f}px;"
                f"height:{max(3, t1 - t0):.1f}px;"
                f"width:{col_w - 10}px;"
                f"background:{color}'>{html.escape(label[:40])}</div>")

        labels = [f"<div class='proc-label' style='left:{c * col_w}px'>"
                  f"{html.escape(str(p))}</div>"
                  for p, c in col_of.items()]
        return (f"<html><head><style>{_STYLE}</style></head><body>"
                f"<div style='position:relative'>{''.join(labels)}"
                f"{''.join(cells)}</div></body></html>")


timeline = Timeline
