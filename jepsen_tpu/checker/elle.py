"""The Elle transactional-anomaly checkers as checker plugins.

Parity: the reference composes elle's cycle checkers into a test's
checker map (jepsen/src/jepsen/tests/cycle/append.clj:15-21, wr.clj:9-25);
here ``ElleChecker`` wraps the elle_tpu engine (device tier with CPU
degradation chain — see jepsen_tpu.elle_tpu) behind the standard Checker
protocol so it composes with checker.core's battery, rides ``check_safe``
budget/``duration-s`` accounting, and writes the ``elle/`` artifact
directory into the store dir like the reference's ``:directory`` option.

Registered (checker.core registry): ``elle-list-append``,
``elle-rw-register``, plus ``-cpu`` variants pinning the oracle path.

Budget plumbing: ``check_safe``'s wall-clock budget kills the checker
thread from outside; this checker *also* threads the same budget into the
engine as a SearchBudget deadline, so cycle recovery degrades gracefully
(``cycle-search-truncated``, clean verdicts -> unknown) before the
outside kill ever fires.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from jepsen_tpu.checker.core import Checker
from jepsen_tpu.elle import render
from jepsen_tpu.history import History


class ElleChecker(Checker):
    def __init__(self, workload: str = "list-append",
                 engine: str = "auto",
                 realtime: bool = False,
                 consistency_models: Optional[Sequence[str]] = None,
                 budget_s: Optional[float] = None,
                 **workload_kw):
        self.workload = workload
        self.engine = engine
        self.realtime = realtime
        self.consistency_models = consistency_models
        self.budget_s = budget_s
        self.workload_kw = workload_kw

    def _budget_s(self, test, opts) -> Optional[float]:
        if self.budget_s is not None:
            return self.budget_s
        b = (opts or {}).get("budget_s")
        if b is None:
            b = (test or {}).get("checker_budget_s")
        return b

    def check(self, test, history: History,
              opts: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        from jepsen_tpu import elle_tpu
        res = elle_tpu.check(history,
                             workload=self.workload,
                             engine=self.engine,
                             realtime=self.realtime,
                             consistency_models=self.consistency_models,
                             budget_s=self._budget_s(test, opts),
                             **self.workload_kw)
        render.write_artifacts(test, res, opts)
        return res


class ElleListAppend(ElleChecker):
    def __init__(self, **kw):
        kw.setdefault("workload", "list-append")
        super().__init__(**kw)


class ElleRwRegister(ElleChecker):
    def __init__(self, **kw):
        kw.setdefault("workload", "rw-register")
        super().__init__(**kw)


def elle_list_append(**kw) -> Checker:
    return ElleListAppend(**kw)


def elle_rw_register(**kw) -> Checker:
    return ElleRwRegister(**kw)
