"""The linearizable checker facade — algorithm selection and competition.

Parity: jepsen.checker/linearizable (checker.clj:185-216), which dispatches
on ``:algorithm`` to knossos's linear/wgl/competition solvers.  Here the
algorithms are:

- ``"tpu"``          — the device engine (wgl_tpu), requires a JaxModel;
- ``"cpu"``/``"wgl"`` — the host BFS oracle (wgl_cpu), any Model;
- ``"linear"``       — the memoized DFS solver (linear_cpu), any Model —
  the knossos ``linear`` role, algorithmically distinct from wgl;
- ``"competition"``  — race device + both host solvers on threads, first
  definite verdict wins (knossos.competition parity — the reference races
  its two CPU algorithms the same way; also the fallback tier for models
  with no device encoding, SURVEY.md §7 hard-parts);
- default: "tpu" when the model has a device tier, else "cpu".
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional, Union

from jepsen_tpu.checker import linear_cpu, wgl_cpu, wgl_tpu
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel, Model


# Losing competition racers still draining after their verdict was beaten.
# Joined (bounded) at interpreter exit: tearing down XLA under a daemon
# thread mid-dispatch aborts the process ("FATAL: exception not rethrown"),
# while a plain non-daemon thread would hang exit forever if a tunneled
# device transfer wedges.  Cancellation makes the join fast in practice —
# losers exit at their next chunk boundary / closure round.
_stragglers: List[threading.Thread] = []
_stragglers_lock = threading.Lock()


@atexit.register
def _drain_stragglers(timeout: float = 30.0) -> None:
    import time
    deadline = time.monotonic() + timeout
    with _stragglers_lock:
        ts = list(_stragglers)
    for t in ts:
        t.join(timeout=max(0.0, deadline - time.monotonic()))


class Linearizable(Checker):
    def __init__(self, model: Union[JaxModel, Model],
                 algorithm: Optional[str] = None, **engine_opts):
        self.model = model
        self.algorithm = algorithm
        self.engine_opts = engine_opts

    def _cpu_model(self) -> Optional[Model]:
        if isinstance(self.model, Model):
            return self.model
        if isinstance(self.model, JaxModel) and self.model.cpu_model:
            return self.model.cpu_model()
        return None

    def _jax_model(self) -> Optional[JaxModel]:
        return self.model if isinstance(self.model, JaxModel) else None

    def check(self, test, history: History, opts=None):
        algo = self.algorithm
        jm, cm = self._jax_model(), self._cpu_model()
        if algo is None:
            algo = "tpu" if jm is not None else "cpu"
        if algo == "tpu":
            if jm is None:
                return {"valid": UNKNOWN,
                        "error": "model has no device tier; use cpu"}
            try:
                # The fission layer IS wgl_tpu.check below the threshold;
                # above it, capacity overflow splits the search instead of
                # degrading to unknown (engine.fission).  Callers opt out
                # per-check with fission=False in engine_opts.
                from jepsen_tpu.engine import fission
                res = fission.check(jm, history, **self.engine_opts)
            except Exception as e:  # noqa: BLE001
                res = self._tpu_fallback(history, cm, e)
        elif algo in ("cpu", "linear", "wgl"):
            if cm is None:
                return {"valid": UNKNOWN, "error": "no host-tier model"}
            solver = linear_cpu if algo == "linear" else wgl_cpu
            try:
                res = solver.check(cm, history)
            except wgl_cpu.SearchExploded as e:
                return {"valid": UNKNOWN, "error": str(e)}
        elif algo == "competition":
            res = self._competition(test, history)
        else:
            return {"valid": UNKNOWN, "error": f"unknown algorithm {algo!r}"}
        if res.get("valid") is False:
            self._render(test, history, res, opts)
        return res

    def _tpu_fallback(self, history: History, cm: Optional[Model],
                      exc: Exception) -> Dict[str, Any]:
        """Degradation chain for a crashed device engine (robustness tier
        of checker.clj:185-216's competition: never let a device error
        decide a verdict).  A TPU failure — XLA OOM, runtime wedge, device
        loss — says nothing about the *history*, so instead of surfacing
        the crash as the result we fall back to the host BFS oracle
        (wgl_cpu), annotating the verdict with the chain it travelled
        (the engine.fallback discipline, shared with the elle engine and
        the serve scheduler's host-fallback cells).  Only when the CPU
        tier is missing or itself gives up (its state set exceeds the
        budget) does the verdict degrade to UNKNOWN, and then it carries
        partial-search stats so the operator can tell \"checker
        overwhelmed\" from \"history lost\"."""
        from jepsen_tpu.engine.fallback import (
            annotate_fallback, chain_entry, warn_fallback,
        )
        entry = chain_entry("wgl-tpu", exc)
        chain: List[Dict[str, Any]] = [entry]
        warn_fallback("wgl-tpu", "wgl-cpu", exc)
        if cm is None:
            return {"valid": UNKNOWN,
                    "error": "device engine failed and model has no "
                             f"host tier: {exc}",
                    "fallback-chain": chain}
        try:
            res = wgl_cpu.check(cm, history)
        except wgl_cpu.SearchExploded as e2:
            chain.append({"solver": "wgl-cpu", "error": str(e2)})
            return {"valid": UNKNOWN, "error": str(e2),
                    "fallback-chain": chain,
                    "partial-search": {"configs-explored": e2.n,
                                       "exhausted": False}}
        except Exception as e2:  # noqa: BLE001
            chain.append(chain_entry("wgl-cpu", e2))
            return {"valid": UNKNOWN,
                    "error": f"device engine and host oracle both "
                             f"failed: {exc}; {e2}",
                    "fallback-chain": chain}
        annotate_fallback(res, "wgl-tpu", "wgl-cpu", entry, chain)
        res.setdefault("solver", "wgl-cpu")
        return res

    def _render(self, test, history, res, opts) -> None:
        """Write linear.svg next to the results (knossos.linear.report
        parity, checker.clj:207-211).  Best-effort: rendering trouble must
        never mask the verdict."""
        import os
        d = (opts or {}).get("store_dir") or (test or {}).get("store_dir")
        if not d:
            return
        try:
            from jepsen_tpu.checker.render import render_analysis
            path = render_analysis(history, res, os.path.join(d, "linear.svg"))
            if path:
                res["render"] = path
        except Exception as e:  # noqa: BLE001
            res["render-error"] = str(e)

    def _competition(self, test, history):
        """Race the device engine and BOTH host solvers (BFS wgl + DFS
        linear — three algorithmically distinct searches); the first
        *definite* verdict (valid True/False) wins and the losers are
        cancelled.  An UNKNOWN from one racer — e.g. a host solver
        exploding early — must NOT mask a definite answer still coming from
        another; only when every racer finishes indefinite does the race
        report unknown.  Parity: knossos.competition via
        checker.clj:199-202, which races knossos's linear and wgl solvers
        the same way, takes the first non-:unknown analysis and cancels the
        losing futures."""
        jm, cm = self._jax_model(), self._cpu_model()
        if jm is None and cm is None:
            return {"valid": UNKNOWN, "error": "no model tier available"}
        if jm is None or cm is None:
            # only one tier available: no cross-tier race (a cm-only model
            # still races its two host algorithms below when jm is None)
            if cm is None:
                self2 = Linearizable(self.model, None, **self.engine_opts)
                return self2.check(test, history)
        done = threading.Event()
        cancel = threading.Event()
        lock = threading.Lock()
        results: Dict[str, Any] = {"indefinite": {}}

        def post(solver: str, r: Dict[str, Any]) -> None:
            definite = r.get("valid") in (True, False)
            with lock:
                if definite and "winner" not in results:
                    results["winner"] = {**r, "solver": solver}
                    cancel.set()   # stop the loser's search
                    done.set()
                elif definite:
                    # A second definite verdict: surface disagreement (a
                    # solver bug!) instead of silently discarding it.  The
                    # winner dict may already be returned to the caller, so
                    # never mutate it here — attach if the race is still
                    # open, log otherwise.
                    w = results["winner"]
                    if w.get("valid") != r.get("valid"):
                        if results.get("returned"):
                            import logging
                            logging.getLogger(__name__).error(
                                "solver disagreement after verdict: "
                                "%s=%r vs %s=%r", w.get("solver"),
                                w.get("valid"), solver, r.get("valid"))
                        else:
                            w["disagreement"] = {**r, "solver": solver}
                else:
                    results["indefinite"][solver] = r
                    if len(results["indefinite"]) == n_racers:
                        done.set()  # all indefinite: race is over anyway

        def run_tpu():
            try:
                r = wgl_tpu.check(jm, history, cancel=cancel,
                                  **self.engine_opts)
            except Exception as e:  # noqa: BLE001
                r = {"valid": UNKNOWN, "error": str(e)}
            post("tpu", r)

        def run_host(name, solver):
            def go():
                try:
                    r = solver.check(cm, history, cancel=cancel)
                except wgl_cpu.Cancelled:
                    r = {"valid": UNKNOWN, "cancelled": True}
                except wgl_cpu.SearchExploded as e:
                    r = {"valid": UNKNOWN, "error": str(e)}
                except Exception as e:  # noqa: BLE001
                    r = {"valid": UNKNOWN, "error": str(e)}
                post(name, r)
            return go

        ts = []
        if jm is not None:
            ts.append(threading.Thread(target=run_tpu, daemon=True))
        if cm is not None:
            ts.append(threading.Thread(target=run_host("cpu", wgl_cpu),
                                       daemon=True))
            ts.append(threading.Thread(target=run_host("linear", linear_cpu),
                                       daemon=True))
        n_racers = len(ts)
        for t in ts:
            t.start()
        done.wait()
        cancel.set()  # both-indefinite path never set it
        for t in ts:  # losers usually exit within one chunk/closure round
            t.join(timeout=0.2)
        with _stragglers_lock:
            _stragglers[:] = [t for t in _stragglers if t.is_alive()]
            _stragglers.extend(t for t in ts if t.is_alive())
        with lock:
            results["returned"] = True
            if "winner" in results:
                # Snapshot: a straggler must not mutate the caller's dict.
                return dict(results["winner"])
            # Both solvers indefinite: report the combined unknown.
            return {"valid": UNKNOWN, "solver": "competition",
                    "solvers": dict(results["indefinite"])}


def linearizable(model, algorithm: Optional[str] = None, **kw) -> Checker:
    return Linearizable(model, algorithm, **kw)
