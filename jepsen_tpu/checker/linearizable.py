"""The linearizable checker facade — algorithm selection and competition.

Parity: jepsen.checker/linearizable (checker.clj:185-216), which dispatches
on ``:algorithm`` to knossos's linear/wgl/competition solvers.  Here the
algorithms are:

- ``"tpu"``          — the device engine (wgl_tpu), requires a JaxModel;
- ``"cpu"``/"linear"/"wgl" — the host oracle (wgl_cpu), any Model;
- ``"competition"``  — race both on two threads, first verdict wins
  (knossos.competition parity; also the fallback tier for models with no
  device encoding, SURVEY.md §7 hard-parts);
- default: "tpu" when the model has a device tier, else "cpu".
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Union

from jepsen_tpu.checker import wgl_cpu, wgl_tpu
from jepsen_tpu.checker.core import Checker, UNKNOWN
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel, Model


class Linearizable(Checker):
    def __init__(self, model: Union[JaxModel, Model],
                 algorithm: Optional[str] = None, **engine_opts):
        self.model = model
        self.algorithm = algorithm
        self.engine_opts = engine_opts

    def _cpu_model(self) -> Optional[Model]:
        if isinstance(self.model, Model):
            return self.model
        if isinstance(self.model, JaxModel) and self.model.cpu_model:
            return self.model.cpu_model()
        return None

    def _jax_model(self) -> Optional[JaxModel]:
        return self.model if isinstance(self.model, JaxModel) else None

    def check(self, test, history: History, opts=None):
        algo = self.algorithm
        jm, cm = self._jax_model(), self._cpu_model()
        if algo is None:
            algo = "tpu" if jm is not None else "cpu"
        if algo == "tpu":
            if jm is None:
                return {"valid": UNKNOWN,
                        "error": "model has no device tier; use cpu"}
            res = wgl_tpu.check(jm, history, **self.engine_opts)
        elif algo in ("cpu", "linear", "wgl"):
            if cm is None:
                return {"valid": UNKNOWN, "error": "no host-tier model"}
            try:
                res = wgl_cpu.check(cm, history)
            except wgl_cpu.SearchExploded as e:
                return {"valid": UNKNOWN, "error": str(e)}
        elif algo == "competition":
            res = self._competition(test, history)
        else:
            return {"valid": UNKNOWN, "error": f"unknown algorithm {algo!r}"}
        if res.get("valid") is False:
            self._render(test, history, res, opts)
        return res

    def _render(self, test, history, res, opts) -> None:
        """Write linear.svg next to the results (knossos.linear.report
        parity, checker.clj:207-211).  Best-effort: rendering trouble must
        never mask the verdict."""
        import os
        d = (opts or {}).get("store_dir") or (test or {}).get("store_dir")
        if not d:
            return
        try:
            from jepsen_tpu.checker.render import render_analysis
            path = render_analysis(history, res, os.path.join(d, "linear.svg"))
            if path:
                res["render"] = path
        except Exception as e:  # noqa: BLE001
            res["render-error"] = str(e)

    def _competition(self, test, history):
        """Race the device engine and the host oracle; first definite verdict
        wins (knossos.competition parity)."""
        jm, cm = self._jax_model(), self._cpu_model()
        if jm is None or cm is None:
            # only one tier available: no race
            self2 = Linearizable(self.model, None, **self.engine_opts)
            return self2.check(test, history)
        done = threading.Event()
        results: Dict[str, Any] = {}

        def run_tpu():
            try:
                r = wgl_tpu.check(jm, history, **self.engine_opts)
            except Exception as e:  # noqa: BLE001
                r = {"valid": UNKNOWN, "error": str(e)}
            results.setdefault("winner", {**r, "solver": "tpu"})
            done.set()

        def run_cpu():
            try:
                r = wgl_cpu.check(cm, history)
            except Exception as e:  # noqa: BLE001
                r = {"valid": UNKNOWN, "error": str(e)}
            results.setdefault("winner", {**r, "solver": "cpu"})
            done.set()

        ts = [threading.Thread(target=run_tpu, daemon=True),
              threading.Thread(target=run_cpu, daemon=True)]
        for t in ts:
            t.start()
        done.wait()
        return results["winner"]


def linearizable(model, algorithm: Optional[str] = None, **kw) -> Checker:
    return Linearizable(model, algorithm, **kw)
