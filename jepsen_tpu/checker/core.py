"""Checker protocol, composition, and the standard checker battery.

Parity: jepsen.checker (jepsen/src/jepsen/checker.clj): a Checker examines a
completed history and returns a map with a ``valid`` verdict; verdicts merge
through a priority lattice where false beats unknown beats true
(checker.clj:29-50).  ``compose`` runs several checkers (in parallel threads,
like the reference's pmap, checker.clj:87) and merges; ``check_safe`` turns
checker crashes into unknown verdicts (checker.clj:74).

Checkers here: stats, unhandled_exceptions, queue, total_queue, set,
set_full, unique_ids, counter — history-in/verdict-out, no cluster needed.
The linearizable checker lives in jepsen_tpu.checker.linearizable.
"""

from __future__ import annotations

import threading
import time as _time
import traceback
from concurrent.futures import ThreadPoolExecutor
from collections import Counter as _Counter, defaultdict
from typing import Any, Dict, List, Optional

from jepsen_tpu.history import FAIL, History, INFO, INVOKE, NEMESIS, OK, Op

UNKNOWN = "unknown"


class Checker:
    def check(self, test: Dict[str, Any], history: History,
              opts: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Checker registry: the plugin seam core.run's analysis phase resolves
# through.  A test map can carry `checker` as a Checker instance (as
# before), a registered name ("elle-list-append"), a {"name": ..., **opts}
# spec, a {sub-name: spec} mapping (composed), or a list of specs.
# Factories are registered lazily so importing this module never drags in
# JAX or the elle machinery.

_REGISTRY: Dict[str, Any] = {}


def register_checker(name: str, factory) -> None:
    """Register ``factory(**opts) -> Checker`` under ``name``."""
    _REGISTRY[name] = factory


def registered_checkers() -> List[str]:
    return sorted(_REGISTRY)


def resolve_checker(spec) -> Checker:
    """Turn a checker spec into a Checker instance.

    - a ``Checker``: returned as-is;
    - ``"name"``: the registered factory, no opts;
    - ``{"name": n, **opts}``: the factory with opts;
    - ``{sub: spec, ...}``: a :class:`Compose` of resolved sub-specs;
    - ``[spec, ...]``: a Compose keyed by each spec's name.
    """
    if isinstance(spec, Checker):
        return spec
    if isinstance(spec, str):
        return _factory(spec)()
    if isinstance(spec, dict):
        if isinstance(spec.get("name"), str):
            opts = {k: v for k, v in spec.items() if k != "name"}
            return _factory(spec["name"])(**opts)
        return Compose({str(k): resolve_checker(v)
                        for k, v in spec.items()})
    if isinstance(spec, (list, tuple)):
        named: Dict[str, Checker] = {}
        for i, s in enumerate(spec):
            if isinstance(s, str):
                name = s
            elif isinstance(s, dict) and isinstance(s.get("name"), str):
                name = s["name"]
            else:
                name = f"{type(s).__name__.lower()}-{i}"
            base, k = name, 1
            while name in named:
                k += 1
                name = f"{base}-{k}"
            named[name] = resolve_checker(s)
        return Compose(named)
    raise TypeError(f"cannot resolve checker spec of type "
                    f"{type(spec).__name__}: {spec!r}")


def _factory(name: str):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"no checker registered as {name!r}; "
                       f"known: {registered_checkers()}") from None


def _lazy_elle(workload: str, **preset):
    def factory(**opts):
        from jepsen_tpu.checker.elle import ElleChecker
        return ElleChecker(workload=workload, **{**preset, **opts})
    return factory


def _lazy_linearizable(**opts):
    from jepsen_tpu.checker.linearizable import Linearizable
    return Linearizable(**opts)


def _register_builtins() -> None:
    for name, cls in [("stats", Stats), ("set", SetChecker),
                      ("set-full", SetFullChecker), ("queue", QueueChecker),
                      ("total-queue", TotalQueueChecker),
                      ("unique-ids", UniqueIds),
                      ("counter", CounterChecker),
                      ("unhandled-exceptions", UnhandledExceptions),
                      ("noop", NoopChecker)]:
        register_checker(name, cls)
    register_checker("linearizable", _lazy_linearizable)
    # The Elle checkers, device tier by default; the -cpu variants pin the
    # oracle path (parity baselines, device-free boxes).
    register_checker("elle-list-append", _lazy_elle("list-append"))
    register_checker("elle-rw-register", _lazy_elle("rw-register"))
    register_checker("elle-list-append-cpu",
                     _lazy_elle("list-append", engine="cpu"))
    register_checker("elle-rw-register-cpu",
                     _lazy_elle("rw-register", engine="cpu"))
    # The engine-substrate plugin seam: device-model checkers (queue, set)
    # and the opacity reduction register through it (engine/plugins.py is
    # import-light; each factory resolves its model/engine lazily).
    from jepsen_tpu.engine.plugins import register_builtin_plugins
    register_builtin_plugins(register_checker)


def merge_valid(valids: List[Any]) -> Any:
    """false > unknown > true (checker.clj:29-50)."""
    out = True
    for v in valids:
        if v is False:
            return False
        if v == UNKNOWN:
            out = UNKNOWN
    return out


def check_safe(checker: Checker, test, history, opts=None,
               budget_s: Optional[float] = None) -> Dict[str, Any]:
    """Run a checker, converting crashes into unknown verdicts
    (checker.clj:74).

    Every verdict gains ``duration-s`` — the checker's wall time — so
    budget tuning for degradation chains is data-driven, not guessed.

    ``budget_s`` (or ``opts["budget_s"]`` / ``test["checker_budget_s"]``)
    bounds the checker's wall clock: past the budget the verdict degrades
    to ``unknown`` with ``budget-exceeded`` instead of wedging the
    analysis phase (decrease-and-conquer spirit, arXiv:2410.04581 — a
    bounded partial answer beats an unbounded all-or-nothing solve).  The
    over-budget checker thread is abandoned (daemonized), never joined."""
    opts = opts or {}
    if budget_s is None:
        budget_s = opts.get("budget_s")
    if budget_s is None:
        budget_s = (test or {}).get("checker_budget_s")
    t0 = _time.monotonic()

    def finish(r: Dict[str, Any]) -> Dict[str, Any]:
        if isinstance(r, dict):
            r.setdefault("duration-s", round(_time.monotonic() - t0, 6))
        return r

    if budget_s is None:
        try:
            return finish(checker.check(test, history, opts))
        except Exception as e:  # noqa: BLE001
            return finish({"valid": UNKNOWN, "error": str(e),
                           "traceback": traceback.format_exc()})

    box: Dict[str, Any] = {}

    def work():
        try:
            box["result"] = checker.check(test, history, opts)
        except Exception as e:  # noqa: BLE001
            box["error"] = {"valid": UNKNOWN, "error": str(e),
                            "traceback": traceback.format_exc()}

    th = threading.Thread(target=work, daemon=True,
                          name=f"checker-{type(checker).__name__}")
    th.start()
    th.join(timeout=float(budget_s))
    if th.is_alive():
        return finish({"valid": UNKNOWN, "budget-exceeded": True,
                       "budget-s": float(budget_s),
                       "error": f"checker exceeded its {budget_s}s "
                                "wall-clock budget"})
    return finish(box["result"] if "result" in box else box["error"])


class Compose(Checker):
    """Run named sub-checkers concurrently; merge verdicts
    (checker.clj:87).

    ``budget_s`` gives every sub-checker the same wall-clock budget (they
    run concurrently, so it is also approximately the compose's own wall
    bound): a wedged sub-checker degrades to ``unknown`` while the rest
    still report — one backend failure never costs the whole analysis.
    Each sub-verdict carries ``duration-s`` (see :func:`check_safe`)."""

    def __init__(self, checkers: Dict[str, Checker],
                 budget_s: Optional[float] = None):
        self.checkers = checkers
        self.budget_s = budget_s

    def check(self, test, history, opts=None):
        opts = opts or {}
        if self.budget_s is not None and "budget_s" not in opts:
            opts = {**opts, "budget_s": self.budget_s}
        names = list(self.checkers)
        with ThreadPoolExecutor(max_workers=max(1, len(names))) as ex:
            futs = {n: ex.submit(check_safe, self.checkers[n], test, history,
                                 opts)
                    for n in names}
            results = {n: f.result() for n, f in futs.items()}
        out = {"valid": merge_valid([r.get("valid")
                                     for r in results.values()]),
               **results}
        # Surface crashed sub-checkers at the top level: an `unknown`
        # verdict must say *which* checker raised *what* without anyone
        # spelunking the nested result map (the reference prints the
        # exception at checker.clj:74; here it also persists in results).
        crashed = {n: r["traceback"] for n, r in results.items()
                   if r.get("valid") == UNKNOWN and "traceback" in r}
        if crashed:
            out["errors"] = crashed
        return out


def compose(checkers: Dict[str, Checker],
            budget_s: Optional[float] = None) -> Checker:
    return Compose(checkers, budget_s=budget_s)


class NoopChecker(Checker):
    def check(self, test, history, opts=None):
        return {"valid": True}


noop = NoopChecker
unbridled_optimism = NoopChecker  # the reference's cheekily-named default


class Stats(Checker):
    """Per-f ok/fail/info/crash counts; some f never succeeding degrades
    the verdict to unknown (a deliberate softening of checker.clj:166-183,
    which fails it — see the block comment in :meth:`check`)."""

    def check(self, test, history, opts=None):
        by_f: Dict[Any, _Counter] = defaultdict(_Counter)
        total = _Counter()
        for op in history:
            if op.process == NEMESIS or op.type == INVOKE:
                continue
            by_f[op.f][op.type] += 1
            total[op.type] += 1
        # Per-f verdict STRUCTURE is reference-style (checker.clj:145-183:
        # stats- puts a :valid? in every by-f block and the top level
        # merges them), but the zero-OK VERDICT deliberately diverges: the
        # reference sets ``:valid? (pos? ok-count)`` — an f that never
        # succeeded makes the block (and thus the run) *false*.  Here it
        # is UNKNOWN: zero successes is evidence of a broken client or
        # nemesis schedule, not of a consistency violation, and this
        # repo's false-means-witnessed discipline (every False carries a
        # refuting op; docs/fission.md) has no witness to attach.  The
        # self-documenting block still flags WHICH f starved — no
        # top-level error string shouting at whoever reads a passing
        # run's artifact under incident pressure.  Pinned by
        # tests/test_checkers.py::TestStats.
        blocks = {}
        never = False
        for f, c in by_f.items():
            f_ok = c[OK] > 0 or not (c[FAIL] > 0 or c[INFO] > 0)
            never = never or not f_ok
            blocks[f] = {"valid": True if f_ok else UNKNOWN, **dict(c)}
        return {"valid": UNKNOWN if never else True,
                "count": sum(total.values()),
                "ok-count": total[OK], "fail-count": total[FAIL],
                "info-count": total[INFO],
                "by-f": blocks}


class UnhandledExceptions(Checker):
    """Collect ops that crashed with errors, grouped by class
    (checker.clj:124)."""

    def check(self, test, history, opts=None):
        by_err: Dict[str, List[Op]] = defaultdict(list)
        for op in history:
            if op.error is not None and op.type == INFO:
                by_err[str(op.error)].append(op)
        return {"valid": True,
                "exceptions": {k: {"count": len(v),
                                   "example": v[0].to_dict()}
                               for k, v in by_err.items()}}


class SetChecker(Checker):
    """Grow-only set: adds followed by a final read; elements read-but-
    never-added are illegal; added-but-never-read are lost
    (checker.clj:240)."""

    def check(self, test, history, opts=None):
        attempts = set()
        adds = set()
        final_read = None
        for op in history:
            if op.f == "add" and op.type == INVOKE:
                attempts.add(op.value)
            elif op.f == "add" and op.type == OK:
                adds.add(op.value)
            elif op.f == "read" and op.type == OK:
                final_read = set(op.value or [])
        if final_read is None:
            return {"valid": UNKNOWN, "error": "no read completed"}
        lost = adds - final_read
        unexpected = final_read - attempts
        recovered = (final_read & attempts) - adds
        return {"valid": not lost and not unexpected,
                "attempt-count": len(attempts),
                "acknowledged-count": len(adds),
                "ok-count": len(final_read & attempts),
                "lost-count": len(lost), "lost": sorted(lost, key=repr),
                "unexpected-count": len(unexpected),
                "unexpected": sorted(unexpected, key=repr),
                "recovered-count": len(recovered)}


class SetFullChecker(Checker):
    """Per-element visibility analysis over many reads (checker.clj:294-461):
    each ok-add must eventually be visible; flags stale windows (absent then
    present) and lost elements (absent from the final reads)."""

    def check(self, test, history, opts=None):
        pairs = history.pair_index()
        add_done: Dict[Any, int] = {}   # element -> completion time
        reads: List[Op] = []            # ok reads with invoke times
        read_invoke_time: Dict[int, int] = {}
        for i, op in enumerate(history):
            if op.f == "add" and op.type == OK:
                j = pairs[i]
                inv = history[j] if j >= 0 else op
                add_done[inv.value if inv.value is not None else op.value] = \
                    op.time or 0
            elif op.f == "read" and op.type == OK:
                j = pairs[i]
                read_invoke_time[len(reads)] = \
                    (history[j].time if j >= 0 else op.time) or 0
                reads.append(op)
        if not reads:
            return {"valid": UNKNOWN, "error": "no reads completed"}
        lost, stale, never_read = [], [], []
        for e, t_add in add_done.items():
            later = [k for k in range(len(reads))
                     if read_invoke_time[k] >= t_add]
            if not later:
                never_read.append(e)
                continue
            present = [e in set(reads[k].value or []) for k in later]
            if not present[-1]:
                lost.append(e)
            elif not all(present):
                # absent somewhere, present later: stale window
                stale.append(e)
        return {"valid": merge_valid([not lost,
                                      UNKNOWN if never_read else True]),
                "add-count": len(add_done),
                "read-count": len(reads),
                "lost-count": len(lost), "lost": sorted(lost, key=repr),
                "stale-count": len(stale), "stale": sorted(stale, key=repr),
                "never-read-count": len(never_read)}


class QueueChecker(Checker):
    """Dequeues must match some enqueue; at-most-once delivery
    (checker.clj:218 queue)."""

    def check(self, test, history, opts=None):
        enq = _Counter()
        deq = _Counter()
        errors = []
        for op in history:
            if op.f == "enqueue" and op.type in (OK, INFO):
                enq[op.value] += 1
            elif op.f == "dequeue" and op.type == OK:
                deq[op.value] += 1
                if deq[op.value] > enq[op.value]:
                    errors.append(op.to_dict())
        return {"valid": not errors, "errors": errors}


class TotalQueueChecker(Checker):
    """Every enqueued element is dequeued exactly once (checker.clj:628):
    reports lost (acked enqueue, never dequeued), unexpected (dequeued,
    never enqueued), duplicated (dequeued more than once), and recovered
    (uncertain enqueue that was dequeued)."""

    def check(self, test, history, opts=None):
        attempts = _Counter()
        enqueues = _Counter()
        dequeues = _Counter()
        for op in history:
            if op.f == "enqueue" and op.type == INVOKE:
                attempts[op.value] += 1
            elif op.f == "enqueue" and op.type == OK:
                enqueues[op.value] += 1
            elif op.f == "dequeue" and op.type == OK:
                dequeues[op.value] += 1
            elif op.f == "drain" and op.type == OK \
                    and isinstance(op.value, (list, tuple)):
                # client-side drain loops return everything they pulled
                # (the reference logs these as individual dequeues,
                # disque.clj:216-240)
                for v in op.value:
                    dequeues[v] += 1
        lost = {v: n - dequeues[v] for v, n in enqueues.items()
                if dequeues[v] < n}
        unexpected = {v: n for v, n in dequeues.items() if attempts[v] == 0}
        duplicated = {v: n - max(attempts[v], 1)
                      for v, n in dequeues.items()
                      if n > max(attempts[v], 1)}
        recovered = {v: n for v, n in dequeues.items()
                     if 0 < n <= attempts[v] and enqueues[v] < n}
        return {"valid": not lost and not unexpected and not duplicated,
                "attempt-count": sum(attempts.values()),
                "acknowledged-count": sum(enqueues.values()),
                "ok-count": sum(dequeues.values()),
                "lost-count": sum(lost.values()), "lost": lost,
                "unexpected-count": sum(unexpected.values()),
                "unexpected": unexpected,
                "duplicated-count": sum(duplicated.values()),
                "duplicated": duplicated,
                "recovered-count": sum(recovered.values())}


class UniqueIds(Checker):
    """All ok-op values are distinct (checker.clj:689)."""

    def check(self, test, history, opts=None):
        seen = _Counter()
        for op in history:
            if op.type == OK and op.value is not None:
                seen[op.value] += 1
        dups = {v: n for v, n in seen.items() if n > 1}
        return {"valid": not dups,
                "attempted-count": sum(seen.values()),
                "acknowledged-count": len(seen),
                "duplicated-count": len(dups),
                "duplicated": dups}


class CounterChecker(Checker):
    """Reads of a PN-counter must fall within the feasible envelope implied
    by concurrent adds (checker.clj:737): a read may observe any subset of
    the adds that were pending at any instant during it, plus everything
    surely applied, never excluding anything surely applied before it
    began."""

    def check(self, test, history, opts=None):
        pairs = history.pair_index()
        # Adds whose completion is FAIL definitively did not apply: the
        # reference removes them before computing bounds (checker.clj
        # counter's remove-failed preprocessing), so they must never widen
        # any read's envelope — not even a read concurrent with them.
        failed_invokes = {int(pairs[i]) for i, op in enumerate(history)
                          if op.f == "add" and op.type == FAIL
                          and int(pairs[i]) >= 0}
        reads = []
        lo = hi = 0          # envelope of possibly-applied sums
        applied = 0          # surely applied (ok) sum
        open_adds: Dict[int, int] = {}  # invoke index -> delta
        # invoke index -> [min lo, max hi] seen over the read's open window:
        # an add concurrent with a read (in either direction) may legally
        # be observed or missed, so a read is acceptable anywhere inside
        # the widest envelope of its interval (checker.clj:737)
        open_reads: Dict[int, list] = {}
        errors = []

        def move_envelope(nlo, nhi):
            nonlocal lo, hi
            lo, hi = nlo, nhi
            for w in open_reads.values():
                w[0] = min(w[0], lo)
                w[1] = max(w[1], hi)

        for i, op in enumerate(history):
            if op.f == "read" and op.type == INVOKE:
                open_reads[i] = [lo, hi]
            if op.f == "add":
                d = op.value or 0
                if op.type == INVOKE:
                    if i in failed_invokes:
                        continue  # never applied; widens nothing
                    open_adds[i] = d
                    if d > 0:
                        move_envelope(lo, hi + d)
                    else:
                        move_envelope(lo + d, hi)
                elif op.type == OK:
                    j = int(pairs[i])
                    d = open_adds.pop(j, d)
                    applied += d
                    if d > 0:
                        move_envelope(lo + d, hi)
                    else:
                        move_envelope(lo, hi + d)
                elif op.type in (FAIL,):
                    # Envelope was never widened for this add (pre-scan);
                    # nothing to narrow.
                    open_adds.pop(int(pairs[i]), None)
                # INFO: stays open forever (may or may not apply)
            elif op.f == "read" and op.type == OK:
                v = op.value
                rd_lo, rd_hi = open_reads.pop(int(pairs[i]), [lo, hi])
                if v is None or not (rd_lo <= v <= rd_hi):
                    errors.append({**op.to_dict(),
                                   "bounds": [rd_lo, rd_hi]})
                reads.append(v)
        return {"valid": not errors,
                "reads": len(reads), "errors": errors,
                "final-bounds": [lo, hi], "applied-sum": applied}


class LogFilePattern(Checker):
    """Grep downloaded node logs for a pattern (checker.clj:839); reads from
    the store directory if present."""

    def __init__(self, pattern: str, filename: str):
        import re
        self.re = re.compile(pattern)
        self.filename = filename

    def check(self, test, history, opts=None):
        import os
        matches = []
        d = (opts or {}).get("store_dir") or test.get("store_dir")
        if not d:
            return {"valid": UNKNOWN, "error": "no store dir with logs"}
        for root, _, files in os.walk(d):
            for fn in files:
                if fn != self.filename:
                    continue
                path = os.path.join(root, fn)
                try:
                    with open(path, errors="replace") as f:
                        for line in f:
                            if self.re.search(line):
                                matches.append({"file": path,
                                                "line": line.strip()})
                except OSError:
                    continue
        return {"valid": not matches, "count": len(matches),
                "matches": matches[:10]}


class ConcurrencyLimitChecker(Checker):
    """Bound how many expensive checks run at once via a shared semaphore
    (checker.clj:101-116)."""

    _sems: Dict[str, Any] = {}

    def __init__(self, limit: int, inner: Checker, key: str = "default"):
        import threading
        self.inner = inner
        sem = self._sems.setdefault(f"{key}:{limit}",
                                    threading.Semaphore(limit))
        self.sem = sem

    def check(self, test, history, opts=None):
        with self.sem:
            return self.inner.check(test, history, opts)


def concurrency_limit(limit: int, inner: Checker) -> Checker:
    return ConcurrencyLimitChecker(limit, inner)


_register_builtins()
