"""Checkers: history-in, verdict-out analysis engines.

The plugin seam matching the reference's jepsen.checker namespace: a Checker
checks a completed history; verdicts merge false > unknown > true.  The
linearizable checker dispatches to the CPU oracle or the TPU search engine.
"""

from jepsen_tpu.checker.core import (  # noqa: F401
    Checker, Compose, CounterChecker, LogFilePattern, NoopChecker,
    QueueChecker, SetChecker, SetFullChecker, Stats, TotalQueueChecker,
    UNKNOWN, UnhandledExceptions, UniqueIds, check_safe, compose,
    concurrency_limit, merge_valid, noop, unbridled_optimism,
)
from jepsen_tpu.checker.linearizable import Linearizable, linearizable  # noqa: F401
