"""Checkers: history-in, verdict-out analysis engines.

The plugin seam matching the reference's jepsen.checker namespace: a Checker
checks a completed history; verdicts merge false > unknown > true.  The
linearizable checker dispatches to the CPU oracle or the TPU search engine.
"""

from jepsen_tpu.checker.core import (  # noqa: F401
    Checker, Compose, CounterChecker, LogFilePattern, NoopChecker,
    QueueChecker, SetChecker, SetFullChecker, Stats, TotalQueueChecker,
    UNKNOWN, UnhandledExceptions, UniqueIds, check_safe, compose,
    concurrency_limit, merge_valid, noop, register_checker,
    registered_checkers, resolve_checker, unbridled_optimism,
)
from jepsen_tpu.checker.elle import (  # noqa: F401
    ElleChecker, ElleListAppend, ElleRwRegister, elle_list_append,
    elle_rw_register,
)
from jepsen_tpu.checker.linearizable import Linearizable, linearizable  # noqa: F401
