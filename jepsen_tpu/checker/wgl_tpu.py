"""Device-tier linearizability engine — the point of this framework.

Replaces the reference's external knossos solver (invoked at
jepsen/src/jepsen/checker.clj:185-216) with a JAX search that runs entirely in
fixed-shape device buffers:

- A configuration is (pending-window bitmask, model state): uint32[MW] mask
  lanes + int32[S] state lanes (see prep.py for why that compression is
  complete).  The engine holds up to ``capacity`` configurations.
- The history is a stream of ENTER/RETURN events consumed by ``lax.scan`` in
  chunks; the host polls failure/overflow flags between chunks (early exit),
  so a refuted history stops in O(prefix).
- At a RETURN event the engine expands the configuration closure: a nested
  vmap applies the model step to every (configuration × pending op) pair —
  [C, W] parallel model steps per round — then the union is deduplicated and
  compacted by a multi-key sort (ops/dedup.py).  Closure repeats to fixpoint
  (no genuinely-new kept candidate), then configurations lacking the
  returning op are pruned.
- Closure is skipped when the set is already closed: pruning on a bit
  preserves closedness (expansions of a surviving configuration also carried
  the bit), so closure is only needed after new ENTERs — the ``dirty`` flag.
- **Ghost subsumption** (the algorithmic contribution that moves the
  practical ceiling): slots held by *ghost* ops — crashed/info ops that
  never return — are never consulted by pruning, so (a) ghosts with equal
  op encodings are interchangeable and a config's ghost bits canonicalize
  to per-class counts, and (b) a config is dropped when one with the same
  non-ghost mask and state holds a subset of its ghost bits (it has a
  superset of the dropped config's futures and can re-derive it at any
  later closure).  Classic configuration search pays 2^crashes — the
  precise regime where the reference's knossos dies and histories must be
  kept short (jepsen/src/jepsen/independent.clj:1-7); with subsumption the
  cost is the antichain of ghost-count vectors, typically O(crashes).

Single-history frontier sharding across a device mesh lives in
jepsen_tpu.parallel; this module is mesh-agnostic but takes an optional
``axis_name`` so the closure can all_gather candidate rows and keep a
device-local slice of the deduplicated global set.
"""

from __future__ import annotations

import math
import os as _os
import sys as _sys
import time as _time
from collections import deque
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu.checker.prep import (
    EV_ENTER, EV_RETURN, PreparedHistory, WindowOverflow, prepare,
)
from jepsen_tpu.clock import mono_now
from jepsen_tpu.engine.cache import CACHE as _ENGINE_CACHE
from jepsen_tpu.engine.ladder import round_window as _round_window
from jepsen_tpu.engine.witness import (
    WITNESS_BUDGET, cpu_witness as _cpu_witness,
)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel
from jepsen_tpu.ops import dedup as _dedup
from jepsen_tpu.ops.dedup import compact_rows, sort_dedup_compact

EV_NOP = 2

# Chunks dispatched ahead of the host's flag poll, so the device→host flags
# transfer of chunk i overlaps with the device computing chunk i+1.
LOOKAHEAD = 2

# (Round-3's EXPAND_BLOCK block-partitioned closure is gone: the delta
# closure with candidate compaction — see make_engine.closure — replaced
# per-block C*(B+1)-row sorts with one compacted C+NC-row merge per round,
# measured 20.2s -> well under the round-3 easy-tier wall on hardware.)

# Per-chunk closure work budget, in capacity x closure-iterations units.
# Closure cost is superlinear in live configuration count (more fixpoint
# rounds AND bigger sorts), so bounding the program by *event count* alone
# cannot bound its duration — a 32-event chunk was measured at 26 s during
# a 7k-config burst at capacity 16384, within sight of the TPU worker's
# ~60 s watchdog.  Instead each chunk carries an iteration budget
# (CLOSURE_WORK_BUDGET / capacity); when it runs out the remaining events
# gate to no-ops, the flags report how many events were really consumed,
# and the host resumes mid-chunk with a fresh budget.  (4M with the delta
# closure's compacted merges: per-iteration cost dropped ~4x vs the block
# closure, so the same watchdog margin affords more iterations per
# dispatch — fewer budget pauses means fewer discarded speculative
# dispatches; measured easy-tier 7.5 s vs 7.8 s at 3M, hard tier
# unchanged.  At capacity 65536 this is 61 iterations/dispatch, which
# stays inside the watchdog even when rounds take the full-grid fallback
# merge.)
CLOSURE_WORK_BUDGET = int(_os.environ.get("JTPU_CLOSURE_BUDGET", "4000000"))

#: Histories with at most this many ghost (crashed/info) ops run the LEAN
#: engine (``gwords=0``): ghost bits stay plain identity mask bits and the
#: whole subsumption pipeline — per-class canonicalization (a matmul),
#: compact-word expansion, and the subset probes — drops out of every merge.
#: Subsumption is an optimization, never a soundness condition: verdicts
#: are identical either way, only the explored-config count (and with it,
#: capacity pressure) changes.  Default 0 — measured on hardware, even 4
#: unsubsumed crashed CAS writes blew the 10k-op easy history from 819k to
#: 2.2M configs and forced capacity 16384 (18.5 s vs 6.6 s): the antichain
#: collapse matters at ANY ghost count, so lean is only for histories with
#: no ghosts at all, where it saves the machinery with nothing to lose.
LEAN_GHOST_MAX = int(_os.environ.get("JTPU_LEAN_GHOSTS", "0"))


def closure_budget(capacity: int) -> int:
    """Closure iterations one chunk may spend at this capacity.

    ``capacity`` is the TOTAL rows a closure iteration sorts: callers whose
    per-iteration cost scales beyond a single engine's capacity (sharded:
    capacity_per_shard * n_shards gathered rows; batch: capacity * lanes)
    pass that product so one dispatch's wall-clock stays at the same bound
    everywhere."""
    return max(16, CLOSURE_WORK_BUDGET // max(1, capacity))


def engine_window(window: int) -> int:
    """The slot count an engine built for ``window`` actually uses (the
    delta closure expands the full window at once, so no block padding —
    kept as the single source of truth for callers that build
    window-shaped carries outside carry0, e.g. parallel.sharded)."""
    return window


# carry = (mask, states, valid, win_ops, active, dirty, failed, failed_op,
#          overflow, explored, rounds, peak, ghosts, budget, consumed,
#          cl_iters, fresh, cur_new)
# peak is the high-water mark of the distinct-configuration count since the
# driver last reset it: the capacity the search *actually* needed, which the
# host reads at chunk boundaries to pick the cheapest sufficient engine.
# ghosts is the uint32[MW] bitmask of window slots held by ops that never
# return (crashed/info ops): closure dedup subsumes on it (see closure).
# budget/consumed implement the per-dispatch work bound (see closure_budget);
# cl_iters is the cumulative fixpoint-iteration count of the *current paused
# closure* — it persists across pause/resume dispatches so the W+1
# convergence cap applies to the cumulative count, exactly as it did when a
# closure always ran inside one dispatch.  fresh ([W] bool) marks slots
# ENTERed since the last completed closure (delta round 0's slot gate);
# cur_new ([C] bool) marks rows added by the previous closure round (delta
# rounds' row gate) — both persist across pause/resume.


def make_engine(model: JaxModel, window: int, capacity: int,
                axis_name: Optional[str] = None, num_shards: int = 1,
                gwords: int = 1, work_budget: Optional[int] = None,
                single_round_closure: bool = False,
                steps_per_dispatch: int = 256):
    """Build the jittable (carry0, event_step, run_chunk) triple.

    ``window`` may be any positive slot count (candidate-row count — and so
    closure sort cost — scales with it, so callers pass the tightest window
    the history needs).  With ``axis_name``, buffers are device-local shards
    of a global set of ``capacity * num_shards`` configurations and closure
    dedup synchronizes via all_gather.  ``gwords`` is the number of compact
    ghost words (>= ceil(n_ghosts / 32) for the history being checked):
    ghost subsumption state sorts as ``gwords`` columns, not ceil(W/32) —
    keeping the big variadic sort narrow (wide sorts at high capacity have
    crashed the TPU compiler).  ``gwords=0`` builds the LEAN engine: ghost
    bits are ordinary identity mask bits, and canonicalization, compact
    expansion, and subsumption all vanish from the merge — sound for any
    history (subsumption is an optimization), chosen by drivers when the
    ghost count is small (chosen_gwords).

    ``single_round_closure`` builds the VMAP-SAFE variant for the batched
    (per-lane) driver: under vmap, ``lax.cond``/``switch`` execute EVERY
    branch for the whole batch, so the standard engine's three merge
    widths and per-return fixpoint loop multiply into a per-step cost that
    outruns the TPU watchdog (the round-2/3 batch-tier killer).  This mode
    runs exactly ONE closure round per scan step with ONE merge width
    (NC = C; a round whose candidates overflow the compacted buffer flags
    engine overflow and the lane escalates).  A RETURN whose closure
    hasn't converged parks in the pending-return register and later steps
    continue it one round at a time; each step gathers the lane's next
    event by the lane's own absolute ``consumed`` cursor (run_chunk's
    ``events`` is then the FULL stream and ``steps_per_dispatch`` fixes
    the program length), so per-step device work is constant, a
    dispatch's wall-clock is bounded by its step count, and vmapped lanes
    progress at fully independent rates with no idle steps.
    """
    assert window > 0
    # Callers building window-shaped carries outside carry0
    # (parallel.sharded) must use
    # engine_window() for the same padding.
    window = engine_window(window)
    # work_budget: None = capacity-scaled default; <= 0 = unlimited
    # (escape hatch for callers that manage their own bounds — the
    # shipped drivers all pass a real budget: the batch driver resumes
    # lanes at independent positions via per-lane consumed counts).
    if work_budget is None:
        work_budget = closure_budget(capacity)
    if work_budget <= 0:
        work_budget = 2**31 - 1
    try:
        # All three engine paths (single-chip, sharded, batched) build here;
        # enabling the persistent compilation cache at this shared layer
        # turns repeat compiles of any engine shape into disk loads.
        # Best-effort: a read-only fs must not break checking.
        from jepsen_tpu.ops.cache import enable_compilation_cache
        enable_compilation_cache()
    except Exception:  # noqa: BLE001
        pass
    W, MW, S, C = window, (window + 31) // 32, model.state_size, capacity
    step = model.step

    # slot_masks[w] = uint32[MW] with bit w set.
    sm = np.zeros((W, MW), np.uint32)
    for w in range(W):
        sm[w, w // 32] = np.uint32(1) << np.uint32(w % 32)
    slot_masks = jnp.asarray(sm)

    def slot_bitmask(slot):
        word = slot // 32
        bit = jnp.left_shift(jnp.uint32(1), (slot % 32).astype(jnp.uint32))
        return jnp.where(jnp.arange(MW) == word, bit, jnp.uint32(0))

    def expand(states, win_ops):
        def per_config(st):
            def per_slot(op):
                ns, ok = step(st, op[0], op[1], op[2])
                return ns.astype(jnp.int32), ok
            return jax.vmap(per_slot)(win_ops)
        return jax.vmap(per_config)(states)  # [C, W, S], [C, W]

    def global_sum(x):
        return lax.psum(x, axis_name) if axis_name else x

    # Per-slot word index / shift for 2D bit extraction (the [N, W, MW]
    # broadcast form would materialize gigabytes at large C*(W+1)).
    GW = gwords
    word_of = jnp.arange(W) // 32
    shift_of = (jnp.arange(W) % 32).astype(jnp.uint32)

    def canonical_compact(mask_words, win_ops):
        """Canonical *compact* ghost state per row: same-encoding ghosts
        are interchangeable (identical step functions, none ever returns),
        so only the per-class COUNT of linearized ghosts matters.  The
        canonical form sets, for each class, the first ``count`` bits of
        the class's contiguous range in a ceil(n_ghosts/32)-word compact
        layout (prep assigns ``gpos`` = class offset + rank)."""
        cls = win_ops[:, 3]                  # [W] class id (slot) or -1
        rank = win_ops[:, 4]                 # [W] rank within class
        gpos = win_ops[:, 5]                 # [W] compact bit position
        is_g = cls >= 0
        bits = (jnp.take(mask_words, word_of, axis=1)
                >> shift_of[None, :]) & 1
        # counts[n, c] = number of class-c ghost bits set in row n (matmul
        # on the MXU; counts <= W, exact in float32)
        onehot = ((cls[None, :] == jnp.arange(W)[:, None]) &
                  is_g[None, :]).astype(jnp.float32)       # [W(cls), W(slot)]
        counts = bits.astype(jnp.float32) @ onehot.T       # [N, W]
        cnt_for_slot = jnp.take(counts, jnp.clip(cls, 0, W - 1), axis=1)
        cbits = (is_g[None, :] & (rank[None, :].astype(jnp.float32)
                                  < cnt_for_slot)).astype(jnp.uint32)
        out = []
        for j in range(GW):
            w = jnp.where(is_g & (gpos // 32 == j),
                          jnp.left_shift(jnp.uint32(1),
                                         (gpos % 32).astype(jnp.uint32)),
                          jnp.uint32(0))
            out.append((cbits * w[None, :]).sum(1, dtype=jnp.uint32))
        return jnp.stack(out, axis=-1)                     # [N, GW]

    def expand_compact(compact, win_ops):
        """Inverse of :func:`canonical_compact`: slot-space ghost words
        from a compact row (bit gpos[s] -> slot bit s)."""
        cls = win_ops[:, 3]
        gpos = win_ops[:, 5]
        is_g = cls >= 0
        word = jnp.take(compact, jnp.clip(gpos // 32, 0, GW - 1), axis=1)
        bits = ((word >> (gpos % 32).astype(jnp.uint32)[None, :]) & 1) \
            * is_g[None, :].astype(jnp.uint32)
        out = []
        for i in range(MW):
            sl = slice(32 * i, min(32 * i + 32, W))
            powers = (jnp.uint32(1) << shift_of[sl])
            out.append((bits[:, sl] * powers[None, :]).sum(
                1, dtype=jnp.uint32))
        return jnp.stack(out, axis=-1)                     # [N, MW]

    def closure(mask, states, valid, win_ops, active, ghosts, overflow,
                budget, it0, fresh, cur_new, enable=None):
        # Dedup treats the ghost-slot part of the mask as a *subsumption*
        # column, not an identity column: ghost ops never return, so their
        # bits are never consulted by pruning, and a config whose ghost set
        # contains another's (same non-ghost mask, same state) has a subset
        # of its futures and is re-derivable from it at any later closure.
        # Together with per-class canonicalization this turns the
        # 2^crashes configuration blowup that kills knossos into
        # O(crashes) — see BENCH ghost tiers.
        #
        # **Delta (semi-naive) evaluation** — the round-4 speedup.  The set
        # is closed between closures, so round 0 only expands (all rows) x
        # (slots ENTERed since the last closure — ``fresh``), and round
        # r>0 only expands (rows kept NEW by round r-1 — ``cur_new``) x
        # (all active slots).  Soundness: S was closed over the old slots;
        # S x old-slots candidates are already present-or-subsumed, and a
        # row dropped by subsumption is simulated by its (kept, expanded)
        # dropper, whose successors subsume the dropped row's successors.
        #
        # **Candidate compaction** — the valid candidates of a round are
        # usually far fewer than the C*W expansion grid, so they compact
        # (stable sort + payload carry, ops.dedup.compact_rows — TPU
        # scatters serialize per update) into a small buffer and the
        # merge sorts C + NC rows instead of C*(W+1).  Four merge widths
        # are compiled (NC = C/2, C, 4C, and the full C*W grid) and
        # selected per round by the (shard-uniform) candidate count.
        #
        # ``budget`` caps the fixpoint iterations of THIS call: a closure
        # that runs out pauses (returns converged=False) with the partial —
        # but sound, monotone — set; the caller must then keep the dirty
        # flag, not consume the event, and let the host resume the same
        # RETURN in a fresh dispatch, where closure continues from the
        # partial set to the same fixpoint.  This makes the per-dispatch
        # iteration bound *tight* (<= budget), not budget + window.
        count0 = global_sum(valid.sum())

        def merge_rows(mask, states, valid, cand_mask, cand_states,
                       cand_valid, ovf, round_new=None):
            """Dedup/compact the union of the existing set and this
            round's candidate rows; returns the new set, per-row newness,
            and fixpoint/overflow signals.

            ``round_new`` (bool[C], tiled-fold path only) marks existing
            rows that were added by an EARLIER fold of the same closure
            round: they must stay in the returned ``cur_new`` (the next
            round's delta frontier) but must not re-trigger the new-rows
            fixpoint signal.  Encoded as origin 2 — dedup's ``new_rows``
            only counts origin 1 (candidates), while the returned frontier
            keeps any origin >= 1."""
            nc = cand_valid.shape[0]
            all_mask = jnp.concatenate([mask, cand_mask])
            all_states = jnp.concatenate([states, cand_states])
            all_valid = jnp.concatenate([valid, cand_valid])
            exist_origin = (jnp.zeros(C, jnp.int32) if round_new is None
                            else 2 * round_new.astype(jnp.int32))
            origin = jnp.concatenate([exist_origin,
                                      jnp.ones(nc, jnp.int32)])
            if axis_name is not None:
                all_mask = lax.all_gather(all_mask, axis_name, tiled=True)
                all_states = lax.all_gather(all_states, axis_name,
                                            tiled=True)
                all_valid = lax.all_gather(all_valid, axis_name, tiled=True)
                origin = lax.all_gather(origin, axis_name, tiled=True)
            if GW:
                keyed = all_mask & ~ghosts[None, :]
                gpart = canonical_compact(all_mask & ghosts[None, :],
                                          win_ops)
                gcols = [gpart[:, i] for i in range(GW)]
            else:
                # Lean engine: ghost bits are identity bits like any other;
                # no canonicalization column, no subset subsumption.
                keyed = all_mask
                gcols = []
            cols = ([keyed[:, i] for i in range(MW)]
                    + [all_states[:, i] for i in range(S)])
            gcap = C * num_shards
            out_cols, out_valid, total, ovf2, new_rows, out_orig = \
                sort_dedup_compact(cols, all_valid, gcap,
                                   ghost_cols=gcols, origin=origin)
            new_keyed = jnp.stack(out_cols[:MW], -1)
            new_states = jnp.stack(out_cols[MW:MW + S], -1)
            if GW:
                new_compact = jnp.stack(out_cols[MW + S:], -1)
                new_mask = new_keyed | expand_compact(new_compact, win_ops)
            else:
                new_mask = new_keyed
            cur_new2 = (out_orig >= 1) & out_valid
            if axis_name is not None:
                start = lax.axis_index(axis_name) * C
                new_mask = lax.dynamic_slice_in_dim(new_mask, start, C)
                new_states = lax.dynamic_slice_in_dim(new_states, start, C)
                out_valid = lax.dynamic_slice_in_dim(out_valid, start, C)
                cur_new2 = lax.dynamic_slice_in_dim(cur_new2, start, C)
            return new_mask, new_states, out_valid, cur_new2, total, \
                new_rows, ovf | ovf2

        def compact_to(cand_mask, cand_states, cv, NC):
            """Compact the [C, W] candidate grid's valid rows into NC rows
            (stable sort + gather; a scatter here serialized over all C*W
            grid rows on TPU and was the closure's single hottest op —
            see ops.dedup.compact_rows)."""
            (cm, cs), cvv, _total = compact_rows(
                [cand_mask.reshape(C * W, MW),
                 cand_states.reshape(C * W, S)],
                cv.reshape(C * W), NC)
            return cm, cs, cvv

        def cond(c):
            _, _, _, _, _, changed, ovf, it = c
            return changed & ~ovf & (it < W + 1) & (it - it0 < budget)

        def body(c):
            mask, states, valid, cur_new, count, _, ovf, it = c
            # Full-window expansion grid, gated by the delta rule.
            cand_states, ok = expand(states, win_ops)          # [C, W, S]
            has = ((mask[:, None, :] & slot_masks[None, :, :]) != 0).any(-1)
            round0 = it == 0
            row_gate = jnp.where(round0, valid, valid & cur_new)
            slot_gate = jnp.where(round0, active & fresh, active)
            cv = row_gate[:, None] & slot_gate[None, :] & ~has & ok
            if enable is not None:  # lane-level gate (single-round mode)
                cv = cv & enable
            cand_mask = mask[:, None, :] | slot_masks[None, :, :]
            nv = cv.sum().astype(jnp.int32)
            nv_max = (lax.pmax(nv, axis_name)
                      if axis_name is not None else nv)
            some = global_sum(nv) > 0

            def merge_compacted(NC):
                def f(args):
                    mask, states, valid, cur_new, ovf = args
                    cm, cs, cvv = compact_to(cand_mask, cand_states, cv, NC)
                    return merge_rows(mask, states, valid, cm, cs, cvv, ovf)
                return f

            def merge_full(args):
                mask, states, valid, cur_new, ovf = args
                return merge_rows(mask, states, valid,
                                  cand_mask.reshape(C * W, MW),
                                  cand_states.reshape(C * W, S),
                                  cv.reshape(C * W), ovf)

            def merge_full_tiled(args):
                """Full-grid merge as a fold over candidate tiles, each
                merge kept under ops.dedup.WIDE_SORT_ROWS so every sort
                takes the single-variadic-sort path.  One C*(W+1)-row
                merge at capacity 65536 exceeds the threshold and falls
                back to the _lex_perm sort chain, whose ~11 full-size
                sort passes compile for tens of minutes on TPU — and
                lax.switch compiles ALL branches, so every 65536-capacity
                engine paid that even when the full fallback never ran.
                The fold's loop body compiles ONCE at (C + tile) rows.

                Soundness of folding: the existing set participates in
                every fold, so duplicates against it are always dropped;
                a candidate duplicating an earlier fold's survivor sees
                that survivor as an existing row.  ``round_new`` threads
                the this-round frontier through the folds (origin-2
                protocol in merge_rows)."""
                mask, states, valid, cur_new, ovf = args
                flat_mask = cand_mask.reshape(C * W, MW)
                flat_states = cand_states.reshape(C * W, S)
                flat_cv = cv.reshape(C * W)
                budget_rows = max(_dedup.WIDE_SORT_ROWS // num_shards - C,
                                  C)
                K = -(-(C * W) // budget_rows)  # ceil
                T = -(-(C * W) // K)
                pad = K * T - C * W
                if pad:
                    flat_mask = jnp.concatenate(
                        [flat_mask, jnp.zeros((pad, MW), flat_mask.dtype)])
                    flat_states = jnp.concatenate(
                        [flat_states, jnp.zeros((pad, S),
                                                flat_states.dtype)])
                    flat_cv = jnp.concatenate(
                        [flat_cv, jnp.zeros(pad, flat_cv.dtype)])

                def fold(i, acc):
                    mask, states, valid, rnew, total, newr, ovf = acc
                    tm = lax.dynamic_slice_in_dim(flat_mask, i * T, T)
                    ts = lax.dynamic_slice_in_dim(flat_states, i * T, T)
                    tv = lax.dynamic_slice_in_dim(flat_cv, i * T, T)
                    m2, s2, v2, rnew2, total2, nr2, ovf2 = merge_rows(
                        mask, states, valid, tm, ts, tv, ovf,
                        round_new=rnew)
                    return (m2, s2, v2, rnew2, total2, newr | nr2, ovf2)

                init = (mask, states, valid, jnp.zeros_like(valid),
                        count, jnp.bool_(False), ovf)
                m2, s2, v2, rnew, total, newr, ovf2 = lax.fori_loop(
                    0, K, fold, init)
                return m2, s2, v2, rnew, total, newr, ovf2

            def do(args):
                if single_round_closure:
                    # vmap runs every switch branch, so the batched engine
                    # gets ONE width; compact_to silently truncates past
                    # NC, which would be unsound — flag overflow instead
                    # so the driver escalates the lane.
                    out = merge_compacted(C)(args)
                    return out[:6] + (out[6] | (nv > C),)
                # Merge width by (shard-uniform) candidate volume: the
                # typical round's candidates are at most the live count
                # (well under C/2 in steady state), burst rounds take the
                # C or 4C buffers, and the full grid is the rare fallback.
                half = max(1, C // 2)
                full = (merge_full_tiled
                        if num_shards * C * (W + 1) > _dedup.WIDE_SORT_ROWS
                        else merge_full)
                sel = jnp.where(nv_max <= half, 0,
                                jnp.where(nv_max <= C, 1,
                                          jnp.where(nv_max <= 4 * C, 2,
                                                    3)))
                return lax.switch(sel, [merge_compacted(half),
                                        merge_compacted(C),
                                        merge_compacted(4 * C),
                                        full], args)

            def skip(args):
                mask, states, valid, cur_new, ovf = args
                return (mask, states, valid,
                        jnp.zeros_like(cur_new), count,
                        jnp.bool_(False), ovf)

            mask, states, valid, cur_new, count, changed, ovf = lax.cond(
                some, do, skip, (mask, states, valid, cur_new, ovf))
            # Fixpoint signal: a kept candidate, NOT a count delta —
            # subsumption can drop an existing row in the round that adds a
            # new one, leaving the count level while the set moved.
            return (mask, states, valid, cur_new, count, changed, ovf,
                    it + 1)

        init = (mask, states, valid, cur_new, count0, jnp.bool_(True),
                overflow, it0)
        if single_round_closure:
            # One round per call.  NOTE the consume-on-arrival design: a
            # RETURN is consumed the step it arrives and parked in the
            # pending-return register; successive steps run one round
            # each until convergence lands the prune.  The host must
            # therefore treat a lane as LIVE while its stalled flag is
            # set even if its cursor passed the stream end (flags[4]).
            mask, states, valid, cur_new, count, changed, overflow, \
                it_fin = body(init)
        else:
            (mask, states, valid, cur_new, count, changed, overflow,
             it_fin) = lax.while_loop(cond, body, init)
        # Exit reasons: fixpoint (~changed), the W+1 cumulative chain-depth
        # cap (treated as converged — matches the pre-budget behavior), or
        # budget exhaustion — the only pause case.
        converged = ~changed | (it_fin >= W + 1)
        return mask, states, valid, cur_new, count, overflow, it_fin, \
            converged

    def event_step(carry, ev):
        (mask, states, valid, win_ops, active, dirty, failed, failed_op,
         overflow, explored, rounds, peak, ghosts, budget, consumed,
         cl_iters, fresh, cur_new) = carry
        kind, slot, f, a, b, op_id, is_ghost, gcls, grank, gpos = (
            ev[0], ev[1], ev[2], ev[3], ev[4], ev[5], ev[6], ev[7], ev[8],
            ev[9])
        # budget > 0: an exhausted closure budget pauses the chunk — the
        # remaining events gate to no-ops and the host resumes them in a
        # fresh dispatch (consumed tells it where).  Bounds one XLA
        # program's duration by *work*, which event counts cannot.
        alive = ~failed & ~overflow & (budget > 0)

        def do_enter(c):
            (mask, states, valid, win_ops, active, dirty, failed, failed_op,
             overflow, explored, rounds, peak, ghosts, budget, consumed,
             cl_iters, fresh, cur_new) = c
            win_ops2 = win_ops.at[slot].set(
                jnp.stack([f, a, b, gcls, grank, gpos]))
            active2 = active.at[slot].set(True)
            fresh2 = fresh.at[slot].set(True)  # delta-closure round 0 gate
            # A crashed op holds its slot forever; its bit becomes a
            # subsumption column in closure dedup.  (Slots of crashed ops
            # are never freed, so the bit can't later mean a live op.)
            ghosts2 = jnp.where(is_ghost == 1,
                                ghosts | slot_bitmask(slot), ghosts)
            return (mask, states, valid, win_ops2, active2, jnp.bool_(True),
                    failed, failed_op, overflow, explored, rounds, peak,
                    ghosts2, budget, consumed + 1, cl_iters, fresh2,
                    cur_new)

        def do_return(c):
            (mask, states, valid, win_ops, active, dirty, failed, failed_op,
             overflow, explored, rounds, peak, ghosts, budget, consumed,
             cl_iters, fresh, cur_new) = c

            def with_closure(args):
                (mask, states, valid, cur_new, overflow, rounds, peak,
                 budget, cl_iters) = args
                (mask, states, valid, cur_new, count, overflow, it_fin,
                 converged) = closure(mask, states, valid, win_ops, active,
                                      ghosts, overflow, budget, cl_iters,
                                      fresh, cur_new)
                iters = it_fin - cl_iters
                return (mask, states, valid, cur_new, overflow,
                        rounds + iters, jnp.maximum(peak, count),
                        budget - iters, it_fin, converged, count)

            def no_closure(args):
                (mask, states, valid, cur_new, overflow, rounds, peak,
                 budget, cl_iters) = args
                # Set already closed (no ENTER since the last closure):
                # nothing to add to ``explored`` — count sentinel -1.
                return (mask, states, valid, cur_new, overflow, rounds,
                        peak, budget, cl_iters, jnp.bool_(True),
                        jnp.int32(-1))

            (mask, states, valid, cur_new, overflow, rounds, peak, budget,
             cl_iters, converged, count) = lax.cond(
                dirty, with_closure, no_closure,
                (mask, states, valid, cur_new, overflow, rounds, peak,
                 budget, cl_iters))

            def do_prune(args):
                # Closure reached fixpoint inside the budget: prune configs
                # lacking the returning op and consume the event.
                (mask, states, valid, active, dirty, failed, failed_op,
                 explored, consumed, cl_iters, fresh) = args
                bm = slot_bitmask(slot)
                has = ((mask & bm[None, :]) != 0).any(-1)
                valid2 = valid & has
                n_surv = global_sum(valid2.sum())
                newly_failed = n_surv == 0
                failed_op2 = jnp.where(newly_failed & ~failed, op_id,
                                       failed_op)
                mask2 = mask & ~bm[None, :]
                active2 = active.at[slot].set(False)
                return (mask2, states, valid2, active2, jnp.bool_(False),
                        failed | newly_failed, failed_op2,
                        explored + jnp.maximum(count, 0), consumed + 1,
                        jnp.int32(0), jnp.zeros_like(fresh))

            def do_pause(args):
                # Budget ran out mid-fixpoint: keep the partial (sound,
                # monotone) set, keep dirty, do NOT consume — the host
                # resumes this same RETURN in a fresh dispatch and the
                # closure continues where it left off (cl_iters carries the
                # cumulative iteration count, cur_new the delta frontier).
                return args

            (mask, states, valid, active, dirty, failed, failed_op, explored,
             consumed, cl_iters, fresh) = lax.cond(
                converged, do_prune, do_pause,
                (mask, states, valid, active, dirty, failed, failed_op,
                 explored, consumed, cl_iters, fresh))
            return (mask, states, valid, win_ops, active, dirty, failed,
                    failed_op, overflow, explored, rounds, peak, ghosts,
                    budget, consumed, cl_iters, fresh, cur_new)

        def do_nop(c):
            return c[:14] + (c[14] + 1,) + c[15:]  # consumed += 1

        def apply(c):
            return lax.switch(kind, [do_enter, do_return, do_nop], c)

        new_carry = lax.cond(alive, apply, lambda c: c, carry)
        return new_carry, None

    def event_step_single(carry, ev):
        """Mask-native event step for the vmapped batch engine: no
        cond/switch (vmap executes every branch), exactly ONE closure
        round per step.  ``ev`` is the lane's NEXT unconsumed event
        (gathered by the lane's own ``consumed`` cursor — see
        run_chunk's single-round variant), so lanes never need positional
        alignment: a step either continues a pending return's closure
        (pr_slot/pr_op, carry[18:20]) one round, or applies the next
        event; every step makes real progress for every lane."""
        (mask, states, valid, win_ops, active, dirty, failed, failed_op,
         overflow, explored, rounds, peak, ghosts, budget, consumed,
         cl_iters, fresh, cur_new, pr_slot, pr_op) = carry
        kind, slot = ev[0], ev[1]
        f, a, b, op_id = ev[2], ev[3], ev[4], ev[5]
        is_ghost, gcls, grank, gpos = ev[6], ev[7], ev[8], ev[9]
        alive = ~failed & ~overflow
        stalled = pr_slot >= 0

        # -- Phase A: one closure round for the pending return, or for an
        # incoming RETURN (at most one closure user per step).
        ret_in = alive & ~stalled & (kind == EV_RETURN)
        c_active = (alive & stalled) | ret_in
        c_slot = jnp.where(stalled, pr_slot, slot)
        c_op = jnp.where(stalled, pr_op, op_id)
        work = c_active & dirty
        (mask, states, valid, cur_new, count, overflow, it_fin,
         converged) = closure(mask, states, valid, win_ops, active, ghosts,
                              overflow, jnp.int32(2**30), cl_iters, fresh,
                              cur_new, enable=work)
        rounds = rounds + jnp.where(work, it_fin - cl_iters, 0)
        peak = jnp.maximum(peak, jnp.where(work, count, 0))
        converged = converged | ~dirty
        finish = c_active & converged
        bm = slot_bitmask(c_slot)
        has = ((mask & bm[None, :]) != 0).any(-1)
        valid = jnp.where(finish, valid & has, valid)
        newly_failed = finish & (global_sum(valid.sum()) == 0)
        failed_op = jnp.where(newly_failed & ~failed, c_op, failed_op)
        failed = failed | newly_failed
        mask = jnp.where(finish, mask & ~bm[None, :], mask)
        active = jnp.where(finish, active.at[c_slot].set(False), active)
        explored = explored + jnp.where(finish & work, count, 0)
        fresh = jnp.where(finish, jnp.zeros_like(fresh), fresh)
        cl_iters = jnp.where(finish, 0,
                             jnp.where(c_active, it_fin, cl_iters))
        dirty = dirty & ~finish
        new_stall = c_active & ~converged & ~stalled
        pr_slot = jnp.where(finish, -1, jnp.where(new_stall, slot, pr_slot))
        pr_op = jnp.where(finish, -1, jnp.where(new_stall, op_id, pr_op))

        # -- Phase B: ENTER/NOP apply only when the lane entered the step
        # un-stalled (a pending return's prune must land before an ENTER
        # can reuse its just-freed slot — the ENTER waits a step).
        entering = alive & ~stalled & (kind == EV_ENTER)
        row = jnp.stack([f, a, b, gcls, grank, gpos])
        win_ops = jnp.where(entering, win_ops.at[slot].set(row), win_ops)
        active = jnp.where(entering, active.at[slot].set(True), active)
        fresh = jnp.where(entering, fresh.at[slot].set(True), fresh)
        ghosts = jnp.where(entering & (is_ghost == 1),
                           ghosts | slot_bitmask(slot), ghosts)
        dirty = dirty | entering

        consumed = consumed + jnp.where(
            entering | ret_in | (alive & ~stalled & (kind == EV_NOP)),
            1, 0)
        return (mask, states, valid, win_ops, active, dirty, failed,
                failed_op, overflow, explored, rounds, peak, ghosts,
                budget, consumed, cl_iters, fresh, cur_new, pr_slot,
                pr_op), None

    def _init_win_ops(w):
        # columns: f, a, b, ghost-class (-1 = not a ghost), ghost-rank,
        # compact ghost bit position
        return jnp.zeros((w, 6), jnp.int32).at[:, 3].set(-1)

    def carry0():
        states = jnp.tile(jnp.asarray(model.init_state_array())[None, :], (C, 1))
        return (jnp.zeros((C, MW), jnp.uint32),            # mask
                states,                                    # states
                jnp.arange(C) == 0 if axis_name is None    # valid: one config
                else None,                                 # (set by caller)
                _init_win_ops(W),                          # win_ops
                jnp.zeros(W, dtype=bool),                  # active
                jnp.bool_(False),                          # dirty
                jnp.bool_(False),                          # failed
                jnp.int32(-1),                             # failed_op
                jnp.bool_(False),                          # overflow
                jnp.int32(0),                              # explored
                jnp.int32(0),                              # closure rounds
                jnp.int32(1),                              # peak config count
                jnp.zeros(MW, jnp.uint32),                 # ghost slots
                jnp.int32(work_budget),                    # closure budget
                jnp.int32(0),                              # events consumed
                jnp.int32(0),                              # paused-closure its
                jnp.zeros(W, dtype=bool),                  # fresh slots
                jnp.zeros(C, dtype=bool)) + (              # delta frontier
                (jnp.int32(-1), jnp.int32(-1))             # pending return
                if single_round_closure else ())

    def run_chunk(carry, events):
        # Reset the peak to the live count on entry, and the work budget /
        # consumed-event counter to fresh values (device-side: the host
        # reads all per-chunk scalars without extra round-trips); scan the
        # events; pack the scalars the host polls into ONE int32 vector so
        # a chunk boundary costs a single device→host transfer.  cl_iters /
        # fresh / cur_new (carry[15:]) are NOT reset: they belong to a
        # possibly-paused closure.
        live0 = global_sum(carry[2].sum()).astype(jnp.int32)
        if single_round_closure:
            # ``events`` is the lane's FULL (padded) stream; ``consumed``
            # is the lane's ABSOLUTE cursor (not reset per dispatch) and
            # each of the fixed per-dispatch steps gathers the cursor's
            # event — no slicing, no alignment, no idle steps.
            carry = carry[:11] + (live0, carry[12],
                                  jnp.int32(work_budget)) + carry[14:]
            n_ev = events.shape[0]

            def gather_step(c, _):
                pos = jnp.minimum(c[14], n_ev - 1)
                ev = lax.dynamic_index_in_dim(events, pos, keepdims=False)
                return event_step_single(c, ev)

            carry, _ = lax.scan(gather_step, carry, None,
                                length=steps_per_dispatch)
        else:
            carry = carry[:11] + (live0, carry[12],
                                  jnp.int32(work_budget), jnp.int32(0)) \
                + carry[15:]
            carry, _ = lax.scan(event_step, carry, events)
        stalled = (carry[18] >= 0) if single_round_closure else jnp.int32(0)
        flags = jnp.stack([carry[6].astype(jnp.int32),   # failed
                           carry[8].astype(jnp.int32),   # overflow
                           carry[11],                    # peak configs
                           carry[14],                    # events consumed
                           # pending return still unconverged: the host
                           # MUST keep dispatching even when the cursor
                           # passed the stream (its prune hasn't landed)
                           jnp.asarray(stalled, jnp.int32)])
        return carry, flags

    return carry0, event_step, run_chunk


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------

_SLICE_CACHE: Dict[int, Any] = {}


def _chunk_slicer(chunk: int, axis: int = 0):
    """Jitted device-side slicer (index traced, not baked): one compile per
    (chunk size, axis), zero host->device payload per dispatch.  Static
    python slice bounds would instead compile one slice op per chunk
    *index*."""
    key = (chunk, axis)
    if key not in _SLICE_CACHE:
        _SLICE_CACHE[key] = jax.jit(
            lambda buf, i: lax.dynamic_slice_in_dim(buf, i, chunk, axis))
    return _SLICE_CACHE[key]


def _get_run_chunk(model: JaxModel, window: int, capacity: int,
                   gwords: int = 1):
    # Same-named registry models share step semantics; keying on the name +
    # variant + initial state (not the closure id) lets every get_model()
    # call reuse one compiled engine.  Entries live in the shared bounded
    # engine cache (engine.cache) next to the batched engines — one LRU,
    # one stats endpoint, one eviction policy for every compiled engine in
    # the process; the "singlev" tag keeps single- and batch-mode keys
    # from colliding.
    key = ("singlev", model.name, model.variant, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords, _dedup.N_PROBES, _dedup.WIDE_SORT_ROWS, _dedup.SUBSUME,
           CLOSURE_WORK_BUDGET)
    hit = _ENGINE_CACHE.get(key)
    if hit is not None:
        return hit
    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords)
    # No donation: the overflow-resume path re-uses the chunk-boundary
    # carry snapshot after the call, and the buffers are small anyway.
    from jepsen_tpu.obs.hist import timed_first_call
    run = timed_first_call(
        jax.jit(run_chunk),
        f"compile:singlev:{model.name}:w{window}:c{capacity}")
    return _ENGINE_CACHE.put(key, (carry0, run))


def events_array(p: PreparedHistory, chunk: int) -> np.ndarray:
    """[E_padded, 10] int32 event stream, NOP-padded to a chunk multiple."""
    e = len(p)
    ep = max(chunk, ((e + chunk - 1) // chunk) * chunk)
    ev = np.full((ep, 10), 0, np.int32)
    ev[:, 0] = EV_NOP
    ev[:e, 0] = p.kind
    ev[:e, 1] = p.slot
    ev[:e, 2] = p.f
    ev[:e, 3] = p.a
    ev[:e, 4] = p.b
    ev[:e, 5] = p.op_id
    ev[:e, 6] = p.ghost
    ev[:e, 7] = p.gcls
    ev[:e, 8] = p.grank
    ev[:e, 9] = p.gpos
    return ev


def ghost_words(p: PreparedHistory) -> int:
    """Compact ghost words an engine needs for this history."""
    return max(1, (int(p.n_ghosts) + 31) // 32)


def chosen_gwords(p: PreparedHistory) -> int:
    """Ghost words the driver actually builds the engine with: 0 (the lean,
    subsumption-free engine) when the history's ghost count is small enough
    that the ≤2^ghosts extra configurations are cheaper than the ghost
    machinery's per-merge op chain (see LEAN_GHOST_MAX), else the compact
    word count.  Single source of truth for check(), the bench warm-up, and
    the batch/sharded drivers — warming a different engine shape than the
    timed path dispatches would re-compile inside the timed run."""
    if int(p.n_ghosts) <= LEAN_GHOST_MAX:
        return 0
    return ghost_words(p)


def chunk_for_capacity(capacity: int, base_chunk: int) -> int:
    """Events per dispatch at ``capacity``.

    Round 3 statically shrank the chunk as capacity grew (512*1024
    capacity*events per dispatch) to keep one XLA program inside the TPU
    worker's ~60 s watchdog — and the resulting per-dispatch host polls
    (128-event chunks at capacity 4096, ~80 polls over a tunneled device)
    became the easy-tier bottleneck.  The per-chunk closure work budget
    (closure_budget: iterations scaled down as capacity grows, enforced
    *inside* a single closure's fixpoint loop with mid-event pause/resume)
    now bounds a dispatch's wall-clock tightly at any capacity, so the
    chunk no longer needs to shrink: a capacity escalation keeps the same
    dispatch granularity and the host just resumes mid-chunk whenever the
    engine pauses."""
    return base_chunk


#: Auto-chunk rule (chunk=None): histories unlikely to escalate take the
#: COARSE chunk — fewer chunk-boundary polls over a tunneled device —
#: while escalation-prone ones keep the fine chunk, whose tighter
#: capacity adaptation wins once bursts drive capacity changes (coarser
#: chunks discard more speculative work per change).  Escalation
#: pressure has two measured drivers: ghosts (each pending crashed op
#: can double the config set) and multi-lane state (wider state, bigger
#: spaces).  Measured on hardware, 10k-op histories: register-easy
#: (~3 ghosts, 1 lane) 3.08 s at 1024 vs 3.81 s at 512; register-hard
#: (56 ghosts) 8.7 s at 512 vs 10.3 s at 1024; multi-register (7
#: ghosts but 3 state lanes, escalates to 16384) 36.2 s at 512 vs
#: 40.6 s at 1024.
AUTO_CHUNK_FINE = 512
AUTO_CHUNK_COARSE = 1024
AUTO_CHUNK_GHOST_MAX = 8


def auto_chunk(p: PreparedHistory, model: JaxModel) -> int:
    """Events per dispatch for this history under the auto-chunk rule."""
    return (AUTO_CHUNK_COARSE
            if p.n_ghosts <= AUTO_CHUNK_GHOST_MAX and model.state_size == 1
            else AUTO_CHUNK_FINE)


def check(model: JaxModel, history: Optional[History] = None,
          prepared: Optional[PreparedHistory] = None,
          capacity: int = 1024, max_capacity: int = 65536,
          chunk: Optional[int] = None, max_window: int = 4096,
          explain: bool = True, cancel=None,
          witness_budget: int = WITNESS_BUDGET,
          growth: int = 4) -> Dict[str, Any]:
    """Decide linearizability on device.  Retries with larger configuration
    capacity on overflow; falls back to ``valid: "unknown"`` past
    ``max_capacity``.  On refutation, optionally re-derives a witness on the
    failing prefix with the CPU oracle (cheap: the prefix is exactly what the
    device already searched).

    ``chunk`` trades host polls against capacity adaptivity: per-closure sort
    cost scales with the *static* capacity, so small chunks let the driver
    escalate/relax capacity tightly around crash-bursts (and re-run less on
    overflow), while the lookahead pipeline hides the per-chunk flag
    transfer.  512 measured ~2x faster than 256 end-to-end on a tunneled
    TPU (chunk-boundary polls dominate there) with an *identical* capacity
    trajectory on the crash-burst benchmark — same configs explored, same
    peak.  ``chunk=None`` (the default) picks per history: coarse 1024 for
    ghost-light streams, fine 512 for ghost-heavy ones (see
    :func:`auto_chunk` for the measured rationale).  Pass chunk=256
    explicitly on directly-attached devices if adaptation matters more
    than polls.  Pure-throughput batch checking with no mid-stream
    adaptation (check_batch) uses its own batch-scaled chunks.

    ``cancel`` is an optional :class:`threading.Event` polled at chunk
    boundaries; when a competing solver already produced a definite verdict
    the driver stops dispatching and returns ``valid: "unknown"`` with
    ``cancelled: True`` (knossos.competition loser cancellation)."""
    p = prepared if prepared is not None else prepare(
        history, model, max_window=max_window)
    if chunk is None:
        chunk = auto_chunk(p, model)
    window = _round_window(p.window)
    # Pad the event stream to a chunk multiple PLUS one chunk-sized NOP
    # cushion: progress is tracked in *event* units, and the cushion
    # guarantees any in-bounds dispatch offset
    # can slice a full chunk without clamping back into (and re-applying!)
    # real events.  Trailing NOPs are inert.  Small-chunk callers keep
    # their small streams — padding to a fixed 512 would multiply
    # dispatches on short histories, and per-dispatch host polls are the
    # dominant cost on tunneled devices.
    base = chunk
    ev = events_array(p, base)
    n_events = ev.shape[0]
    ev = np.concatenate([ev, ev[:1].repeat(base, axis=0) * 0])
    ev[n_events:, 0] = EV_NOP
    # One host->device transfer for the whole stream; per-chunk slices then
    # happen device-side.  A per-chunk jnp.asarray would be a blocking
    # ~12 KB RPC per dispatch — on a tunneled device that synchronous
    # transfer, not compute, dominated the easy-history wall-clock.
    ev_dev = jnp.asarray(ev)

    gw = chosen_gwords(p)
    cap = capacity
    max_cap_reached = cap  # diagnostics: how far escalation actually went
    # The chunk is capacity-INVARIANT (see chunk_for_capacity): capacity
    # changes rebuild the engine but keep the dispatch granularity, and
    # watchdog bounding comes from the closure work budget + mid-chunk
    # resume, not from shrinking chunks.
    cur_chunk = chunk_for_capacity(cap, chunk)
    slice_chunk = _chunk_slicer(cur_chunk)
    carry0, run_chunk = _get_run_chunk(model, window, cap, gw)
    carry = carry0()
    # (peak, events-consumed) samples since the last capacity change.  With
    # budget pauses a dispatch can cover anywhere from 0 to cur_chunk
    # events, so shrink-back decisions weigh samples by the events they
    # cover (>= SHRINK_WINDOW events of evidence), not by dispatch count.
    SHRINK_WINDOW = 4 * cur_chunk
    recent_peaks: deque = deque()
    # Pipelined dispatch: keep LOOKAHEAD chunks in flight so the (possibly
    # slow, e.g. tunneled) device→host flags transfer of chunk i overlaps
    # with the device computing chunk i+1.  Speculation is safe: once the
    # failed/overflow lane is set, event_step gates all updates, so
    # speculative chunks past a failure compute nothing wrong — they are
    # simply discarded on resume.
    inflight: deque = deque()  # (pos, carry_before, carry_after, flags)
    pos = 0
    trace = bool(_os.environ.get("JTPU_TRACE"))
    t_last = mono_now() if trace else 0.0
    # n_events >= 512 always, so the loop pops at least once and failed/
    # overflow/carry are always (re)assigned before use below.
    while True:
        # Poll cancellation before refilling the pipeline, so a lost race
        # doesn't dispatch up to LOOKAHEAD more chunks of discarded work.
        if cancel is not None and cancel.is_set():
            return {"valid": "unknown", "analyzer": "wgl-tpu",
                    "cancelled": True}
        while len(inflight) < LOOKAHEAD and pos < n_events:
            prev = carry
            carry, flags = run_chunk(carry, slice_chunk(ev_dev, pos))
            inflight.append((pos, prev, carry, flags))
            pos += cur_chunk
        if not inflight:
            break
        cpos, prev, after, flags = inflight.popleft()
        fl = np.asarray(flags)
        failed, overflow = bool(fl[0]), bool(fl[1])
        peak = int(fl[2])
        consumed = int(fl[3])
        if trace:
            now = mono_now()
            print(f"[wgl] pos={cpos} cap={cap} peak={peak} "
                  f"consumed={consumed}/{cur_chunk} ovf={int(overflow)} "
                  f"dt={now - t_last:.3f}", file=_sys.stderr, flush=True)
            t_last = now
        if overflow and cap < max_capacity:
            # Grow straight to a capacity the observed peak says is enough
            # (peak is a lower bound on the true need — it may itself have
            # been clipped — so the loop can escalate again) and resume from
            # the snapshot: no restart, no re-search of the prefix.
            while cap < max_capacity and cap < 2 * peak:
                cap = min(cap * growth, max_capacity)
            max_cap_reached = max(max_cap_reached, cap)
            recent_peaks.clear()
            inflight.clear()
            _, run_chunk = _get_run_chunk(model, window, cap, gw)
            carry = _grow_carry(prev, cap)
            pos = cpos
            overflow = False
            continue
        done = after
        if failed or overflow:
            break
        recent_peaks.append((peak, consumed))
        covered = sum(e for _, e in recent_peaks)
        while len(recent_peaks) > 1 and covered - recent_peaks[0][1] >= \
                SHRINK_WINDOW:
            covered -= recent_peaks.popleft()[1]
        resumed = consumed < cur_chunk
        if cap > capacity and covered >= SHRINK_WINDOW:
            # Crash-bursts inflate the configuration set transiently.  The
            # per-round sort cost scales with the *static* capacity, so once
            # recent peaks show a smaller buffer suffices (2x headroom over
            # the last SHRINK_WINDOW events' high-water mark), drop back to
            # a cheaper-per-round engine (discarding speculative chunks).
            need = 2 * max(pk for pk, _ in recent_peaks)
            target = cap
            while target > capacity and target // growth >= need:
                target //= growth
            # an escalation clamped to max_capacity can sit off the
            # power-of-4 lattice; never shrink below the configured floor
            target = max(target, capacity)
            if target < cap:
                cap = target
                recent_peaks.clear()
                inflight.clear()
                _, run_chunk = _get_run_chunk(model, window, cap, gw)
                carry = _shrink_carry(after, cap)
                pos = cpos + consumed
                continue
        if resumed:
            # Closure budget exhausted mid-chunk: the unconsumed tail was
            # gated to no-ops, and any speculative chunks skipped it —
            # discard them and resume exactly where the engine stopped.
            # (Keeps one XLA program's wall time bounded by work, under
            # the TPU worker's watchdog, regardless of config-count
            # superlinearity.)
            inflight.clear()
            carry = after
            pos = cpos + consumed
    carry = done

    explored = int(carry[9])
    if overflow:
        # ``explored`` only accumulates at converged RETURN prunes; a
        # history that overflows before any return prunes (the ceiling
        # shape: one giant ghost-burst closure) would report 0 even though
        # the engine explored a full frontier per closure round.  Count the
        # in-progress (clipped) frontier — its high-water mark — as
        # explored work so the overflow artifact shows what the engine did
        # before degrading.
        # "capacity-exceeded" is the structured form of the error string:
        # the fission layer keys its split-don't-escalate decision on it
        # instead of parsing the message.
        return {"valid": "unknown", "analyzer": "wgl-tpu",
                "error": f"configuration capacity exceeded at {cap}",
                "capacity-exceeded": True,
                "configs-explored": explored + int(carry[11]),
                "closure-rounds": int(carry[10]),
                "max-capacity-reached": max_cap_reached}
    if not failed:
        return {"valid": True, "analyzer": "wgl-tpu",
                "configs-explored": explored,
                "closure-rounds": int(carry[10]),
                "window": p.window, "capacity": cap,
                "max-capacity-reached": max_cap_reached}
    failed_op = p.ops[int(carry[7])]
    # witness: device frontier emptied on a RETURN; refuting op attached
    res: Dict[str, Any] = {"valid": False, "analyzer": "wgl-tpu",
                           "op": failed_op.to_dict(),
                           "configs-explored": explored,
                           "window": p.window, "capacity": cap,
                           "max-capacity-reached": max_cap_reached}
    if explain and history is not None and model.cpu_model is not None:
        res["witness"] = _cpu_witness(model, history, failed_op,
                                      witness_budget)
    return res


def _grow_carry(carry, new_capacity: int):
    """Pad the configuration buffers (mask, states, valid, cur_new) of a
    chunk-boundary carry up to a larger capacity; other elements carry over.
    Gaps are fine — the engine tracks liveness with the valid flags."""
    mask, states, valid, cur_new = carry[0], carry[1], carry[2], carry[17]
    c = mask.shape[0]
    extra = new_capacity - c
    mask2 = jnp.concatenate([mask, jnp.zeros((extra,) + mask.shape[1:],
                                             mask.dtype)])
    states2 = jnp.concatenate([states, jnp.zeros((extra,) + states.shape[1:],
                                                 states.dtype)])
    valid2 = jnp.concatenate([valid, jnp.zeros(extra, valid.dtype)])
    cur_new2 = jnp.concatenate([cur_new, jnp.zeros(extra, cur_new.dtype)])
    return (mask2, states2, valid2) + tuple(carry[3:17]) + (cur_new2,)


def _shrink_carry(carry, new_capacity: int):
    """Compact live configurations into a smaller buffer (host-side; the
    arrays are KBs).  Only called when they provably fit."""
    mask = np.asarray(carry[0])
    states = np.asarray(carry[1])
    valid = np.asarray(carry[2])
    cur_new = np.asarray(carry[17])
    idx = np.flatnonzero(valid)[:new_capacity]
    mask2 = np.zeros((new_capacity,) + mask.shape[1:], mask.dtype)
    states2 = np.zeros((new_capacity,) + states.shape[1:], states.dtype)
    valid2 = np.zeros(new_capacity, bool)
    cur_new2 = np.zeros(new_capacity, bool)
    mask2[:len(idx)] = mask[idx]
    states2[:len(idx)] = states[idx]
    valid2[:len(idx)] = True
    cur_new2[:len(idx)] = cur_new[idx]
    return (jnp.asarray(mask2), jnp.asarray(states2),
            jnp.asarray(valid2)) + tuple(carry[3:17]) \
        + (jnp.asarray(cur_new2),)


# _cpu_witness / WITNESS_BUDGET / _round_window moved to the shared
# engine substrate (engine.witness, engine.ladder); imported above under
# their historical names for this module's callers.
