"""Device-tier linearizability engine — the point of this framework.

Replaces the reference's external knossos solver (invoked at
jepsen/src/jepsen/checker.clj:185-216) with a JAX search that runs entirely in
fixed-shape device buffers:

- A configuration is (pending-window bitmask, model state): uint32[MW] mask
  lanes + int32[S] state lanes (see prep.py for why that compression is
  complete).  The engine holds up to ``capacity`` configurations.
- The history is a stream of ENTER/RETURN events consumed by ``lax.scan`` in
  chunks; the host polls failure/overflow flags between chunks (early exit),
  so a refuted history stops in O(prefix).
- At a RETURN event the engine expands the configuration closure: a nested
  vmap applies the model step to every (configuration × pending op) pair —
  [C, W] parallel model steps per round — then the union is deduplicated and
  compacted by a multi-key sort (ops/dedup.py).  Closure repeats to fixpoint
  (count-stable), then configurations lacking the returning op are pruned.
- Closure is skipped when the set is already closed: pruning on a bit
  preserves closedness (expansions of a surviving configuration also carried
  the bit), so closure is only needed after new ENTERs — the ``dirty`` flag.

Single-history frontier sharding across a device mesh lives in
jepsen_tpu.parallel; this module is mesh-agnostic but takes an optional
``axis_name`` so the closure can all_gather candidate rows and keep a
device-local slice of the deduplicated global set.
"""

from __future__ import annotations

import math
from collections import deque
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu.checker.prep import (
    EV_ENTER, EV_RETURN, PreparedHistory, WindowOverflow, prepare,
)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel
from jepsen_tpu.ops.dedup import sort_dedup_compact

EV_NOP = 2

# Chunks dispatched ahead of the host's flag poll, so the device→host flags
# transfer of chunk i overlaps with the device computing chunk i+1.
LOOKAHEAD = 2

# carry = (mask, states, valid, win_ops, active, dirty, failed, failed_op,
#          overflow, explored, rounds, peak)
# peak is the high-water mark of the distinct-configuration count since the
# driver last reset it: the capacity the search *actually* needed, which the
# host reads at chunk boundaries to pick the cheapest sufficient engine.


def make_engine(model: JaxModel, window: int, capacity: int,
                axis_name: Optional[str] = None, num_shards: int = 1):
    """Build the jittable (carry0, event_step, run_chunk) triple.

    ``window`` may be any positive slot count (candidate-row count — and so
    closure sort cost — scales with it, so callers pass the tightest window
    the history needs).  With ``axis_name``, buffers are device-local shards
    of a global set of ``capacity * num_shards`` configurations and closure
    dedup synchronizes via all_gather.
    """
    assert window > 0
    try:
        # All three engine paths (single-chip, sharded, batched) build here;
        # enabling the persistent compilation cache at this shared layer
        # turns repeat compiles of any engine shape into disk loads.
        # Best-effort: a read-only fs must not break checking.
        from jepsen_tpu.ops.cache import enable_compilation_cache
        enable_compilation_cache()
    except Exception:  # noqa: BLE001
        pass
    W, MW, S, C = window, (window + 31) // 32, model.state_size, capacity
    step = model.step

    # slot_masks[w] = uint32[MW] with bit w set.
    sm = np.zeros((W, MW), np.uint32)
    for w in range(W):
        sm[w, w // 32] = np.uint32(1) << np.uint32(w % 32)
    slot_masks = jnp.asarray(sm)

    def slot_bitmask(slot):
        word = slot // 32
        bit = jnp.left_shift(jnp.uint32(1), (slot % 32).astype(jnp.uint32))
        return jnp.where(jnp.arange(MW) == word, bit, jnp.uint32(0))

    def expand(states, win_ops):
        def per_config(st):
            def per_slot(op):
                ns, ok = step(st, op[0], op[1], op[2])
                return ns.astype(jnp.int32), ok
            return jax.vmap(per_slot)(win_ops)
        return jax.vmap(per_config)(states)  # [C, W, S], [C, W]

    def global_sum(x):
        return lax.psum(x, axis_name) if axis_name else x

    def closure(mask, states, valid, win_ops, active, overflow):
        count0 = global_sum(valid.sum())

        def cond(c):
            _, _, _, _, changed, ovf, it = c
            return changed & ~ovf & (it < W + 1)

        def body(c):
            mask, states, valid, count, _, ovf, it = c
            cand_states, ok = expand(states, win_ops)
            has = ((mask[:, None, :] & slot_masks[None, :, :]) != 0).any(-1)
            cand_valid = valid[:, None] & active[None, :] & ~has & ok
            cand_mask = mask[:, None, :] | slot_masks[None, :, :]

            all_mask = jnp.concatenate([mask, cand_mask.reshape(C * W, MW)])
            all_states = jnp.concatenate([states, cand_states.reshape(C * W, S)])
            all_valid = jnp.concatenate([valid, cand_valid.reshape(C * W)])
            if axis_name is not None:
                all_mask = lax.all_gather(all_mask, axis_name, tiled=True)
                all_states = lax.all_gather(all_states, axis_name, tiled=True)
                all_valid = lax.all_gather(all_valid, axis_name, tiled=True)
            cols = ([all_mask[:, i] for i in range(MW)]
                    + [all_states[:, i] for i in range(S)])
            gcap = C * num_shards
            out_cols, out_valid, total, ovf2 = sort_dedup_compact(
                cols, all_valid, gcap)
            new_mask = jnp.stack(out_cols[:MW], -1)
            new_states = jnp.stack(out_cols[MW:], -1)
            if axis_name is not None:
                start = lax.axis_index(axis_name) * C
                new_mask = lax.dynamic_slice_in_dim(new_mask, start, C)
                new_states = lax.dynamic_slice_in_dim(new_states, start, C)
                out_valid = lax.dynamic_slice_in_dim(out_valid, start, C)
            changed = total > count
            return (new_mask, new_states, out_valid, total, changed,
                    ovf | ovf2, it + 1)

        init = (mask, states, valid, count0, jnp.bool_(True), overflow,
                jnp.int32(0))
        mask, states, valid, count, _, overflow, iters = lax.while_loop(
            cond, body, init)
        return mask, states, valid, count, overflow, iters

    def event_step(carry, ev):
        (mask, states, valid, win_ops, active, dirty, failed, failed_op,
         overflow, explored, rounds, peak) = carry
        kind, slot, f, a, b, op_id = (ev[0], ev[1], ev[2], ev[3], ev[4], ev[5])
        alive = ~failed & ~overflow

        def do_enter(c):
            (mask, states, valid, win_ops, active, dirty, failed, failed_op,
             overflow, explored, rounds, peak) = c
            win_ops2 = win_ops.at[slot].set(jnp.stack([f, a, b]))
            active2 = active.at[slot].set(True)
            return (mask, states, valid, win_ops2, active2, jnp.bool_(True),
                    failed, failed_op, overflow, explored, rounds, peak)

        def do_return(c):
            (mask, states, valid, win_ops, active, dirty, failed, failed_op,
             overflow, explored, rounds, peak) = c

            def with_closure(args):
                mask, states, valid, overflow, explored, rounds, peak = args
                mask, states, valid, count, overflow, iters = closure(
                    mask, states, valid, win_ops, active, overflow)
                return (mask, states, valid, overflow, explored + count,
                        rounds + iters, jnp.maximum(peak, count))

            mask, states, valid, overflow, explored, rounds, peak = lax.cond(
                dirty, with_closure, lambda a: a,
                (mask, states, valid, overflow, explored, rounds, peak))

            bm = slot_bitmask(slot)
            has = ((mask & bm[None, :]) != 0).any(-1)
            valid2 = valid & has
            n_surv = global_sum(valid2.sum())
            newly_failed = n_surv == 0
            failed_op2 = jnp.where(newly_failed & ~failed, op_id, failed_op)
            mask2 = mask & ~bm[None, :]
            active2 = active.at[slot].set(False)
            return (mask2, states, valid2, win_ops, active2, jnp.bool_(False),
                    failed | newly_failed, failed_op2, overflow, explored,
                    rounds, peak)

        new_carry = lax.cond(
            alive,
            lambda c: lax.switch(kind, [do_enter, do_return, lambda x: x], c),
            lambda c: c, carry)
        return new_carry, None

    def carry0():
        states = jnp.tile(jnp.asarray(model.init_state_array())[None, :], (C, 1))
        return (jnp.zeros((C, MW), jnp.uint32),            # mask
                states,                                    # states
                jnp.arange(C) == 0 if axis_name is None    # valid: one config
                else None,                                 # (set by caller)
                jnp.zeros((W, 3), jnp.int32),              # win_ops
                jnp.zeros(W, dtype=bool),                  # active
                jnp.bool_(False),                          # dirty
                jnp.bool_(False),                          # failed
                jnp.int32(-1),                             # failed_op
                jnp.bool_(False),                          # overflow
                jnp.int32(0),                              # explored
                jnp.int32(0),                              # closure rounds
                jnp.int32(1))                              # peak config count

    def run_chunk(carry, events):
        # Reset the peak to the live count on entry (device-side: the host
        # reads per-chunk peaks without extra round-trips), scan the events,
        # and pack the scalars the host polls into ONE int32 vector so a
        # chunk boundary costs a single device→host transfer.
        live0 = global_sum(carry[2].sum()).astype(jnp.int32)
        carry = carry[:11] + (live0,)
        carry, _ = lax.scan(event_step, carry, events)
        flags = jnp.stack([carry[6].astype(jnp.int32),   # failed
                           carry[8].astype(jnp.int32),   # overflow
                           carry[11]])                   # peak configs
        return carry, flags

    return carry0, event_step, run_chunk


# ---------------------------------------------------------------------------
# Host driver
# ---------------------------------------------------------------------------

_ENGINE_CACHE: Dict[Tuple, Any] = {}


def _get_run_chunk(model: JaxModel, window: int, capacity: int):
    # Same-named registry models share step semantics; keying on the name +
    # initial state (not the closure id) lets every get_model() call reuse
    # one compiled engine.
    key = (model.name, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity)
    if key not in _ENGINE_CACHE:
        carry0, _, run_chunk = make_engine(model, window, capacity)
        # No donation: the overflow-resume path re-uses the chunk-boundary
        # carry snapshot after the call, and the buffers are small anyway.
        _ENGINE_CACHE[key] = (carry0, jax.jit(run_chunk))
    return _ENGINE_CACHE[key]


def events_array(p: PreparedHistory, chunk: int) -> np.ndarray:
    """[E_padded, 6] int32 event stream, NOP-padded to a chunk multiple."""
    e = len(p)
    ep = max(chunk, ((e + chunk - 1) // chunk) * chunk)
    ev = np.full((ep, 6), 0, np.int32)
    ev[:, 0] = EV_NOP
    ev[:e, 0] = p.kind
    ev[:e, 1] = p.slot
    ev[:e, 2] = p.f
    ev[:e, 3] = p.a
    ev[:e, 4] = p.b
    ev[:e, 5] = p.op_id
    return ev


#: Configuration budget for the CPU witness re-derivation on refuted
#: histories (knossos-style final-paths cost cap; checker.clj:213-216
#: truncates for the same reason).  Exceeding it degrades the result to
#: ``witness: {"error": ...}`` — the refutation verdict itself stands.
WITNESS_BUDGET = 200_000


def check(model: JaxModel, history: Optional[History] = None,
          prepared: Optional[PreparedHistory] = None,
          capacity: int = 1024, max_capacity: int = 65536,
          chunk: int = 512, max_window: int = 4096,
          explain: bool = True, cancel=None,
          witness_budget: int = WITNESS_BUDGET) -> Dict[str, Any]:
    """Decide linearizability on device.  Retries with larger configuration
    capacity on overflow; falls back to ``valid: "unknown"`` past
    ``max_capacity``.  On refutation, optionally re-derives a witness on the
    failing prefix with the CPU oracle (cheap: the prefix is exactly what the
    device already searched).

    ``chunk`` trades host polls against capacity adaptivity: per-closure sort
    cost scales with the *static* capacity, so small chunks let the driver
    escalate/relax capacity tightly around crash-bursts (and re-run less on
    overflow), while the lookahead pipeline hides the per-chunk flag
    transfer.  512 measured ~2x faster than 256 end-to-end on a tunneled
    TPU (chunk-boundary polls dominate there) with an *identical* capacity
    trajectory on the crash-burst benchmark — same configs explored, same
    peak — so the coarser adaptation is theoretical on these workloads;
    pass chunk=256 explicitly on directly-attached devices if adaptation
    matters more than polls.  Pure-throughput batch checking with no
    mid-stream adaptation (check_batch) uses larger chunks.

    ``cancel`` is an optional :class:`threading.Event` polled at chunk
    boundaries; when a competing solver already produced a definite verdict
    the driver stops dispatching and returns ``valid: "unknown"`` with
    ``cancelled: True`` (knossos.competition loser cancellation)."""
    p = prepared if prepared is not None else prepare(
        history, model, max_window=max_window)
    window = _round_window(p.window)
    ev = events_array(p, chunk)
    n_chunks = ev.shape[0] // chunk

    cap = capacity
    max_cap_reached = cap  # diagnostics: how far escalation actually went
    carry0, run_chunk = _get_run_chunk(model, window, cap)
    carry = carry0()
    recent_peaks: deque = deque(maxlen=4)  # per-chunk high-water marks
    # Pipelined dispatch: keep LOOKAHEAD chunks in flight so the (possibly
    # slow, e.g. tunneled) device→host flags transfer of chunk i overlaps
    # with the device computing chunk i+1.  Speculation is safe: once the
    # failed/overflow lane is set, event_step gates all updates, so
    # speculative chunks past a failure compute nothing wrong — they are
    # simply discarded on resume.
    inflight: deque = deque()  # (ci, carry_before, carry_after, flags)
    next_ci = 0
    # n_chunks >= 1 always (events_array pads to a chunk multiple of at
    # least one chunk), so the loop pops at least once and failed/overflow/
    # carry are always (re)assigned before use below.
    while True:
        # Poll cancellation before refilling the pipeline, so a lost race
        # doesn't dispatch up to LOOKAHEAD more chunks of discarded work.
        if cancel is not None and cancel.is_set():
            return {"valid": "unknown", "analyzer": "wgl-tpu",
                    "cancelled": True}
        while len(inflight) < LOOKAHEAD and next_ci < n_chunks:
            prev = carry
            carry, flags = run_chunk(
                carry, jnp.asarray(ev[next_ci * chunk:(next_ci + 1) * chunk]))
            inflight.append((next_ci, prev, carry, flags))
            next_ci += 1
        if not inflight:
            break
        ci, prev, after, flags = inflight.popleft()
        fl = np.asarray(flags)
        failed, overflow = bool(fl[0]), bool(fl[1])
        peak = int(fl[2])
        if overflow and cap < max_capacity:
            # Grow straight to a capacity the observed peak says is enough
            # (peak is a lower bound on the true need — it may itself have
            # been clipped — so the loop can escalate again) and resume from
            # the snapshot: no restart, no re-search of the prefix.
            while cap < max_capacity and cap < 2 * peak:
                cap = min(cap * 4, max_capacity)
            max_cap_reached = max(max_cap_reached, cap)
            recent_peaks.clear()
            inflight.clear()
            _, run_chunk = _get_run_chunk(model, window, cap)
            carry = _grow_carry(prev, cap)
            next_ci = ci
            overflow = False
            continue
        done = after
        if failed or overflow:
            break
        recent_peaks.append(peak)
        if cap > capacity and len(recent_peaks) == 4:
            # Crash-bursts inflate the configuration set transiently.  The
            # per-round sort cost scales with the *static* capacity, so once
            # recent peaks show a smaller buffer suffices (2x headroom over
            # the last 4 chunks' high-water mark), drop back to a
            # cheaper-per-round engine (discarding speculative chunks).
            need = 2 * max(recent_peaks)
            target = cap
            while target > capacity and target // 4 >= need:
                target //= 4
            # an escalation clamped to max_capacity can sit off the
            # power-of-4 lattice; never shrink below the configured floor
            target = max(target, capacity)
            if target < cap:
                cap = target
                recent_peaks.clear()
                inflight.clear()
                _, run_chunk = _get_run_chunk(model, window, cap)
                carry = _shrink_carry(after, cap)
                next_ci = ci + 1
    carry = done

    explored = int(carry[9])
    if overflow:
        return {"valid": "unknown", "analyzer": "wgl-tpu",
                "error": f"configuration capacity exceeded at {cap}",
                "configs-explored": explored}
    if not failed:
        return {"valid": True, "analyzer": "wgl-tpu",
                "configs-explored": explored,
                "closure-rounds": int(carry[10]),
                "window": p.window, "capacity": cap,
                "max-capacity-reached": max_cap_reached}
    failed_op = p.ops[int(carry[7])]
    res: Dict[str, Any] = {"valid": False, "analyzer": "wgl-tpu",
                           "op": failed_op.to_dict(),
                           "configs-explored": explored,
                           "window": p.window, "capacity": cap,
                           "max-capacity-reached": max_cap_reached}
    if explain and history is not None and model.cpu_model is not None:
        res["witness"] = _cpu_witness(model, history, failed_op,
                                      witness_budget)
    return res


def _round_window(w: int) -> int:
    """Tightest engine window for a history: multiple of 4, >= 8."""
    return max(8, ((w + 3) // 4) * 4)


def _grow_carry(carry, new_capacity: int):
    """Pad the configuration buffers (mask, states, valid) of a
    chunk-boundary carry up to a larger capacity; other elements carry over.
    Gaps are fine — the engine tracks liveness with the valid flags."""
    mask, states, valid = carry[0], carry[1], carry[2]
    c = mask.shape[0]
    extra = new_capacity - c
    mask2 = jnp.concatenate([mask, jnp.zeros((extra,) + mask.shape[1:],
                                             mask.dtype)])
    states2 = jnp.concatenate([states, jnp.zeros((extra,) + states.shape[1:],
                                                 states.dtype)])
    valid2 = jnp.concatenate([valid, jnp.zeros(extra, valid.dtype)])
    return (mask2, states2, valid2) + tuple(carry[3:])


def _shrink_carry(carry, new_capacity: int):
    """Compact live configurations into a smaller buffer (host-side; the
    arrays are KBs).  Only called when they provably fit."""
    mask = np.asarray(carry[0])
    states = np.asarray(carry[1])
    valid = np.asarray(carry[2])
    idx = np.flatnonzero(valid)[:new_capacity]
    mask2 = np.zeros((new_capacity,) + mask.shape[1:], mask.dtype)
    states2 = np.zeros((new_capacity,) + states.shape[1:], states.dtype)
    valid2 = np.zeros(new_capacity, bool)
    mask2[:len(idx)] = mask[idx]
    states2[:len(idx)] = states[idx]
    valid2[:len(idx)] = True
    return (jnp.asarray(mask2), jnp.asarray(states2),
            jnp.asarray(valid2)) + tuple(carry[3:])


def _cpu_witness(model: JaxModel, history: History, failed_op,
                 budget: int = WITNESS_BUDGET) -> Dict[str, Any]:
    """Re-run the CPU oracle on the prefix ending at the failing op's
    completion for a knossos-style final-configs report."""
    from jepsen_tpu.checker import wgl_cpu
    h = history.client_ops().complete()
    pairs = h.pair_index()
    cut = None
    for i, op in enumerate(h):
        if op.index == failed_op.index:
            cut = int(pairs[i]) if pairs[i] >= 0 else i
            break
    if cut is None:
        return {"error": "failing op not found in history"}
    prefix = History(h.ops[:cut + 1])
    try:
        return wgl_cpu.check(model.cpu_model(), prefix, max_configs=budget)
    except wgl_cpu.SearchExploded:
        return {"error": "witness search exceeded budget"}
