"""History preprocessing for linearizability engines.

Turns a raw history into the event stream both engines (CPU oracle and TPU
search) consume, and computes the *pending-window* slot assignment that is the
core compression behind the device representation:

    In the configuration-BFS view of linearizability checking (Wing & Gong's
    search, as refined by Lowe's just-in-time linearization), a configuration
    is (set of linearized ops, model state).  But every op whose completion
    event has been processed MUST be linearized in every surviving
    configuration, and ops not yet invoked CANNOT be — so configurations can
    only disagree about ops that are *currently pending*.  A configuration
    therefore compresses to (bitmask over pending-window slots, model state):
    a handful of int32 lanes, fixed-shape, perfect for vmapped expansion on
    device.  (See PAPERS.md: P-compositionality's just-in-time linearization;
    knossos's configurations play the same role on the JVM.)

Rules applied here (knossos parity):
  - only client ops participate (nemesis ops are stripped);
  - ``fail`` ops never took effect — invoke+fail pairs are removed outright;
  - ``info`` ops may take effect at any time from invocation on — they enter
    the window and never leave (crashed ops, reference behavior at
    jepsen/src/jepsen/generator/interpreter.clj:142-157);
  - ``info`` pure-read ops with unknown values are dropped (unconstraining);
  - ``ok`` ops produce an ENTER event at their invocation index and a RETURN
    event at their completion index.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Set, Tuple

import numpy as np

from jepsen_tpu.history import History, INFO, INVOKE, OK, FAIL, Op
from jepsen_tpu.models.base import JaxModel, UNKNOWN32

EV_ENTER = 0   # op joins the pending window (its invocation)
EV_RETURN = 1  # op's ok-completion: must be linearized in every config


@dataclass
class PreparedHistory:
    """Event-stream view of a history, ready for either engine."""

    # Per-event columns (length E):
    kind: np.ndarray        # int32, EV_ENTER / EV_RETURN
    slot: np.ndarray        # int32, pending-window slot of the event's op
    f: np.ndarray           # int32, model op code (0 if no encoder given)
    a: np.ndarray           # int32 operand
    b: np.ndarray           # int32 operand
    op_id: np.ndarray       # int32, index into ``ops`` (invocation order)
    ghost: np.ndarray       # int32 0/1: ENTER of an op that never returns
                            # (info/crashed) — enables ghost-bit subsumption
    gcls: np.ndarray        # int32: ghost equivalence class (slot of the
                            # first ghost with the same (f,a,b) encoding);
                            # -1 for non-ghost events.  Same-encoding ghosts
                            # are interchangeable, so engines canonicalize
                            # a config's ghost bits to per-class counts.
    grank: np.ndarray       # int32: this ghost's index within its class
    gpos: np.ndarray        # int32: compact ghost bit position, grouped by
                            # class (class offset + rank) — ghost state
                            # packs into ceil(n_ghosts/32) sort words
                            # instead of ceil(window/32)
    # Scalars / host-side:
    window: int             # number of slots ever needed (max concurrency)
    ops: List[Op]           # participating ops, invocation order
    crashed_slots: Tuple[int, ...]  # slots held forever by info ops
    n_ghosts: int = 0       # total crashed ops (= compact ghost bits)

    @property
    def n_ops(self) -> int:
        return len(self.ops)

    def __len__(self):
        return len(self.kind)


class WindowOverflow(Exception):
    """History's pending-op concurrency exceeds the engine's window size."""


def prepare(history: History,
            model: Optional[JaxModel] = None,
            max_window: Optional[int] = None,
            pure_read_names: Sequence[str] = ("read", "r"),
            ) -> PreparedHistory:
    """Build the event stream.  With a :class:`JaxModel`, ops are encoded into
    the int32 (f, a, b) columns and the model's ``pure_read_fs`` drive
    crashed-read elimination; without one (host-tier engines), columns are
    zero and ``pure_read_names`` + a None value identify droppable reads."""
    h = history.client_ops().complete()
    pairs = h.pair_index()

    events: List[Tuple[int, ...]] = []
    ops: List[Op] = []
    free: List[int] = []
    next_slot = 0
    slot_of: dict = {}      # history position of invoke -> slot
    opid_of: dict = {}      # history position of invoke -> op_id
    crashed: List[int] = []
    gclasses: dict = {}     # (f, a, b) -> [ghost slots, in enter order]
    pure_fs: Set[int] = set(model.pure_read_fs) if model else set()

    def alloc_slot() -> int:
        nonlocal next_slot
        if free:
            return free.pop()
        s = next_slot
        next_slot += 1
        return s

    for i, op in enumerate(h):
        if op.type == INVOKE:
            j = pairs[i]
            comp = h[j] if j >= 0 else None
            ctype = comp.type if comp is not None else INFO
            if ctype == FAIL:
                continue  # never took effect
            if model is not None:
                f, a, b = model.encode_op(op)
                if ctype == INFO and f in pure_fs and a == UNKNOWN32:
                    continue  # crashed read, unknown value: unconstraining
            else:
                f = a = b = 0
                if ctype == INFO and op.f in pure_read_names and op.value is None:
                    continue
            s = alloc_slot()
            slot_of[i] = s
            opid_of[i] = len(ops)
            if ctype == INFO:
                # Class key: the op's semantics.  With a model, the int32
                # encoding; without (host tier), the raw (f, value) — the
                # all-zero placeholder encodings must not merge classes.
                key = (f, a, b) if model is not None else (op.f,
                                                          repr(op.value))
                members = gclasses.setdefault(key, [])
                cls, rank = (members[0] if members else s), len(members)
                members.append(s)
                # gpos (col 9) is a placeholder here; class-grouped compact
                # positions are assigned once all class sizes are known.
                events.append((EV_ENTER, s, f, a, b, len(ops), 1, cls, rank,
                               0))
                crashed.append(s)
            else:
                events.append((EV_ENTER, s, f, a, b, len(ops), 0, -1, 0, 0))
            ops.append(op)
        elif op.type == OK:
            j = pairs[i]
            if j in slot_of:
                s = slot_of[j]
                events.append((EV_RETURN, s, 0, 0, 0, opid_of[j], 0, -1, 0,
                               0))
                free.append(s)
        # FAIL completions: pair already skipped. INFO completions: op stays.

    if max_window is not None and next_slot > max_window:
        raise WindowOverflow(
            f"history needs {next_slot} pending-window slots "
            f"(> max {max_window}); raise max_window or shard the history")

    # Compact ghost positions: classes get contiguous ranges in discovery
    # order, each ghost at (class offset + rank).
    offsets: dict = {}
    off = 0
    for key, members in gclasses.items():
        offsets[key] = off
        off += len(members)
    class_off = {members[0]: offsets[key]
                 for key, members in gclasses.items()}
    events = [e[:9] + (class_off[e[7]] + e[8],) if e[6] else e
              for e in events]

    cols = np.array(events, np.int32).reshape(-1, 10)
    return PreparedHistory(
        kind=cols[:, 0], slot=cols[:, 1], f=cols[:, 2],
        a=cols[:, 3], b=cols[:, 4], op_id=cols[:, 5], ghost=cols[:, 6],
        gcls=cols[:, 7], grank=cols[:, 8], gpos=cols[:, 9],
        window=next_slot, ops=ops, crashed_slots=tuple(crashed),
        n_ghosts=off,
    )
