"""Second host-tier solver: memoized depth-first linearization search.

Algorithmically distinct from :mod:`jepsen_tpu.checker.wgl_cpu` (which
carries the FULL configuration set breadth-first through the history —
knossos's WGL role): this solver walks the event stream depth-first,
committing to one linearization choice at a time and backtracking on
contradiction, with every visited ``(event, linearized-set, model)`` state
memoized so no subtree is explored twice.  That is the knossos ``linear``
role — the reference races linear vs wgl inside ``competition``
(jepsen/src/jepsen/checker.clj:199-202), and racing two different
algorithms both diversifies performance (DFS typically touches a tiny
fraction of WGL's frontier on *valid* histories, since ops usually
linearize in completion order) and cross-validates each against the other.

Verdict-equivalence with the BFS oracle: both decide reachability over the
same state graph — states are ``(event index, applied-pending bitmask,
model state)``, DFS just orders the exploration differently and prunes
visited states instead of deduplicating a frontier.  Ghosts (crashed ops
that never return) may be applied or not; a fully consumed event stream is
a witness (pending ghosts are optional, like the BFS oracle's final
argument).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from jepsen_tpu.checker.prep import (EV_ENTER, EV_RETURN, PreparedHistory,
                                     prepare)
from jepsen_tpu.checker.wgl_cpu import Cancelled, SearchExploded
from jepsen_tpu.history import History, Op
from jepsen_tpu.models.base import Inconsistent, Model


def check(model: Model, history: History,
          prepared: Optional[PreparedHistory] = None,
          max_states: int = 2_000_000,
          cancel=None) -> Dict[str, Any]:
    """Decide linearizability by memoized DFS.  Returns a knossos-shaped
    analysis map; raises :class:`SearchExploded` past ``max_states`` visited
    states and :class:`Cancelled` when a competing solver already won."""
    p = prepared if prepared is not None else prepare(history)
    n = len(p)
    if n == 0:
        return {"valid": True, "analyzer": "linear-cpu",
                "states-explored": 0}

    # Per-event window reconstruction: slot -> Op at each RETURN event, and
    # the op entering/returning at each event.  DFS backtracks across event
    # indices, so the window must be addressable by event, not maintained
    # incrementally the way the forward-only BFS driver does it.
    window: Dict[int, Op] = {}
    pending_at: List[Optional[List[Tuple[int, Op]]]] = [None] * n
    ret_slot: List[int] = [0] * n
    ret_op: List[Optional[Op]] = [None] * n
    for e in range(n):
        kind, slot, op_id = int(p.kind[e]), int(p.slot[e]), int(p.op_id[e])
        if kind == EV_ENTER:
            window[slot] = p.ops[op_id]
        elif kind == EV_RETURN:
            pending_at[e] = sorted(window.items())
            ret_slot[e] = slot
            ret_op[e] = p.ops[op_id]
            del window[slot]

    visited: set = set()
    # Deepest STUCK return for the refutation report: a RETURN event whose
    # frame produced no successor at all (nothing could linearize past
    # it on that branch).  The deepest merely-VISITED return would name
    # whatever op some abandoned branch happened to reach — knossos names
    # the op whose return is unsatisfiable, and so do we.
    deepest_stuck = -1
    deepest_e = -1

    # Explicit stack of (event, mask, model, choice iterator).  A frame's
    # iterator yields successor states lazily; exhausting it backtracks.
    def successors(e: int, mask: int, m: Model):
        """Lazily yield next states from (e, mask, m)."""
        kind = int(p.kind[e])
        if kind == EV_ENTER:
            yield (e + 1, mask, m)
            return
        if kind != EV_RETURN:
            yield (e + 1, mask, m)
            return
        slot = ret_slot[e]
        bit = 1 << slot
        if mask & bit:
            # already linearized: consume the return, retire the bit
            yield (e + 1, mask & ~bit, m)
            return
        # Must linearize more pending ops before this return can pass.
        # Heuristic: try the returning op itself first — on valid histories
        # ops overwhelmingly linearize in completion order, which is what
        # makes the DFS fast where BFS pays for the whole frontier.
        ordered = sorted(pending_at[e], key=lambda kv: kv[0] != slot)
        for s, op in ordered:
            b = 1 << s
            if mask & b:
                continue
            m2 = m.step(op)
            if isinstance(m2, Inconsistent):
                continue
            yield (e, mask | b, m2)

    start = (0, 0, model)
    visited.add(start)
    # Frames: [e, mask, model, iterator, ever_advanced]
    stack: List[List[Any]] = [[0, 0, model, successors(0, 0, model), False]]
    steps = 0
    while stack:
        steps += 1
        if (steps & 0xFFF) == 0 and cancel is not None and cancel.is_set():
            raise Cancelled()
        frame = stack[-1]
        e, mask, m, it = frame[0], frame[1], frame[2], frame[3]
        if int(p.kind[e]) == EV_RETURN:
            deepest_e = max(deepest_e, e)
        advanced = False
        for nxt in it:
            ne, nmask, nm = nxt
            if ne >= n:
                return {"valid": True, "analyzer": "linear-cpu",
                        "states-explored": len(visited)}
            key = (ne, nmask, nm)
            if key in visited:
                continue
            visited.add(key)
            if len(visited) > max_states:
                raise SearchExploded(len(visited))
            stack.append([ne, nmask, nm, successors(ne, nmask, nm), False])
            advanced = True
            frame[4] = True
            break
        if not advanced:
            if not frame[4] and int(p.kind[e]) == EV_RETURN:
                deepest_stuck = max(deepest_stuck, e)
            stack.pop()

    named = deepest_stuck if deepest_stuck >= 0 else deepest_e
    bad = ret_op[named] if named >= 0 else None
    # witness: DFS exhausted with no linearization; deepest stuck op rides
    return {"valid": False, "analyzer": "linear-cpu",
            "op": bad.to_dict() if bad is not None else None,
            "states-explored": len(visited),
            "deepest-event": deepest_e,
            "stuck-event": deepest_stuck}
