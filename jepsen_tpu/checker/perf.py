"""Performance analysis and plots from histories.

Parity: jepsen.checker.perf + the perf/latency-graph/rate-graph checkers
(jepsen/src/jepsen/checker.clj:797-829, checker/perf.clj:21-80): latency
quantiles and throughput over time, rendered with matplotlib (the
reference's gnuplot), with nemesis activity windows shaded
(util.clj:744 nemesis-intervals).
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jepsen_tpu.checker.core import Checker
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, NEMESIS, OK

QUANTILES = [0.5, 0.95, 0.99, 1.0]


def latency_points(history: History) -> Dict[str, List[Tuple[float, float]]]:
    """[(invoke-time-s, latency-ms)] per f, completed client ops only."""
    pairs = history.pair_index()
    out: Dict[str, List[Tuple[float, float]]] = defaultdict(list)
    for i, op in enumerate(history):
        if op.process == NEMESIS or op.type != INVOKE:
            continue
        j = pairs[i]
        if j < 0:
            continue
        comp = history[j]
        if None in (op.time, comp.time):
            continue
        out[f"{op.f}:{comp.type}"].append(
            (op.time / 1e9, (comp.time - op.time) / 1e6))
    return dict(out)


def rate_points(history: History, dt_s: float = 1.0) -> Dict[str, np.ndarray]:
    """Completions/sec per (f, type) in dt buckets."""
    buckets: Dict[str, Dict[int, int]] = defaultdict(lambda: defaultdict(int))
    tmax = 0.0
    for op in history:
        if op.process == NEMESIS or op.type == INVOKE or op.time is None:
            continue
        t = op.time / 1e9
        tmax = max(tmax, t)
        buckets[f"{op.f}:{op.type}"][int(t / dt_s)] += 1
    n = int(tmax / dt_s) + 1
    out = {}
    for k, b in buckets.items():
        arr = np.zeros(n)
        for i, c in b.items():
            arr[i] = c / dt_s
        out[k] = arr
    return out


def nemesis_intervals(history: History,
                      start_fs=("start",), stop_fs=("stop",)
                      ) -> List[Tuple[float, float]]:
    """[(start-s, stop-s)] windows of nemesis activity (util.clj:744);
    any nemesis f containing 'start'/'stop' (or listed) toggles."""
    out = []
    open_t: Optional[float] = None
    tmax = 0.0
    for op in history:
        if op.time is None:
            continue
        tmax = max(tmax, op.time / 1e9)
        if op.process != NEMESIS or op.type == INVOKE:
            continue
        f = str(op.f)
        is_start = f in start_fs or f.startswith("start") or "start-" in f
        is_stop = f in stop_fs or f.startswith("stop") or "stop-" in f or \
            f.startswith("heal") or f.startswith("resume")
        if is_start and open_t is None:
            open_t = op.time / 1e9
        elif is_stop and open_t is not None:
            out.append((open_t, op.time / 1e9))
            open_t = None
    if open_t is not None:
        out.append((open_t, tmax))
    return out


def latency_quantiles(history: History) -> Dict[str, Dict[str, float]]:
    pts = latency_points(history)
    out = {}
    for k, series in pts.items():
        lat = np.array([l for _, l in series])
        out[k] = {f"p{int(q * 100)}": float(np.quantile(lat, q))
                  for q in QUANTILES}
        out[k]["count"] = len(series)
    return out


def _plot(history: History, store_dir: str, which: str) -> Optional[str]:
    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    fig, ax = plt.subplots(figsize=(10, 5))
    for a, b in nemesis_intervals(history):
        ax.axvspan(a, b, color="#FDD", zorder=0)
    if which == "latency":
        for k, series in sorted(latency_points(history).items()):
            xs = [t for t, _ in series]
            ys = [l for _, l in series]
            marker = "." if k.endswith(OK) else "x"
            ax.plot(xs, ys, marker, markersize=3, label=k, alpha=0.6)
        ax.set_yscale("log")
        ax.set_ylabel("latency (ms)")
    else:
        for k, arr in sorted(rate_points(history).items()):
            ax.plot(np.arange(len(arr)), arr, label=k)
        ax.set_ylabel("throughput (ops/s)")
    ax.set_xlabel("time (s)")
    ax.legend(fontsize=7)
    path = os.path.join(store_dir, f"{which}-raw.png")
    fig.savefig(path, dpi=100, bbox_inches="tight")
    plt.close(fig)
    return path


class LatencyGraph(Checker):
    """checker.clj:797 latency-graph."""

    def check(self, test, history, opts=None):
        d = (opts or {}).get("store_dir") or test.get("store_dir")
        out = {"valid": True, "quantiles": latency_quantiles(history)}
        if d:
            out["plot"] = _plot(history, d, "latency")
        return out


class RateGraph(Checker):
    """checker.clj:810 rate-graph."""

    def check(self, test, history, opts=None):
        d = (opts or {}).get("store_dir") or test.get("store_dir")
        out = {"valid": True}
        if d:
            out["plot"] = _plot(history, d, "rate")
        return out


class Perf(Checker):
    """checker.clj:822 perf — both graphs."""

    def check(self, test, history, opts=None):
        lg = LatencyGraph().check(test, history, opts)
        rg = RateGraph().check(test, history, opts)
        return {"valid": True, "latency": lg, "rate": rg}


class ClockPlot(Checker):
    """Plot clock offsets recorded by a clock nemesis
    (checker/clock.clj:13-34): ops whose value carries {node: offset-s}."""

    def check(self, test, history, opts=None):
        series: Dict[str, List[Tuple[float, float]]] = defaultdict(list)
        for op in history:
            if op.f == "clock-offsets" and isinstance(op.value, dict) \
                    and op.time is not None:
                for node, off in op.value.items():
                    series[node].append((op.time / 1e9, off))
        out = {"valid": True, "nodes": sorted(series)}
        d = (opts or {}).get("store_dir") or test.get("store_dir")
        if d and series:
            try:
                import matplotlib
                matplotlib.use("Agg")
                import matplotlib.pyplot as plt
                fig, ax = plt.subplots(figsize=(10, 4))
                for node, pts in sorted(series.items()):
                    ax.plot([t for t, _ in pts], [o for _, o in pts],
                            label=node)
                ax.set_xlabel("time (s)")
                ax.set_ylabel("clock offset (s)")
                ax.legend(fontsize=7)
                path = os.path.join(d, "clock-skew.png")
                fig.savefig(path, dpi=100, bbox_inches="tight")
                plt.close(fig)
                out["plot"] = path
            except ImportError:
                pass
        return out
