"""Mutex, queue, set, and multi-register models (knossos.model parity).

The reference's suites construct these via knossos.model (e.g. mutex for lock
services, fifo-queue for queue workloads); see the external-library inventory
in SURVEY.md §2.2.  Host tier for all; device tier for mutex (trivial state),
bounded-domain set, and the multi-register (k int32 lanes).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, FrozenSet, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history import Op
from jepsen_tpu.models.base import (
    UNKNOWN32, JaxModel, Model, inconsistent, register_model,
)


# -- mutex ------------------------------------------------------------------

@dataclass(frozen=True)
class Mutex(Model):
    locked: bool = False

    def step(self, op: Op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown f {op.f!r}")


F_ACQUIRE, F_RELEASE = 0, 1


@register_model("mutex")
def mutex_jax() -> JaxModel:
    def step(state, f, a, b):
        locked = state[0]
        is_acq = f == F_ACQUIRE
        ok = jnp.where(is_acq, locked == 0, locked == 1)
        new = jnp.where(ok, jnp.where(is_acq, 1, 0), locked)
        return new[None].astype(jnp.int32), ok

    def encode(op: Op):
        if op.f == "acquire":
            return F_ACQUIRE, 0, 0
        if op.f == "release":
            return F_RELEASE, 0, 0
        raise ValueError(f"mutex can't encode f={op.f!r}")

    return JaxModel(name="mutex", state_size=1,
                    init_state=np.array([0], np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: Mutex())


# -- fifo / unordered queues ------------------------------------------------

@dataclass(frozen=True)
class FIFOQueue(Model):
    items: Tuple[Any, ...] = ()

    def step(self, op: Op):
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            if op.value is not None and self.items[0] != op.value:
                return inconsistent(
                    f"expected {op.value!r} at head, found {self.items[0]!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """Queue without ordering guarantees — dequeue may take any element."""

    items: FrozenSet[Any] = frozenset()

    def step(self, op: Op):
        if op.f == "enqueue":
            return UnorderedQueue(self.items | {op.value})
        if op.f == "dequeue":
            if op.value is None:
                if not self.items:
                    return inconsistent("dequeue from empty queue")
                return UnorderedQueue(frozenset(list(self.items)[1:]))
            if op.value not in self.items:
                return inconsistent(f"{op.value!r} not in queue")
            return UnorderedQueue(self.items - {op.value})
        return inconsistent(f"unknown f {op.f!r}")


# -- grow-only / read-full set ---------------------------------------------

@dataclass(frozen=True)
class SetModel(Model):
    items: FrozenSet[Any] = frozenset()

    def step(self, op: Op):
        if op.f == "add":
            return SetModel(self.items | {op.value})
        if op.f == "read":
            if op.value is None:
                return self
            observed = frozenset(op.value)
            if observed == self.items:
                return self
            return inconsistent(
                f"read {sorted(map(repr, observed))} but set is "
                f"{sorted(map(repr, self.items))}")
        return inconsistent(f"unknown f {op.f!r}")


# -- multi-register ---------------------------------------------------------

@dataclass(frozen=True)
class MultiRegister(Model):
    """Map of keys to values; ops carry value = {key: v, ...} maps.

    read asserts all observed keys; write sets all given keys (knossos
    multi-register parity).
    """

    values: Tuple[Tuple[Any, Any], ...] = ()

    def _as_dict(self):
        return dict(self.values)

    def step(self, op: Op):
        d = self._as_dict()
        if op.f in ("read", "r"):
            if op.value is None:
                return self
            for k, v in dict(op.value).items():
                # Nil reads are always legal (multi_key_acid.clj:22-23): a
                # None value is an unfilled placeholder (pending/info read),
                # not an observation of "key absent".
                if v is None:
                    continue
                if d.get(k) != v:
                    return inconsistent(f"key {k!r}: read {v!r}, have {d.get(k)!r}")
            return self
        if op.f in ("write", "w"):
            d.update(dict(op.value))
            return MultiRegister(tuple(sorted(d.items(), key=repr)))
        return inconsistent(f"unknown f {op.f!r}")


# -- multi-register, device tier --------------------------------------------

F_MR_READ, F_MR_WRITE = 0, 1


@register_model("multi-register")
def multi_register_jax(keys: int = 3, vbits: int = 4) -> JaxModel:
    """Device tier for :class:`MultiRegister`: k int32 lanes, one per key.

    Multi-key ops (the multi_key_acid.clj / crdb / tidb register shapes,
    BASELINE configs #4/#5) pack into the engine's (f, a, b) encoding:
    ``a`` is the touched-key bitmask, ``b`` packs each touched key's value in
    ``vbits``-bit fields.  None read values are simply absent from the mask —
    nil reads are always legal (multi_key_acid.clj:22-23) — and an op whose
    mask is empty (e.g. a crashed read that never observed anything) encodes
    ``a = UNKNOWN32`` so preprocessing's crashed-read elimination drops it.

    Constraints checked at encode time: integer keys in [0, keys), integer
    values in [0, 2**vbits); keys ≤ 31 and keys*vbits ≤ 31 so both fields fit
    an int32.  Out-of-domain histories raise ValueError — the competition
    facade then falls through to the host oracle.
    """
    if keys > 31 or keys * vbits > 31:
        raise ValueError(f"multi-register device tier needs keys<=31 and "
                         f"keys*vbits<=31 (got {keys}x{vbits})")
    vmask = (1 << vbits) - 1
    lanes = np.arange(keys, dtype=np.int32)

    def step(state, f, a, b):
        unconstrained = a == UNKNOWN32
        mask = jnp.where(unconstrained, 0, a)
        touched = ((mask >> lanes) & 1) == 1
        vals = (b >> (lanes * vbits)) & vmask
        is_read = f == F_MR_READ
        is_write = f == F_MR_WRITE
        read_ok = jnp.all(~touched | (state == vals))
        ok = jnp.where(is_read, read_ok, is_write)
        new_state = jnp.where(is_write & touched, vals, state)
        return jnp.where(ok, new_state, state), ok

    def encode(op: Op):
        f = {"read": F_MR_READ, "r": F_MR_READ,
             "write": F_MR_WRITE, "w": F_MR_WRITE}.get(op.f)
        if f is None:
            raise ValueError(f"multi-register can't encode f={op.f!r}")
        if op.value is None:
            return f, UNKNOWN32, 0
        mask = packed = 0
        for k, v in dict(op.value).items():
            if v is None:
                if f == F_MR_WRITE:
                    # The host model stores the None literally; silently
                    # dropping the pair here would diverge the tiers.  No
                    # workload writes nil (multi_key_acid.clj rand-val) —
                    # refuse and let the facade fall back to the host.
                    raise ValueError("multi-register can't encode a nil "
                                     f"write for key {k!r}")
                continue  # nil read: unconstraining
            k, v = int(k), int(v)
            if not 0 <= k < keys:
                raise ValueError(f"key {k} outside [0, {keys})")
            if not 0 <= v <= vmask:
                raise ValueError(f"value {v} outside [0, {vmask}]")
            mask |= 1 << k
            packed |= v << (k * vbits)
        if mask == 0:
            return f, UNKNOWN32, 0
        return f, mask, packed

    return JaxModel(name="multi-register", state_size=keys,
                    init_state=np.full(keys, UNKNOWN32 + 1, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: MultiRegister(),
                    pure_read_fs=(F_MR_READ,),
                    variant=(keys, vbits))


# -- bounded-domain set, device tier ---------------------------------------


@dataclass(frozen=True)
class BitSetModel(Model):
    """Host-tier oracle for the device bitset: grow-only int set with
    single-element membership reads (f=read value=(k, present))."""

    items: FrozenSet[int] = frozenset()

    def step(self, op: Op):
        if op.f == "add":
            return BitSetModel(self.items | {int(op.value)})
        if op.f == "read":
            k, present = op.value
            if bool(present) == (int(k) in self.items):
                return self
            return inconsistent(
                f"read ({k}, {present}) but membership is "
                f"{int(k) in self.items}")
        return inconsistent(f"unknown f {op.f!r}")


F_ADD, F_READBIT = 0, 1


@register_model("bitset")
def bitset_jax(domain: int = 1024) -> JaxModel:
    """Grow-only set over int keys [0, domain): state is a bitmask.

    Device-tier analog of SetModel for workloads whose reads check a single
    element's membership: f=add value=k; f=read value=(k, present?1:0).
    """
    words = (domain + 31) // 32

    def step(state, f, a, b):
        word, bit = a // 32, a % 32
        mask = (jnp.int32(1) << bit)
        has = (state[word] & mask) != 0
        is_add = f == F_ADD
        ok = jnp.where(is_add, True, has == (b != 0))
        new = state.at[word].set(
            jnp.where(is_add, state[word] | mask, state[word]))
        return new, ok

    def encode(op: Op):
        if op.f == "add":
            return F_ADD, int(op.value), 0
        if op.f == "read":
            k, present = op.value
            return F_READBIT, int(k), int(bool(present))
        raise ValueError(f"bitset can't encode f={op.f!r}")

    return JaxModel(name="bitset", state_size=words,
                    init_state=np.zeros(words, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: BitSetModel())


@register_model("bitset-256")
def bitset256_jax() -> JaxModel:
    """256-element bitset: 8 state words instead of 32, keeping the
    engine's variadic dedup sort narrow (wide sorts at large row counts
    have crashed the TPU compiler) — the bench ceiling tier's model."""
    m = bitset_jax(256)
    return JaxModel(name="bitset-256", state_size=m.state_size,
                    init_state=m.init_state, step=m.step,
                    encode_op=m.encode_op, cpu_model=m.cpu_model)
