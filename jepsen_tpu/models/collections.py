"""Mutex, queue, set, and multi-register models (knossos.model parity).

The reference's suites construct these via knossos.model (e.g. mutex for lock
services, fifo-queue for queue workloads); see the external-library inventory
in SURVEY.md §2.2.  Host tier for all; device tier for mutex (trivial state),
bounded-domain set, and the multi-register (k int32 lanes).
"""

from __future__ import annotations

import numbers
from dataclasses import dataclass, field
from typing import Any, FrozenSet, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history import Op
from jepsen_tpu.models.base import (
    UNKNOWN32, JaxModel, Model, inconsistent, register_model,
)


# -- mutex ------------------------------------------------------------------

@dataclass(frozen=True)
class Mutex(Model):
    locked: bool = False

    def step(self, op: Op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown f {op.f!r}")


F_ACQUIRE, F_RELEASE = 0, 1


@register_model("mutex")
def mutex_jax() -> JaxModel:
    def step(state, f, a, b):
        locked = state[0]
        is_acq = f == F_ACQUIRE
        ok = jnp.where(is_acq, locked == 0, locked == 1)
        new = jnp.where(ok, jnp.where(is_acq, 1, 0), locked)
        return new[None].astype(jnp.int32), ok

    def encode(op: Op):
        if op.f == "acquire":
            return F_ACQUIRE, 0, 0
        if op.f == "release":
            return F_RELEASE, 0, 0
        raise ValueError(f"mutex can't encode f={op.f!r}")

    return JaxModel(name="mutex", state_size=1,
                    init_state=np.array([0], np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: Mutex())


# -- fifo / unordered queues ------------------------------------------------

@dataclass(frozen=True)
class FIFOQueue(Model):
    items: Tuple[Any, ...] = ()

    def step(self, op: Op):
        if op.f == "enqueue":
            return FIFOQueue(self.items + (op.value,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent("dequeue from empty queue")
            if op.value is not None and self.items[0] != op.value:
                return inconsistent(
                    f"expected {op.value!r} at head, found {self.items[0]!r}")
            return FIFOQueue(self.items[1:])
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class UnorderedQueue(Model):
    """Queue without ordering guarantees — dequeue may take any element."""

    items: FrozenSet[Any] = frozenset()

    def step(self, op: Op):
        if op.f == "enqueue":
            return UnorderedQueue(self.items | {op.value})
        if op.f == "dequeue":
            if op.value is None:
                if not self.items:
                    return inconsistent("dequeue from empty queue")
                # Unconstrained dequeue (crashed/info op): SOME element
                # left, we don't know which.  A single-successor step must
                # pick one; pick deterministically (smallest by repr) —
                # ``list(frozenset)[1:]`` depended on hash iteration order,
                # so verdicts varied run-to-run with PYTHONHASHSEED (see
                # tests/test_models.py pinning tests).
                keep = sorted(self.items, key=repr)[1:]
                return UnorderedQueue(frozenset(keep))
            if op.value not in self.items:
                return inconsistent(f"{op.value!r} not in queue")
            return UnorderedQueue(self.items - {op.value})
        return inconsistent(f"unknown f {op.f!r}")


# -- grow-only / read-full set ---------------------------------------------

@dataclass(frozen=True)
class SetModel(Model):
    items: FrozenSet[Any] = frozenset()

    def step(self, op: Op):
        if op.f == "add":
            return SetModel(self.items | {op.value})
        if op.f == "read":
            if op.value is None:
                return self
            observed = frozenset(op.value)
            if observed == self.items:
                return self
            return inconsistent(
                f"read {sorted(map(repr, observed))} but set is "
                f"{sorted(map(repr, self.items))}")
        return inconsistent(f"unknown f {op.f!r}")


# -- multi-register ---------------------------------------------------------

@dataclass(frozen=True)
class MultiRegister(Model):
    """Map of keys to values; ops carry value = {key: v, ...} maps.

    read asserts all observed keys; write sets all given keys (knossos
    multi-register parity).
    """

    values: Tuple[Tuple[Any, Any], ...] = ()

    def _as_dict(self):
        return dict(self.values)

    def step(self, op: Op):
        d = self._as_dict()
        if op.f in ("read", "r"):
            if op.value is None:
                return self
            for k, v in dict(op.value).items():
                # Nil reads are always legal (multi_key_acid.clj:22-23): a
                # None value is an unfilled placeholder (pending/info read),
                # not an observation of "key absent".
                if v is None:
                    continue
                if d.get(k) != v:
                    return inconsistent(f"key {k!r}: read {v!r}, have {d.get(k)!r}")
            return self
        if op.f in ("write", "w"):
            d.update(dict(op.value))
            return MultiRegister(tuple(sorted(d.items(), key=repr)))
        return inconsistent(f"unknown f {op.f!r}")


# -- multi-register, device tier --------------------------------------------

F_MR_READ, F_MR_WRITE = 0, 1


def multi_register_components(op: Op):
    """Per-key independence: the map is a product of one register per key,
    a write touches exactly its keys, and a read constrains only the keys
    it observed (nil reads are always legal, multi_key_acid.clj:22-23, so
    a key read as None constrains nothing)."""
    if op.f in ("write", "w"):
        if op.value is None:
            return None  # crashed write with unknown keys: can't place it
        return frozenset(dict(op.value).keys())
    if op.f in ("read", "r"):
        if op.value is None:
            return frozenset()
        return frozenset(k for k, v in dict(op.value).items()
                         if v is not None)
    return None


@register_model("multi-register")
def multi_register_jax(keys: int = 3, vbits: int = 4) -> JaxModel:
    """Device tier for :class:`MultiRegister`: k int32 lanes, one per key.

    Multi-key ops (the multi_key_acid.clj / crdb / tidb register shapes,
    BASELINE configs #4/#5) pack into the engine's (f, a, b) encoding:
    ``a`` is the touched-key bitmask, ``b`` packs each touched key's value in
    ``vbits``-bit fields.  None read values are simply absent from the mask —
    nil reads are always legal (multi_key_acid.clj:22-23) — and an op whose
    mask is empty (e.g. a crashed read that never observed anything) encodes
    ``a = UNKNOWN32`` so preprocessing's crashed-read elimination drops it.

    Constraints checked at encode time: integer keys in [0, keys), integer
    values in [0, 2**vbits); keys ≤ 31 and keys*vbits ≤ 31 so both fields fit
    an int32.  Out-of-domain histories raise ValueError — the competition
    facade then falls through to the host oracle.
    """
    if keys > 31 or keys * vbits > 31:
        raise ValueError(f"multi-register device tier needs keys<=31 and "
                         f"keys*vbits<=31 (got {keys}x{vbits})")
    vmask = (1 << vbits) - 1
    lanes = np.arange(keys, dtype=np.int32)

    def step(state, f, a, b):
        unconstrained = a == UNKNOWN32
        mask = jnp.where(unconstrained, 0, a)
        touched = ((mask >> lanes) & 1) == 1
        vals = (b >> (lanes * vbits)) & vmask
        is_read = f == F_MR_READ
        is_write = f == F_MR_WRITE
        read_ok = jnp.all(~touched | (state == vals))
        ok = jnp.where(is_read, read_ok, is_write)
        new_state = jnp.where(is_write & touched, vals, state)
        return jnp.where(ok, new_state, state), ok

    def encode(op: Op):
        f = {"read": F_MR_READ, "r": F_MR_READ,
             "write": F_MR_WRITE, "w": F_MR_WRITE}.get(op.f)
        if f is None:
            raise ValueError(f"multi-register can't encode f={op.f!r}")
        if op.value is None:
            return f, UNKNOWN32, 0
        mask = packed = 0
        for k, v in dict(op.value).items():
            if v is None:
                if f == F_MR_WRITE:
                    # The host model stores the None literally; silently
                    # dropping the pair here would diverge the tiers.  No
                    # workload writes nil (multi_key_acid.clj rand-val) —
                    # refuse and let the facade fall back to the host.
                    raise ValueError("multi-register can't encode a nil "
                                     f"write for key {k!r}")
                continue  # nil read: unconstraining
            # Coercion must not widen the domain: ``int("1")`` would make
            # the device treat a string key as key 1 while the host
            # MultiRegister compares raw keys ("1" != 1) — the tiers
            # would silently disagree.  Only integral keys/values encode;
            # anything else raises, and the facade falls back to the
            # host oracle, which handles arbitrary keys correctly.
            if not isinstance(k, numbers.Integral):
                raise ValueError(f"multi-register can't encode non-int "
                                 f"key {k!r}")
            if not isinstance(v, numbers.Integral):
                raise ValueError(f"multi-register can't encode non-int "
                                 f"value {v!r} for key {k!r}")
            k, v = int(k), int(v)
            if not 0 <= k < keys:
                raise ValueError(f"key {k} outside [0, {keys})")
            if not 0 <= v <= vmask:
                raise ValueError(f"value {v} outside [0, {vmask}]")
            mask |= 1 << k
            packed |= v << (k * vbits)
        if mask == 0:
            return f, UNKNOWN32, 0
        return f, mask, packed

    return JaxModel(name="multi-register", state_size=keys,
                    init_state=np.full(keys, UNKNOWN32 + 1, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: MultiRegister(),
                    pure_read_fs=(F_MR_READ,),
                    variant=(keys, vbits),
                    components=multi_register_components)


# -- bounded-domain set, device tier ---------------------------------------


@dataclass(frozen=True)
class BitSetModel(Model):
    """Host-tier oracle for the device bitset: grow-only int set with
    single-element membership reads (f=read value=(k, present))."""

    items: FrozenSet[int] = frozenset()

    def step(self, op: Op):
        if op.f == "add":
            return BitSetModel(self.items | {int(op.value)})
        if op.f == "read":
            k, present = op.value
            if bool(present) == (int(k) in self.items):
                return self
            return inconsistent(
                f"read ({k}, {present}) but membership is "
                f"{int(k) in self.items}")
        return inconsistent(f"unknown f {op.f!r}")


F_ADD, F_READBIT = 0, 1


def bitset_components(op: Op):
    """Per-element independence: a grow-only set's state is a product of
    one bit per element, ``add v`` writes only bit v, and ``read (k, _)``
    constrains only bit k (Herlihy–Wing locality per element)."""
    if op.f == "add":
        if op.value is None:
            return None  # value unknown: can't place the write
        return frozenset({int(op.value)})
    if op.f == "read":
        if op.value is None:
            return frozenset()  # crashed read, nothing observed
        k, _present = op.value
        return frozenset({int(k)})
    return None


@register_model("bitset")
def bitset_jax(domain: int = 1024) -> JaxModel:
    """Grow-only set over int keys [0, domain): state is a bitmask.

    Device-tier analog of SetModel for workloads whose reads check a single
    element's membership: f=add value=k; f=read value=(k, present?1:0).
    """
    words = (domain + 31) // 32

    def step(state, f, a, b):
        word, bit = a // 32, a % 32
        mask = (jnp.int32(1) << bit)
        has = (state[word] & mask) != 0
        is_add = f == F_ADD
        ok = jnp.where(is_add, True, has == (b != 0))
        new = state.at[word].set(
            jnp.where(is_add, state[word] | mask, state[word]))
        return new, ok

    def encode(op: Op):
        if op.f == "add":
            return F_ADD, int(op.value), 0
        if op.f == "read":
            k, present = op.value
            return F_READBIT, int(k), int(bool(present))
        raise ValueError(f"bitset can't encode f={op.f!r}")

    return JaxModel(name="bitset", state_size=words,
                    init_state=np.zeros(words, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: BitSetModel(),
                    components=bitset_components)


@register_model("bitset-256")
def bitset256_jax() -> JaxModel:
    """256-element bitset: 8 state words instead of 32, keeping the
    engine's variadic dedup sort narrow (wide sorts at large row counts
    have crashed the TPU compiler) — the bench ceiling tier's model."""
    m = bitset_jax(256)
    return JaxModel(name="bitset-256", state_size=m.state_size,
                    init_state=m.init_state, step=m.step,
                    encode_op=m.encode_op, cpu_model=m.cpu_model,
                    components=m.components)


# -- fifo queue, device tier -------------------------------------------------

F_ENQ, F_DEQ = 0, 1


@register_model("fifo-queue")
def fifo_queue_jax(slots: int = 64) -> JaxModel:
    """Device tier for :class:`FIFOQueue`: a bounded int32 ring buffer.

    State is ``[head, tail, buf[slots]]``; head/tail are monotonic
    cursors (depth = tail - head), ``buf[2 + cursor % slots]`` holds the
    element.  Enqueue appends at tail; dequeue pops at head, constrained
    to the head element when the op observed a value (``b=1``) and
    unconstrained for crashed/nil dequeues (``b=0``) — matching the host
    oracle's "None pops the head" semantics exactly, since FIFO leaves no
    choice of which element leaves.  All scatters are int32 (vmap-safe;
    see engine.groups for the bool-scatter cliff).

    Soundness bound: a linearization holding more than ``slots`` elements
    at once would wrongly fail the enqueue, so ``encode_op`` counts the
    history's enqueues at encode time via the plugin facade picking
    ``slots`` >= total enqueues — the builtin plugin derives ``slots``
    from the history; out-of-domain values (non-int, |v| at the int32
    edge) raise ValueError and the facade falls back to the host oracle.
    """
    if slots < 1:
        raise ValueError(f"fifo-queue needs slots >= 1 (got {slots})")

    def step(state, f, a, b):
        head, tail = state[0], state[1]
        depth = tail - head
        is_enq = f == F_ENQ
        slot_e = 2 + jnp.mod(tail, slots)
        slot_d = 2 + jnp.mod(head, slots)
        head_v = state[slot_d]
        enq_ok = depth < slots
        deq_ok = (depth > 0) & ((b == 0) | (head_v == a))
        ok = jnp.where(is_enq, enq_ok, deq_ok)
        # Enqueue writes a at tail's slot; dequeue zeroes head's slot (so
        # drained queues dedup back onto each other).  At depth 0 the two
        # slots coincide: write the enqueue value first, then zero only on
        # an actual dequeue.
        new = state.at[slot_e].set(jnp.where(is_enq, a, state[slot_e]))
        new = new.at[slot_d].set(jnp.where(is_enq, new[slot_d], 0))
        new = new.at[0].set(jnp.where(is_enq, head, head + 1))
        new = new.at[1].set(jnp.where(is_enq, tail + 1, tail))
        return jnp.where(ok, new, state), ok

    def encode(op: Op):
        if op.f == "enqueue":
            v = op.value
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(f"fifo-queue device tier needs int "
                                 f"elements (got {v!r})")
            if not -2**31 < v < 2**31:
                raise ValueError(f"element {v} outside int32")
            return F_ENQ, v, 0
        if op.f == "dequeue":
            if op.value is None:
                return F_DEQ, UNKNOWN32, 0
            v = op.value
            if not isinstance(v, int) or isinstance(v, bool):
                raise ValueError(f"fifo-queue device tier needs int "
                                 f"elements (got {v!r})")
            if not -2**31 < v < 2**31:
                raise ValueError(f"element {v} outside int32")
            return F_DEQ, v, 1
        raise ValueError(f"fifo-queue can't encode f={op.f!r}")

    return JaxModel(name="fifo-queue", state_size=2 + slots,
                    init_state=np.zeros(2 + slots, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: FIFOQueue(),
                    variant=(slots,))


# -- read-full set, device tier ----------------------------------------------

#: Element domain of the device set: two 31-bit words (bit 31 stays clear
#: so the packed read masks are non-negative int32s, and neither word can
#: collide with the UNKNOWN32 sentinel).
SET_DOMAIN = 62

F_SADD, F_SREAD = 0, 1


@register_model("set")
def set_jax() -> JaxModel:
    """Device tier for :class:`SetModel`: grow-only int set with
    *read-the-full-set* reads (the jepsen set-full workload shape).

    State is the membership bitmask over [0, 62) split across two 31-bit
    int32 words.  ``add k`` ORs the bit in; ``read S`` packs S into the
    same two words and requires exact equality with the state — precisely
    the host oracle's frozenset equality.  Nil reads (crashed) encode
    ``a = UNKNOWN32`` and constrain nothing; reads are pure so
    preprocessing's crashed-read elimination drops them.  Out-of-domain
    elements raise ValueError and the facade falls back to the host.
    """
    def step(state, f, a, b):
        is_add = f == F_SADD
        k = jnp.where(is_add, a, 0)
        word = k // 31
        mask = jnp.int32(1) << jnp.mod(k, 31)
        added = state.at[word].set(state[word] | mask)
        unconstrained = a == UNKNOWN32
        read_ok = unconstrained | ((state[0] == a) & (state[1] == b))
        ok = jnp.where(is_add, True, read_ok)
        new = jnp.where(is_add, added, state)
        return new, ok

    def _elem(v) -> int:
        if not isinstance(v, int) or isinstance(v, bool):
            raise ValueError(f"set device tier needs int elements "
                             f"(got {v!r})")
        if not 0 <= v < SET_DOMAIN:
            raise ValueError(f"element {v} outside [0, {SET_DOMAIN})")
        return v

    def encode(op: Op):
        if op.f == "add":
            return F_SADD, _elem(op.value), 0
        if op.f == "read":
            if op.value is None:
                return F_SREAD, UNKNOWN32, 0
            lo = hi = 0
            for e in op.value:
                k = _elem(e)
                if k < 31:
                    lo |= 1 << k
                else:
                    hi |= 1 << (k - 31)
            return F_SREAD, lo, hi
        raise ValueError(f"set can't encode f={op.f!r}")

    return JaxModel(name="set", state_size=2,
                    init_state=np.zeros(2, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: SetModel(),
                    pure_read_fs=(F_SREAD,))


# -- transactional register (the opacity reduction's target model) -----------

@dataclass(frozen=True)
class TxnRegister(Model):
    """Host oracle for transactions-as-atomic-ops: value is a list of
    micro-ops ``[op, k, v]`` with op in {"r", "w"}, applied atomically and
    sequentially (reads after an intra-txn write see the written value).
    ``f="txn"`` may write; ``f="txn-ro"`` is the opacity reduction's
    aborted-transaction image and must be read-only.  Nil read values are
    unfilled placeholders (pending/info), not observations.
    """

    values: Tuple[Tuple[Any, Any], ...] = ()

    def step(self, op: Op):
        if op.f not in ("txn", "txn-ro"):
            return inconsistent(f"unknown f {op.f!r}")
        local = dict(self.values)
        wrote = False
        for mop in (op.value or ()):
            ftag, k, v = mop[0], mop[1], mop[2]
            if ftag in ("r", "read"):
                if v is None:
                    continue
                if local.get(k) != v:
                    return inconsistent(
                        f"key {k!r}: read {v!r}, have {local.get(k)!r}")
            elif ftag in ("w", "write"):
                if op.f == "txn-ro":
                    return inconsistent("write inside read-only txn")
                local[k] = v
                wrote = True
            else:
                return inconsistent(f"unknown mop {ftag!r}")
        if not wrote:
            return self
        return TxnRegister(tuple(sorted(local.items(), key=repr)))


F_TXN, F_TXN_RO = 0, 1


@register_model("txn-register")
def txn_register_jax(keys: int = 3, vbits: int = 4) -> JaxModel:
    """Device tier for :class:`TxnRegister`: k int32 lanes, one per key.

    A whole transaction is ONE engine event: ``a`` packs the external
    read set (touched-key bitmask in the low ``keys`` bits, each touched
    key's observed value in a ``vbits`` field above), ``b`` packs the
    write set the same way.  ``encode_op`` folds the sequential intra-txn
    semantics at encode time: reads after an intra-txn write check the
    local view and vanish from the external read set; two external reads
    of one key must agree (else ValueError -> host fallback, where the
    sequential oracle refutes precisely).  ``f=txn-ro`` (the opacity
    reduction's aborted transactions) is a pure read.  Needs
    ``keys * (1 + vbits) <= 31`` so each packed set fits an int32.
    """
    if keys * (1 + vbits) > 31:
        raise ValueError(f"txn-register device tier needs keys*(1+vbits)"
                         f"<=31 (got {keys}x{vbits})")
    vmask = (1 << vbits) - 1
    lanes = np.arange(keys, dtype=np.int32)

    def _unpack(word):
        touched = ((word >> lanes) & 1) == 1
        vals = (word >> (keys + lanes * vbits)) & vmask
        return touched, vals

    def step(state, f, a, b):
        ra = jnp.where(a == UNKNOWN32, 0, a)
        rtouch, rvals = _unpack(ra)
        ok = jnp.all(~rtouch | (state == rvals))
        wb = jnp.where(b == UNKNOWN32, 0, b)
        wtouch, wvals = _unpack(wb)
        new = jnp.where(wtouch, wvals, state)
        return jnp.where(ok, new, state), ok

    def encode(op: Op):
        f = {"txn": F_TXN, "txn-ro": F_TXN_RO}.get(op.f)
        if f is None:
            raise ValueError(f"txn-register can't encode f={op.f!r}")
        local: dict = {}
        rmask = rpack = wmask = wpack = 0
        for mop in (op.value or ()):
            ftag, k, v = mop[0], mop[1], mop[2]
            k = int(k)
            if not 0 <= k < keys:
                raise ValueError(f"key {k} outside [0, {keys})")
            if ftag in ("r", "read"):
                if v is None:
                    continue  # unfilled placeholder: unconstraining
                v = int(v)
                if not 0 <= v <= vmask:
                    raise ValueError(f"value {v} outside [0, {vmask}]")
                if k in local:
                    if local[k] != v:
                        raise ValueError(
                            f"read-own-write mismatch on key {k}")
                    continue  # satisfied locally: not an external read
                bit = 1 << k
                if rmask & bit:
                    prev = (rpack >> (keys + k * vbits)) & vmask
                    if prev != v:
                        raise ValueError(
                            f"conflicting external reads of key {k}")
                    continue
                rmask |= bit
                rpack |= v << (keys + k * vbits)
            elif ftag in ("w", "write"):
                if op.f == "txn-ro":
                    raise ValueError("write inside read-only txn")
                v = int(v)
                if not 0 <= v <= vmask:
                    raise ValueError(f"value {v} outside [0, {vmask}]")
                local[k] = v
            else:
                raise ValueError(f"unknown mop {ftag!r}")
        for k, v in local.items():
            wmask |= 1 << k
            wpack |= v << (keys + k * vbits)
        a = (rmask | rpack) if rmask else UNKNOWN32
        return f, a, wmask | wpack

    return JaxModel(name="txn-register", state_size=keys,
                    init_state=np.full(keys, UNKNOWN32 + 1, np.int32),
                    step=step, encode_op=encode,
                    cpu_model=lambda: TxnRegister(),
                    pure_read_fs=(F_TXN_RO,),
                    variant=(keys, vbits))
