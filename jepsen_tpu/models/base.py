"""Model interfaces for linearizability checking.

Two tiers, mirroring the reference's split between knossos.model's pluggable
Clojure models and the checker engines that consume them
(jepsen/src/jepsen/checker.clj:185-216, and the Model protocol echoed at
jepsen/src/jepsen/tests/causal.clj:13-27):

- :class:`Model` — a host-side immutable object with ``step(op)``; any Python
  model works, checked by the CPU engine.  This is the compatibility tier.
- :class:`JaxModel` — a pure function ``step(state, f, a, b) -> (state', ok)``
  over fixed-width int32 state, plus an op encoder.  This is the fast tier:
  the TPU engine vmaps the step over whole configuration frontiers.

A model may provide both; ``linearizable(..., algorithm="competition")`` races
the tiers like knossos.competition does for its two CPU solvers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, FrozenSet, Optional, Tuple

import numpy as np

from jepsen_tpu.history import Op

# Sentinel for "value unknown" in int32 op encodings (e.g. crashed reads).
UNKNOWN32 = -(2**31)


class Inconsistent:
    """Returned by Model.step when the op cannot be applied to this state."""

    __slots__ = ("msg",)

    def __init__(self, msg: str = ""):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent({self.msg!r})"

    def __bool__(self):  # allow `if result:` to mean "consistent"
        return False


def inconsistent(msg: str = "") -> Inconsistent:
    return Inconsistent(msg)


class Model:
    """Immutable sequential datatype specification (host tier).

    Implementations must be hashable and equality-comparable on their state
    (use frozen dataclasses), and must implement :meth:`step`.
    """

    def step(self, op: Op) -> "Model | Inconsistent":
        raise NotImplementedError

    def __eq__(self, other):  # pragma: no cover - overridden by dataclasses
        raise NotImplementedError

    def __hash__(self):  # pragma: no cover
        raise NotImplementedError


@dataclass
class JaxModel:
    """Device-tier model: pure int32 state machine.

    ``step(state, f, a, b)`` must be jax-traceable, where ``state`` is an
    int32[state_size] vector and (f, a, b) the encoded op; returns
    ``(new_state, ok)`` with ok a bool scalar.  ``encode_op`` maps an
    :class:`Op` (with completion-filled values) to ``(f, a, b)`` int32s.
    """

    name: str
    state_size: int
    init_state: np.ndarray
    step: Callable  # (state, f, a, b) -> (new_state, ok)
    encode_op: Callable[[Op], Tuple[int, int, int]]
    # Optional factory for the equivalent host-tier model (the oracle).
    cpu_model: Optional[Callable[[], Model]] = None
    # f codes that never mutate state AND always succeed when their value is
    # unknown — ops with these codes and unknown values can be dropped during
    # preprocessing (e.g. crashed reads; knossos does the same elimination).
    pure_read_fs: Tuple[int, ...] = ()
    # Engine-cache discriminator: parametrized models whose STEP SEMANTICS
    # differ while (name, state_size, init_state) coincide MUST set this
    # (e.g. multi-register's (keys, vbits) packing) — compiled engines are
    # cached by name + shape + variant, and a collision silently runs the
    # wrong step function.
    variant: Tuple = ()
    # Independence oracle for P-compositionality (engine.fission).  Given a
    # completion-filled op, returns the set of independent sub-object keys
    # the op touches or constrains.  The contract is Herlihy–Wing locality:
    # the model's state must be a product of per-key sub-states, an op may
    # only read/write the keys it reports, and a history is linearizable
    # iff every per-component projection is.  Return values:
    #   None         — the op spans the whole object (model unsplittable);
    #   frozenset()  — the op is unconstraining (always linearizable,
    #                  state-preserving; the splitter may elide it);
    #   frozenset(k) — the keys touched (ops sharing a key are grouped).
    # Models without true per-key independence (cas-register, queues) must
    # leave this None.
    components: Optional[Callable[[Op], Optional[FrozenSet]]] = None

    def init_state_array(self) -> np.ndarray:
        return np.asarray(self.init_state, np.int32).reshape(self.state_size)

    def carry_descriptor(self) -> Tuple[str, Tuple, Tuple[int, ...], str]:
        """How this model's per-configuration state rides the engine
        carry: ``(family, variant, shape, dtype)``.  Every JaxModel packs
        as a flat int32 vector of width ``state_size`` — what varies per
        family is only the width, which the megabatch bin-packer
        quantizes through ``state_width_bucket`` so queue rings, bitmask
        words, and register cells share one bounded carry-shape
        universe.  Whether a family is *routed* through megabatch is the
        separate opt-in in ``engine.plugins`` (``has_carry_descriptor``)."""
        return (self.name, self.variant, (int(self.state_size),), "int32")


# ---------------------------------------------------------------------------
# Registry — name -> JaxModel factory (mirrors how suites name knossos models,
# e.g. model/cas-register at zookeeper/src/jepsen/zookeeper.clj:132-136).
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, Callable[..., JaxModel]] = {}


def register_model(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def get_model(name: str, **kw) -> JaxModel:
    if name not in _REGISTRY:
        raise KeyError(f"unknown model {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kw)


def known_models():
    return sorted(_REGISTRY)
