"""Register models: read/write register and CAS register.

Parity targets: knossos.model/register and knossos.model/cas-register as used
by the reference's linearizable-register workloads
(jepsen/src/jepsen/tests/linearizable_register.clj:18-53,
zookeeper/src/jepsen/zookeeper.clj:132-136, consul CAS register —
consul/src/jepsen/consul/register.clj:72).

Op language:
  read  — value = observed register value (None on the invoke; filled from
          the completion by History.complete()).
  write — value = value written.
  cas   — value = [old, new]; succeeds iff register == old.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from jepsen_tpu.history import Op
from jepsen_tpu.models.base import (
    UNKNOWN32, Inconsistent, JaxModel, Model, inconsistent, register_model,
)

F_READ, F_WRITE, F_CAS = 0, 1, 2
F_NAMES = {"read": F_READ, "r": F_READ,
           "write": F_WRITE, "w": F_WRITE,
           "cas": F_CAS}

# Initial register value.  The reference's cas-register starts nil; we encode
# nil as UNKNOWN32+1 (distinct from the unknown-value sentinel).
NIL32 = UNKNOWN32 + 1


# -- host tier --------------------------------------------------------------

@dataclass(frozen=True)
class CASRegister(Model):
    value: Any = None

    def step(self, op: Op):
        f = op.f
        if f in ("read", "r"):
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"can't read {op.value!r} from {self.value!r}")
        if f in ("write", "w"):
            return CASRegister(op.value)
        if f == "cas":
            old, new = op.value
            if self.value == old:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {old!r}")
        return inconsistent(f"unknown f {f!r}")


@dataclass(frozen=True)
class RWRegister(Model):
    """Read/write register (no CAS)."""

    value: Any = None

    def step(self, op: Op):
        f = op.f
        if f in ("read", "r"):
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(f"can't read {op.value!r} from {self.value!r}")
        if f in ("write", "w"):
            return RWRegister(op.value)
        return inconsistent(f"unknown f {f!r}")


# -- device tier ------------------------------------------------------------

def _encode_register_op(op: Op):
    f = F_NAMES.get(op.f)
    if f is None:
        raise ValueError(f"register models can't encode f={op.f!r}")
    v = op.value
    if f == F_CAS:
        old, new = v
        return f, int(old), int(new)
    if v is None:
        return f, UNKNOWN32, 0
    return f, int(v), 0


def _cas_step(state, f, a, b):
    """state: int32[1]; returns (new_state, ok)."""
    v = state[0]
    is_read = f == F_READ
    is_write = f == F_WRITE
    is_cas = f == F_CAS
    read_ok = (a == UNKNOWN32) | (a == v)
    cas_ok = v == a
    ok = jnp.where(is_read, read_ok, jnp.where(is_cas, cas_ok, is_write))
    new_v = jnp.where(is_write, a, jnp.where(is_cas & cas_ok, b, v))
    return jnp.where(ok, new_v, v)[None], ok


@register_model("cas-register")
def cas_register_jax(init: Optional[int] = None) -> JaxModel:
    init32 = NIL32 if init is None else int(init)
    return JaxModel(
        name="cas-register",
        state_size=1,
        init_state=np.array([init32], np.int32),
        step=_cas_step,
        encode_op=_encode_register_op,
        cpu_model=lambda: CASRegister(init),
        pure_read_fs=(F_READ,),
    )


@register_model("register")
def rw_register_jax(init: Optional[int] = None) -> JaxModel:
    init32 = NIL32 if init is None else int(init)

    def step(state, f, a, b):
        v = state[0]
        is_read = f == F_READ
        is_write = f == F_WRITE
        read_ok = (a == UNKNOWN32) | (a == v)
        ok = jnp.where(is_read, read_ok, is_write)
        new_v = jnp.where(is_write, a, v)
        return jnp.where(ok, new_v, v)[None], ok

    return JaxModel(
        name="register",
        state_size=1,
        init_state=np.array([init32], np.int32),
        step=step,
        encode_op=_encode_register_op,
        cpu_model=lambda: RWRegister(init),
        pure_read_fs=(F_READ,),
    )
