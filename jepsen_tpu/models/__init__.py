"""Sequential datatype models for linearizability checking.

Host-tier models (:class:`~jepsen_tpu.models.base.Model`) are arbitrary
immutable Python objects; device-tier models
(:class:`~jepsen_tpu.models.base.JaxModel`) are pure int32 state machines the
TPU engine vmaps over configuration frontiers.  ``get_model(name)`` looks up
registered device-tier models by the same names the reference's suites use
for knossos models.
"""

from jepsen_tpu.models.base import (  # noqa: F401
    Inconsistent, JaxModel, Model, UNKNOWN32,
    get_model, inconsistent, known_models, register_model,
)
from jepsen_tpu.models.register import (  # noqa: F401
    CASRegister, RWRegister, cas_register_jax, rw_register_jax,
)
from jepsen_tpu.models.collections import (  # noqa: F401
    BitSetModel, FIFOQueue, MultiRegister, Mutex, SET_DOMAIN, SetModel,
    TxnRegister, UnorderedQueue, fifo_queue_jax, set_jax, txn_register_jax,
)
from jepsen_tpu.models.locks import (  # noqa: F401
    AcquiredPermits, FencedMutex, OwnerAwareMutex, ReentrantFencedMutex,
    ReentrantMutex,
)
