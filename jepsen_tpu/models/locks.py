"""Distributed-lock model family (host tier).

Parity: the hazelcast suite's checker models
(hazelcast/src/jepsen/hazelcast.clj:511-651): reentrant, owner-aware,
fenced, and reentrant-fenced mutexes plus the multi-permit semaphore.
Op values are dicts {"client": name, "fence": int} (the reference routes
client UUIDs through a uid->name map; here clients stamp their name into
the op value directly).  Fence 0 is "no fence observed"
(hazelcast.clj:55 invalid-fence).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from jepsen_tpu.history import Op
from jepsen_tpu.models.base import Model, inconsistent, register_model

INVALID_FENCE = 0
REENTRANT_ACQUIRE_CAP = 2  # hazelcast.clj:53
NUM_PERMITS = 2            # hazelcast.clj:54


def op_client(op: Op) -> Optional[str]:
    v = op.value
    if isinstance(v, dict):
        return v.get("client")
    return v if isinstance(v, str) else None


def op_fence(op: Op) -> int:
    v = op.value
    if isinstance(v, dict):
        return v.get("fence") or INVALID_FENCE
    return INVALID_FENCE


@dataclass(frozen=True)
class OwnerAwareMutex(Model):
    """Non-reentrant mutex that knows who holds it
    (hazelcast.clj:538-559)."""

    owner: Optional[str] = None

    def step(self, op: Op):
        client = op_client(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.owner is None:
                return OwnerAwareMutex(client)
            return inconsistent(f"{client} cannot acquire: {self}")
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(f"{client} cannot release: {self}")
            return OwnerAwareMutex(None)
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class ReentrantMutex(Model):
    """Mutex re-acquirable up to a cap by its owner
    (hazelcast.clj:515-535)."""

    owner: Optional[str] = None
    lock_count: int = 0

    def step(self, op: Op):
        client = op_client(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.lock_count < REENTRANT_ACQUIRE_CAP and \
                    (self.owner is None or self.owner == client):
                return ReentrantMutex(client, self.lock_count + 1)
            return inconsistent(f"{client} cannot acquire: {self}")
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(f"{client} cannot release: {self}")
            return ReentrantMutex(None if self.lock_count == 1
                                  else self.owner, self.lock_count - 1)
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class FencedMutex(Model):
    """Mutex whose acquires carry monotonically-increasing fencing tokens
    (hazelcast.clj:565-588)."""

    owner: Optional[str] = None
    lock_fence: int = INVALID_FENCE

    def step(self, op: Op):
        client = op_client(op)
        fence = op_fence(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.owner is not None:
                return inconsistent(f"{client} cannot acquire: {self}")
            if fence == INVALID_FENCE:
                return FencedMutex(client, self.lock_fence)
            if fence > self.lock_fence:
                return FencedMutex(client, fence)
            return inconsistent(
                f"{client} fence {fence} not above {self.lock_fence}")
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(f"{client} cannot release: {self}")
            return FencedMutex(None, self.lock_fence)
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class ReentrantFencedMutex(Model):
    """Reentrant fenced mutex tracking the highest observed fence
    (hazelcast.clj:590-628)."""

    owner: Optional[str] = None
    lock_count: int = 0
    current_fence: int = INVALID_FENCE
    highest_fence: int = INVALID_FENCE

    def step(self, op: Op):
        client = op_client(op)
        fence = op_fence(op)
        if client is None:
            return inconsistent("no owner!")
        if op.f == "acquire":
            if self.owner is None:
                if fence == INVALID_FENCE or fence > self.highest_fence:
                    return ReentrantFencedMutex(
                        client, 1, fence, max(fence, self.highest_fence))
                return inconsistent(
                    f"{client} fence {fence} not above "
                    f"{self.highest_fence}")
            if self.owner != client or \
                    self.lock_count == REENTRANT_ACQUIRE_CAP:
                return inconsistent(f"{client} cannot acquire: {self}")
            if self.current_fence == INVALID_FENCE:
                if fence == INVALID_FENCE or fence > self.highest_fence:
                    return ReentrantFencedMutex(
                        client, self.lock_count + 1, fence,
                        max(fence, self.highest_fence))
                return inconsistent(f"{client} cannot reacquire: {self}")
            if fence == INVALID_FENCE or fence == self.current_fence:
                return ReentrantFencedMutex(
                    client, self.lock_count + 1, self.current_fence,
                    self.highest_fence)
            return inconsistent(f"{client} cannot reacquire: {self}")
        if op.f == "release":
            if self.owner is None or self.owner != client:
                return inconsistent(f"{client} cannot release: {self}")
            if self.lock_count == 1:
                return ReentrantFencedMutex(None, 0, INVALID_FENCE,
                                            self.highest_fence)
            return ReentrantFencedMutex(self.owner, self.lock_count - 1,
                                        self.current_fence,
                                        self.highest_fence)
        return inconsistent(f"unknown f {op.f!r}")


@dataclass(frozen=True)
class AcquiredPermits(Model):
    """Semaphore with a bounded permit pool, tracked per client
    (hazelcast.clj:630-651)."""

    acquired: Tuple[Tuple[str, int], ...] = ()
    permits: int = NUM_PERMITS

    def _get(self, client: str) -> int:
        return dict(self.acquired).get(client, 0)

    def _with(self, client: str, n: int) -> "AcquiredPermits":
        d = dict(self.acquired)
        d[client] = n
        return AcquiredPermits(tuple(sorted(d.items())), self.permits)

    def step(self, op: Op):
        client = op_client(op)
        if client is None:
            return inconsistent("no owner!")
        total = sum(dict(self.acquired).values())
        if op.f == "acquire":
            if total < self.permits:
                return self._with(client, self._get(client) + 1)
            return inconsistent(f"{client} cannot acquire: {self}")
        if op.f == "release":
            if self._get(client) > 0:
                return self._with(client, self._get(client) - 1)
            return inconsistent(f"{client} cannot release: {self}")
        return inconsistent(f"unknown f {op.f!r}")


register_model("owner-aware-mutex")(lambda: OwnerAwareMutex())
register_model("reentrant-mutex")(lambda: ReentrantMutex())
register_model("fenced-mutex")(lambda: FencedMutex())
register_model("reentrant-fenced-mutex")(lambda: ReentrantFencedMutex())
register_model("acquired-permits")(lambda: AcquiredPermits())
