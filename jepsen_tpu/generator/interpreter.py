"""The interpreter: folds a generator into a history using real threads.

Parity: jepsen.generator.interpreter (interpreter.clj:181-313).  A
single-threaded scheduler loop owns the generator and the context; one
worker thread per client thread (plus the nemesis) performs invocations.
Key semantics carried over exactly:

- all generator computation happens in the scheduler loop; workers only
  run client/nemesis invoke;
- a worker exception converts the op into an ``info`` completion with the
  error attached (interpreter.clj:142-157) — indeterminate, not failed;
- a crashed client process is burned: its thread gets a fresh process id
  (p + concurrency) and a fresh client, unless the client is Reusable
  (interpreter.clj:33-67, 234-239);
- :pending polls with a bounded (1 ms) backoff (interpreter.clj:166-170);
- ops scheduled in the future are dispatched no earlier than their time.

Fault tolerance (this layer must survive the faults it injects):

- **Per-op deadlines** — ``test["op_timeout_s"]`` (a number, or a dict of
  f -> seconds with a ``"default"`` key) bounds each invocation's wall
  clock.  A hung ``invoke`` cannot be interrupted in Python, so the
  scheduler *abandons* it: the op completes as ``info`` with a
  ``:timeout`` error (indeterminate — it may still take effect, exactly
  like a crash, interpreter.clj:142-157), the worker thread is replaced by
  a fresh one at a new epoch, and the process is burned.  The abandoned
  worker's late completion, if it ever arrives, is recognized by its stale
  epoch and dropped — each logical op completes exactly once.
- **Scheduler watchdog** — ``test["watchdog_s"]`` (default 300; None/0
  disables) bounds how long the run may sit with outstanding ops and zero
  progress.  Threads whose ops carry their own deadline are exempt (the
  deadline will fire first); if an op *without* a deadline wedges past the
  watchdog, the run fails loudly with :class:`StalledRun` naming the stuck
  ops, instead of blocking its worker thread forever.
"""

from __future__ import annotations

import logging
import queue
import threading
import time as _time
from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, INFO, INVOKE, NEMESIS, Op

logger = logging.getLogger("jepsen.interpreter")

_STOP = object()
MAX_PENDING_WAIT_S = 0.001  # 1 ms, like the reference's poll granularity
DEFAULT_WATCHDOG_S = 300.0
TIMEOUT_ERROR = ":timeout"


class StalledRun(RuntimeError):
    """The completion queue stalled: outstanding ops without deadlines made
    no progress for the watchdog interval.  Carries the stuck invocations
    so the failure names the wedged processes instead of wedging the run."""

    def __init__(self, stalled_s: float, ops: List[Op]):
        self.stalled_s = stalled_s
        self.ops = list(ops)
        super().__init__(
            f"scheduler stalled: no completion for {stalled_s:.1f}s with "
            f"{len(ops)} outstanding op(s): "
            + ", ".join(f"{o.process}/{o.f}" for o in self.ops))


class _Worker(threading.Thread):
    """Base worker: pulls ops from its queue, pushes completions to the
    shared completion queue.  ``epoch`` stamps every completion so the
    scheduler can drop output from workers it has already abandoned."""

    def __init__(self, thread_id, test, completions, epoch: int = 0):
        super().__init__(name=f"jepsen-worker-{thread_id}.{epoch}",
                         daemon=True)
        self.thread_id = thread_id
        self.test = test
        self.epoch = epoch
        self.inbox: "queue.Queue" = queue.Queue()
        self.completions = completions

    def run(self):
        while True:
            item = self.inbox.get()
            if item is _STOP:
                self._shutdown()
                return
            op: Op = item
            try:
                res = self._invoke(op)
                if res.type == INVOKE:
                    raise RuntimeError(
                        f"invoke returned an :invoke op: {res!r}")
            except Exception as e:  # noqa: BLE001 - crash => indeterminate
                logger.warning("process %s crashed in %s: %s",
                               op.process, op.f, e)
                res = op.with_(type=INFO, error=str(e) or type(e).__name__)
            self.completions.put((self.thread_id, self.epoch, res))

    def _invoke(self, op: Op) -> Op:
        raise NotImplementedError

    def _shutdown(self):
        pass


class ClientWorker(_Worker):
    """Owns the client lifecycle for its thread's current process
    (interpreter.clj:33-67)."""

    def __init__(self, thread_id, test, completions, client_proto,
                 epoch: int = 0):
        super().__init__(thread_id, test, completions, epoch)
        self.client_proto = client_proto
        self.client: Optional[jclient.Client] = None
        self.process = None

    def _node_for(self, process) -> Optional[str]:
        nodes = self.test.get("nodes") or []
        if not nodes:
            return None
        return nodes[process % len(nodes)]

    def _invoke(self, op: Op) -> Op:
        if self.process != op.process or self.client is None:
            # Fresh process: open a client for it (unless reusable).
            if self.client is not None and not self.client.reusable:
                try:
                    self.client.close(self.test)
                except Exception:  # noqa: BLE001
                    logger.exception("closing crashed client")
                self.client = None
            if self.client is None:
                self.client = self.client_proto.open(
                    self.test, self._node_for(op.process))
            self.process = op.process
        return self.client.invoke(self.test, op)

    def _shutdown(self):
        if self.client is not None:
            try:
                self.client.close(self.test)
            except Exception:  # noqa: BLE001
                logger.exception("closing client at shutdown")


class NemesisWorker(_Worker):
    """The nemesis runs on its own logical thread (interpreter.clj:69)."""

    def __init__(self, test, completions, nemesis, epoch: int = 0):
        super().__init__(NEMESIS, test, completions, epoch)
        self.nemesis = nemesis

    def _invoke(self, op: Op) -> Op:
        return self.nemesis.invoke(self.test, op)


def _op_timeout_s(test: Dict[str, Any], op: Op) -> Optional[float]:
    """The per-op wall-clock budget, or None for unbounded."""
    spec = test.get("op_timeout_s")
    if spec is None:
        return None
    if isinstance(spec, dict):
        t = spec.get(op.f, spec.get("default"))
    else:
        t = spec
    return None if t is None else float(t)


def run(test: Dict[str, Any]) -> History:
    """Run test["generator"] against test["client"] / test["nemesis"],
    returning the complete history.  In-process; no cluster required."""
    g = gen.validate(gen.lift(test.get("generator")))
    client_proto = test.get("client") or jclient.NoopClient()
    nemesis = test.get("nemesis")
    if nemesis is None:
        from jepsen_tpu import nemesis as jnemesis
        nemesis = jnemesis.NoopNemesis()

    # completion entries: (thread_id, worker_epoch, op)
    ctx = gen.context(test)
    completions: "queue.Queue" = queue.Queue()
    workers: Dict[Any, _Worker] = {}
    epochs: Dict[Any, int] = {}

    def spawn(thread_id, epoch: int = 0) -> _Worker:
        if thread_id == NEMESIS:
            w = NemesisWorker(test, completions, nemesis, epoch)
        else:
            w = ClientWorker(thread_id, test, completions, client_proto,
                             epoch)
        workers[thread_id] = w
        epochs[thread_id] = epoch
        w.start()
        return w

    for t in ctx.all_threads():
        spawn(t)

    history: List[Op] = []
    outstanding = 0
    inflight: Dict[Any, Op] = {}        # thread -> dispatched, uncompleted op
    deadlines: Dict[Any, float] = {}    # thread -> monotonic deadline
    watchdog_s = test.get("watchdog_s", DEFAULT_WATCHDOG_S) or None
    last_progress = _time.monotonic()
    t0 = _time.monotonic_ns()

    def now() -> int:
        return _time.monotonic_ns() - t0

    # Online monitor (jepsen_tpu.monitor): core.run parks it on the test
    # map; the tap never blocks this loop.
    mon = test.get("_monitor")

    def handle_completion(thread_id, res: Op):
        nonlocal ctx, g, outstanding, last_progress
        outstanding -= 1
        inflight.pop(thread_id, None)
        deadlines.pop(thread_id, None)
        last_progress = _time.monotonic()
        res = res.with_(time=now(), index=len(history))
        history.append(res)
        if mon is not None:
            mon.offer(res)
        ctx = ctx.with_time(res.time).free_thread(thread_id)
        if res.type == INFO and thread_id != NEMESIS:
            ctx = ctx.with_next_process(thread_id)
        if g is not None:
            g = g.update(test, ctx, res)

    def take(item) -> bool:
        """Apply one queue entry; False if it came from a burned worker
        (stale epoch) and was dropped."""
        thread_id, epoch, res = item
        if epochs.get(thread_id) != epoch:
            logger.info("dropping late completion from abandoned worker "
                        "%s (epoch %d): %s", thread_id, epoch, res)
            return False
        handle_completion(thread_id, res)
        return True

    def fire_deadlines() -> bool:
        """Abandon every worker whose op blew its deadline: synthesize the
        ``info :timeout`` completion, burn the process, replace the worker
        at a fresh epoch (the hung thread's late output is dropped by
        ``take``).  True if anything fired."""
        now_m = _time.monotonic()
        fired = False
        for thread_id in [t for t, dl in list(deadlines.items())
                          if dl <= now_m]:
            op = inflight[thread_id]
            logger.warning(
                "op exceeded its %ss deadline; abandoning worker %s and "
                "completing as info: %s/%s",
                _op_timeout_s(test, op), thread_id, op.process, op.f)
            old = workers[thread_id]
            old.inbox.put(_STOP)  # if it ever unwedges, it exits
            spawn(thread_id, epochs[thread_id] + 1)
            handle_completion(thread_id, op.with_(type=INFO,
                                                  error=TIMEOUT_ERROR))
            fired = True
        return fired

    def check_watchdog() -> None:
        if not watchdog_s or not outstanding:
            return
        stalled = _time.monotonic() - last_progress
        if stalled < watchdog_s:
            return
        # Ops with their own deadline are the deadline's problem.
        stuck = [inflight[t] for t in inflight if t not in deadlines]
        if stuck:
            raise StalledRun(stalled, stuck)

    def bounded(want: Optional[float]) -> Optional[float]:
        """Cap a queue wait so the scheduler wakes for the nearest op
        deadline and the watchdog — it must never block past either."""
        limit = want
        now_m = _time.monotonic()
        if deadlines:
            d = min(deadlines.values()) - now_m
            limit = d if limit is None else min(limit, d)
        if watchdog_s and outstanding:
            d = (last_progress + watchdog_s) - now_m
            limit = d if limit is None else min(limit, d)
        return None if limit is None else max(0.0, limit)

    def wait_completion(want: Optional[float]) -> bool:
        """Block up to ``want`` (None = until deadline/watchdog) for one
        completion; fire deadlines/watchdog on timeout.  True if the
        context changed (a completion was applied or a deadline fired)."""
        try:
            item = completions.get(timeout=bounded(want))
        except queue.Empty:
            if fire_deadlines():
                return True
            check_watchdog()
            return False
        return take(item)

    try:
        while True:
            # 1. Drain any ready completions.
            drained = False
            while True:
                try:
                    drained = take(completions.get_nowait()) or drained
                except queue.Empty:
                    break
            if fire_deadlines():
                drained = True
            if drained:
                continue
            check_watchdog()
            # 2. Ask the generator — unless the monitor refuted the run
            # and the test opted into early abort: cut the generator,
            # let outstanding ops drain, and the loop exits normally.
            if g is not None and mon is not None and mon.should_abort():
                logger.warning("monitor refuted the run; aborting the "
                               "generator with %d op(s) outstanding",
                               outstanding)
                test["monitor_aborted"] = True
                g = None
            ctx = ctx.with_time(now())
            r = g.op(test, ctx) if g is not None else None
            if r is None:
                if outstanding == 0:
                    break
                wait_completion(None)
                continue
            v, g2 = r
            if v == gen.PENDING:
                g = g2
                wait_completion(MAX_PENDING_WAIT_S)
                continue
            op: Op = v
            if op.time is not None and op.time > ctx.time:
                # Scheduled in the future: wait, staying responsive.
                wait = (op.time - ctx.time) / 1e9
                if wait_completion(wait):
                    continue  # context changed; re-ask the generator
                if _time.monotonic_ns() - t0 < op.time:
                    continue  # woken early (bounded wait); not due yet
            if op.type == "log":
                logger.info("%s", op.value)
                g = g2
                continue
            op = op.with_(time=now(), index=len(history))
            thread_id = ctx.process_thread(op.process)
            history.append(op)
            if mon is not None:
                mon.offer(op)
            ctx = ctx.busy_thread(thread_id)
            g = g2.update(test, ctx, op) if g2 is not None else None
            outstanding += 1
            inflight[thread_id] = op
            timeout_s = _op_timeout_s(test, op)
            if timeout_s is not None:
                deadlines[thread_id] = _time.monotonic() + timeout_s
            last_progress = _time.monotonic()
            workers[thread_id].inbox.put(op)
    except StalledRun:
        # Fail loudly, but leave a usable partial history behind for
        # whoever catches this (core.run stores what it got).
        test["partial_history"] = History(history, reindex=True)
        raise
    finally:
        for w in workers.values():
            w.inbox.put(_STOP)
        for w in workers.values():
            w.join(timeout=5)

    return History(history, reindex=True)
