"""The interpreter: folds a generator into a history using real threads.

Parity: jepsen.generator.interpreter (interpreter.clj:181-313).  A
single-threaded scheduler loop owns the generator and the context; one
worker thread per client thread (plus the nemesis) performs invocations.
Key semantics carried over exactly:

- all generator computation happens in the scheduler loop; workers only
  run client/nemesis invoke;
- a worker exception converts the op into an ``info`` completion with the
  error attached (interpreter.clj:142-157) — indeterminate, not failed;
- a crashed client process is burned: its thread gets a fresh process id
  (p + concurrency) and a fresh client, unless the client is Reusable
  (interpreter.clj:33-67, 234-239);
- :pending polls with a bounded (1 ms) backoff (interpreter.clj:166-170);
- ops scheduled in the future are dispatched no earlier than their time.
"""

from __future__ import annotations

import logging
import queue
import threading
import time as _time
from typing import Any, Dict, List, Optional

from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, INFO, INVOKE, NEMESIS, Op

logger = logging.getLogger("jepsen.interpreter")

_STOP = object()
MAX_PENDING_WAIT_S = 0.001  # 1 ms, like the reference's poll granularity


class _Worker(threading.Thread):
    """Base worker: pulls ops from its queue, pushes completions to the
    shared completion queue."""

    def __init__(self, thread_id, test, completions):
        super().__init__(name=f"jepsen-worker-{thread_id}", daemon=True)
        self.thread_id = thread_id
        self.test = test
        self.inbox: "queue.Queue" = queue.Queue()
        self.completions = completions

    def run(self):
        while True:
            item = self.inbox.get()
            if item is _STOP:
                self._shutdown()
                return
            op: Op = item
            try:
                res = self._invoke(op)
                if res.type == INVOKE:
                    raise RuntimeError(
                        f"invoke returned an :invoke op: {res!r}")
            except Exception as e:  # noqa: BLE001 - crash => indeterminate
                logger.warning("process %s crashed in %s: %s",
                               op.process, op.f, e)
                res = op.with_(type=INFO, error=str(e) or type(e).__name__)
            self.completions.put((self.thread_id, res))

    def _invoke(self, op: Op) -> Op:
        raise NotImplementedError

    def _shutdown(self):
        pass


class ClientWorker(_Worker):
    """Owns the client lifecycle for its thread's current process
    (interpreter.clj:33-67)."""

    def __init__(self, thread_id, test, completions, client_proto):
        super().__init__(thread_id, test, completions)
        self.client_proto = client_proto
        self.client: Optional[jclient.Client] = None
        self.process = None

    def _node_for(self, process) -> Optional[str]:
        nodes = self.test.get("nodes") or []
        if not nodes:
            return None
        return nodes[process % len(nodes)]

    def _invoke(self, op: Op) -> Op:
        if self.process != op.process or self.client is None:
            # Fresh process: open a client for it (unless reusable).
            if self.client is not None and not self.client.reusable:
                try:
                    self.client.close(self.test)
                except Exception:  # noqa: BLE001
                    logger.exception("closing crashed client")
                self.client = None
            if self.client is None:
                self.client = self.client_proto.open(
                    self.test, self._node_for(op.process))
            self.process = op.process
        return self.client.invoke(self.test, op)

    def _shutdown(self):
        if self.client is not None:
            try:
                self.client.close(self.test)
            except Exception:  # noqa: BLE001
                logger.exception("closing client at shutdown")


class NemesisWorker(_Worker):
    """The nemesis runs on its own logical thread (interpreter.clj:69)."""

    def __init__(self, test, completions, nemesis):
        super().__init__(NEMESIS, test, completions)
        self.nemesis = nemesis

    def _invoke(self, op: Op) -> Op:
        return self.nemesis.invoke(self.test, op)


def run(test: Dict[str, Any]) -> History:
    """Run test["generator"] against test["client"] / test["nemesis"],
    returning the complete history.  In-process; no cluster required."""
    g = gen.validate(gen.lift(test.get("generator")))
    client_proto = test.get("client") or jclient.NoopClient()
    nemesis = test.get("nemesis")
    if nemesis is None:
        from jepsen_tpu import nemesis as jnemesis
        nemesis = jnemesis.NoopNemesis()

    ctx = gen.context(test)
    completions: "queue.Queue" = queue.Queue()
    workers: Dict[Any, _Worker] = {}
    for t in ctx.all_threads():
        if t == NEMESIS:
            workers[t] = NemesisWorker(test, completions, nemesis)
        else:
            workers[t] = ClientWorker(t, test, completions, client_proto)
        workers[t].start()

    history: List[Op] = []
    outstanding = 0
    t0 = _time.monotonic_ns()

    def now() -> int:
        return _time.monotonic_ns() - t0

    def handle_completion(item):
        nonlocal ctx, g, outstanding
        thread_id, res = item
        outstanding -= 1
        res = res.with_(time=now(), index=len(history))
        history.append(res)
        ctx = ctx.with_time(res.time).free_thread(thread_id)
        if res.type == INFO and thread_id != NEMESIS:
            ctx = ctx.with_next_process(thread_id)
        if g is not None:
            g = g.update(test, ctx, res)

    try:
        while True:
            # 1. Drain any ready completions.
            drained = False
            while True:
                try:
                    handle_completion(completions.get_nowait())
                    drained = True
                except queue.Empty:
                    break
            if drained:
                continue
            # 2. Ask the generator.
            ctx = ctx.with_time(now())
            r = g.op(test, ctx) if g is not None else None
            if r is None:
                if outstanding == 0:
                    break
                handle_completion(completions.get())
                continue
            v, g2 = r
            if v == gen.PENDING:
                g = g2
                try:
                    handle_completion(
                        completions.get(timeout=MAX_PENDING_WAIT_S))
                except queue.Empty:
                    pass
                continue
            op: Op = v
            if op.time is not None and op.time > ctx.time:
                # Scheduled in the future: wait, staying responsive.
                wait = (op.time - ctx.time) / 1e9
                try:
                    handle_completion(completions.get(timeout=wait))
                    continue  # context changed; re-ask the generator
                except queue.Empty:
                    pass
            if op.type == "log":
                logger.info("%s", op.value)
                g = g2
                continue
            op = op.with_(time=now(), index=len(history))
            thread_id = ctx.process_thread(op.process)
            history.append(op)
            ctx = ctx.busy_thread(thread_id)
            g = g2.update(test, ctx, op) if g2 is not None else None
            outstanding += 1
            workers[thread_id].inbox.put(op)
    finally:
        for w in workers.values():
            w.inbox.put(_STOP)
        for w in workers.values():
            w.join(timeout=5)

    return History(history, reindex=True)
