"""Deterministic generator simulation — no threads, fixed seed, fake clock.

Parity: jepsen.generator.test/simulate (generator/test.clj:28-60): fold a
generator into a history by simulating op dispatch and completion with a
pluggable latency model, advancing a synthetic nanosecond clock.  This is
both the unit-test harness for every combinator and the performance harness
for scheduler throughput (the reference claims >20k ops/s,
generator.clj:67-70).
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Dict, List, Optional, Tuple

from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, INVOKE, NEMESIS, OK, Op

DEFAULT_SEED = 45100  # mirrors the reference's fixed seed choice


def perfect_latency(op: Op) -> Tuple[int, str]:
    """Completion model: 10 ms latency, always ok."""
    return 10_000_000, OK


def instant(op: Op) -> Tuple[int, str]:
    return 0, OK


def simulate(test: Dict[str, Any],
             g,
             complete_fn: Callable[[Op], Optional[Tuple[int, str]]] = perfect_latency,
             seed: int = DEFAULT_SEED,
             max_ops: int = 100_000) -> History:
    """Run generator ``g`` to exhaustion against a simulated executor.

    ``complete_fn(op) -> (latency_ns, completion_type) | None`` decides how
    invocations complete (None = never, like a crashed op).  Returns the full
    invoke/completion history with times from the synthetic clock.
    """
    gen.seed(seed)
    g = gen.validate(gen.lift(g))
    ctx = gen.context(test)
    history: List[Op] = []
    # pending completions: (completion_time, seq, completion_op, thread)
    pq: List[Tuple[int, int, Op, Any]] = []
    seqno = 0

    while len(history) < max_ops:
        r = g.op(test, ctx) if g is not None else None
        if r is None:
            if not pq:
                break
            ctx, g = _drain_one(test, g, ctx, pq, history)
            continue
        v, g2 = r
        if v == gen.PENDING:
            if pq:
                ctx, g2 = _drain_one(test, g2, ctx, pq, history)
            else:
                ctx = ctx.with_time(ctx.time + 1_000_000)  # 1ms poll tick
            g = g2
            continue
        # Dispatchable op: future ops first complete earlier events.
        if pq and pq[0][0] <= v.time:
            ctx, g = _drain_one(test, g, ctx, pq, history)
            continue
        op = v.with_(index=len(history))
        t = max(ctx.time, op.time or 0)
        op = op.with_(time=t)
        ctx = ctx.with_time(t)
        if op.type == "log":
            history.append(op)
            g = g2
            continue
        thread = ctx.process_thread(op.process)
        ctx = ctx.busy_thread(thread)
        history.append(op)
        g = g2.update(test, ctx, op) if g2 is not None else None
        comp = complete_fn(op)
        if comp is not None:
            latency, ctype = comp
            cop = op.with_(type=ctype, time=op.time + latency)
            seqno += 1
            heapq.heappush(pq, (op.time + latency, seqno, cop, thread))

    # drain remaining completions
    while pq:
        ctx, g = _drain_one(test, g, ctx, pq, history)
    return History(history, reindex=True)


def _drain_one(test, g, ctx, pq, history):
    t, _, cop, thread = heapq.heappop(pq)
    ctx = ctx.with_time(max(ctx.time, t))
    cop = cop.with_(index=len(history))
    history.append(cop)
    ctx = ctx.free_thread(thread)
    if cop.type == "info" and thread != NEMESIS:
        ctx = ctx.with_next_process(thread)
    if g is not None:
        g = g.update(test, ctx, cop)
    return ctx, g


def quick(g, concurrency: int = 2, **kw) -> History:
    return simulate({"concurrency": concurrency}, g, **kw)


def ops_of(h: History, type_: str = INVOKE) -> List[Op]:
    return [o for o in h if o.type == type_]
