"""Pure-functional operation scheduler — the generator DSL.

Parity target: the reference's generator system
(jepsen/src/jepsen/generator.clj): a *generator* is an immutable value that,
given the test and a scheduling *context*, either yields an operation (plus
its successor generator), declares itself :pending (nothing to do yet), or is
exhausted; and is *updated* with every history event so it can react to
completions.  The interpreter (jepsen_tpu.generator.interpreter) folds a
generator into a history.

Protocol (generator.clj:382-390):
    gen.op(test, ctx)        -> None | (op, gen') | (PENDING, gen')
    gen.update(test, ctx, ev) -> gen'

Lifting (generator.clj:326-371): plain dicts/Ops are one-shot generators;
callables are infinite streams of whatever they return (exhausted on None);
lists/tuples are sequential concatenation.

All combinators of the reference exist here with the same semantics:
mix, stagger, time_limit, limit, once, repeat, cycle, phases, then, any,
each_thread, reserve, clients, nemesis, on_threads, f_map, map, filter,
on_update, synchronize, sleep, delay, log, trace, until_ok, flip_flop,
process_limit, concurrency_limit, cycle_times, validate.

Randomness flows through a module RNG so the deterministic simulation
harness (testkit.py, mirroring jepsen.generator.test/simulate) can seed it.
"""

from __future__ import annotations

import math
import random as _random_mod
from dataclasses import dataclass, field, replace
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Tuple, Union)

from jepsen_tpu.history import INVOKE, NEMESIS, OK, Op

PENDING = "pending"

# Module RNG: seedable for deterministic simulation (the reference pins
# rand-int via with-fixed-rand-int, generator/test.clj:32-48).
RNG = _random_mod.Random()


def seed(n: int) -> None:
    RNG.seed(n)


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Context:
    """Scheduling context (generator.clj:453-530): logical time (ns), the set
    of free threads, and the thread->process map (processes migrate to fresh
    ids when they crash; threads are fixed)."""

    time: int
    free_threads: frozenset
    workers: Tuple[Tuple[Any, Any], ...]  # ((thread, process), ...)

    # -- derived (cached per immutable context; caches are dropped by
    # _clone so functional updates can't serve stale views) ---------------
    def worker_map(self) -> Dict[Any, Any]:
        wm = self.__dict__.get("_wm")
        if wm is None:
            wm = self.__dict__["_wm"] = dict(self.workers)
        return wm

    def _clone(self, **kw) -> "Context":
        new = object.__new__(Context)
        d = new.__dict__
        d["time"] = self.time
        d["free_threads"] = self.free_threads
        d["workers"] = self.workers
        d.update(kw)
        return new

    def all_threads(self) -> List[Any]:
        return [t for t, _ in self.workers]

    def thread_process(self, thread) -> Any:
        return self.worker_map()[thread]

    def process_thread(self, process) -> Any:
        pm = self.__dict__.get("_pm")
        if pm is None:
            pm = self.__dict__["_pm"] = {p: t for t, p in self.workers}
        return pm.get(process)

    def free_processes(self) -> List[Any]:
        wm = self.worker_map()
        return [wm[t] for t in self.sorted_free_threads()]

    def sorted_free_threads(self) -> List[Any]:
        sf = self.__dict__.get("_sfree")
        if sf is None:
            sf = self.__dict__["_sfree"] = sorted(self.free_threads,
                                                  key=_thread_key)
        return sf

    def some_free_process(self) -> Optional[Any]:
        """A uniformly random free process (fair scheduling; the reference
        uses a Bifurcan set for O(1) random nth, generator.clj:437-451).

        Client threads are preferred; the nemesis only receives ops when the
        context is restricted to it (via the nemesis() wrapper) — unwrapped
        workload generators never land on the nemesis thread."""
        pool = self.__dict__.get("_pool")
        if pool is None:
            free = self.sorted_free_threads()
            if any(t != NEMESIS for t, _ in self.workers):
                pool = [t for t in free if t != NEMESIS]
            else:
                pool = free
            self.__dict__["_pool"] = pool
        if not pool:
            return None
        return self.worker_map()[RNG.choice(pool)]

    # -- functional updates ----------------------------------------------
    def with_time(self, time: int) -> "Context":
        # keeps free_threads/workers: caches may be rebuilt but stay valid
        new = self._clone(time=time)
        for k in ("_wm", "_sfree", "_pool", "_pm"):
            if k in self.__dict__:
                new.__dict__[k] = self.__dict__[k]
        return new

    def busy_thread(self, thread) -> "Context":
        return self._clone(free_threads=self.free_threads - {thread})

    def free_thread(self, thread) -> "Context":
        return self._clone(free_threads=self.free_threads | {thread})

    def with_next_process(self, thread) -> "Context":
        """Replace thread's process with its next incarnation (crashed
        process semantics: p' = p + (#client threads), generator.clj:519-529)."""
        n = len([t for t, _ in self.workers if t != NEMESIS])
        wm = dict(self.worker_map())  # never mutate the shared cache
        p = wm[thread]
        wm[thread] = p + n if isinstance(p, int) else p
        return replace(self, workers=tuple(sorted(wm.items(), key=lambda kv: _thread_key(kv[0]))))

    def restrict(self, threads) -> "Context":
        """Sub-context visible to a generator bound to `threads`.

        This is the scheduler's hottest allocation (clients/nemesis/
        on_threads wrap every op AND update): _clone skips dataclass
        machinery, and a restriction that keeps every worker returns self.
        """
        tset = threads if isinstance(threads, (set, frozenset)) \
            else set(threads)
        workers = tuple((t, p) for t, p in self.workers if t in tset)
        if workers == self.workers:
            return self
        return self._clone(
            free_threads=frozenset(t for t in self.free_threads
                                   if t in tset),
            workers=workers)


def _thread_key(t):
    return (1, 0) if t == NEMESIS else (0, t)


def context(test: Dict[str, Any]) -> Context:
    """Fresh context for a test map: concurrency client threads + nemesis."""
    n = int(test.get("concurrency", 1))
    workers = [(i, i) for i in range(n)] + [(NEMESIS, NEMESIS)]
    return Context(time=0,
                   free_threads=frozenset([i for i in range(n)] + [NEMESIS]),
                   workers=tuple(workers))


# ---------------------------------------------------------------------------
# Generator protocol + lifting
# ---------------------------------------------------------------------------


class Generator:
    def op(self, test, ctx) -> Optional[Tuple[Any, Optional["Generator"]]]:
        raise NotImplementedError

    def update(self, test, ctx, event) -> Optional["Generator"]:
        return self


GenLike = Union[Generator, Dict[str, Any], Op, Callable, Sequence, None]


def lift(g: GenLike) -> Optional[Generator]:
    """Coerce a value into a Generator (generator.clj's protocol extension
    over maps, fns, and seqs)."""
    if g is None or isinstance(g, Generator):
        return g
    if isinstance(g, (dict, Op)):
        return OpGen(g)
    if callable(g):
        return FnGen(g)
    if isinstance(g, (list, tuple)):
        return Concat([lift(x) for x in g])
    raise TypeError(f"can't lift {type(g)} into a Generator")


_OP_STD_FIELDS = ("process", "type", "f", "value", "time")


def fill_op(template: Union[Dict, Op], ctx: Context):
    """Complete an op template with time/process from the context; returns
    PENDING if it needs a free process and none exists.  The process is
    resolved *before* any Op is built — dispatch-blocked draws are the
    scheduler's common case and must stay allocation-free."""
    d_process = template.process if isinstance(template, Op) \
        else template.get("process")
    if d_process is None:
        process = ctx.some_free_process()
        if process is None:
            return PENDING
    else:
        # A fixed process must be free to dispatch.
        t = ctx.process_thread(d_process)
        if t is None or t not in ctx.free_threads:
            return PENDING
        process = d_process
    if isinstance(template, Op):
        return template.with_(time=ctx.time, process=process)
    op = object.__new__(Op)
    od = op.__dict__
    od["process"] = process
    od["type"] = template.get("type", INVOKE)
    od["f"] = template.get("f")
    od["value"] = template.get("value")
    od["time"] = ctx.time
    od["index"] = None
    od["error"] = None
    extra = None
    for k in template:
        if k not in _OP_STD_FIELDS:
            if extra is None:
                extra = {}
            extra[k] = template[k]
    od["extra"] = extra if extra is not None else {}
    return op


class OpGen(Generator):
    """A single op (dict/Op literal): yields exactly one operation."""

    def __init__(self, template):
        self.template = template

    def op(self, test, ctx):
        op = fill_op(self.template, ctx)
        if op is PENDING:
            return (PENDING, self)
        return (op, None)

    def __repr__(self):
        return f"OpGen({self.template!r})"


class FnGen(Generator):
    """A function of () or (test, ctx): an infinite stream; each call's
    return value is lifted and asked for one op.  Exhausted when the function
    returns None.  A value produced while dispatch is blocked (:pending) is
    cached, not discarded — stateful functions see each call delivered."""

    def __init__(self, f, pending_gen: Optional[Generator] = None):
        self.f = f
        self.pending_gen = pending_gen

    def op(self, test, ctx):
        g = self.pending_gen
        while True:
            if g is None:
                try:
                    v = self.f(test, ctx)
                except TypeError:
                    v = self.f()
                if v is None:
                    return None
                g = lift(v)
            r = g.op(test, ctx)
            if r is None:
                g = None  # inner produced nothing; draw the next value
                continue
            v, _ = r
            if v is PENDING:
                return (PENDING, FnGen(self.f, g))
            return (v, FnGen(self.f))

    def __repr__(self):
        return f"FnGen({getattr(self.f, '__name__', self.f)!r})"


class Concat(Generator):
    """Sequential concatenation (generator.clj concat/seq extension): draws
    from the first non-exhausted element."""

    def __init__(self, gens: Sequence[Optional[Generator]]):
        self.gens = [g for g in gens if g is not None]

    def op(self, test, ctx):
        gens = self.gens
        i = 0
        while i < len(gens):
            r = gens[i].op(test, ctx)
            if r is None:
                i += 1
                continue
            v, g2 = r
            rest = gens[i + 1:]
            new = ([g2] if g2 is not None else []) + rest
            if not new:
                return (v, None)
            return (v, Concat(new) if len(new) > 1 else new[0])
        return None

    def update(self, test, ctx, event):
        if not self.gens:
            return self
        g2 = self.gens[0].update(test, ctx, event)
        return Concat([g2] + self.gens[1:])

    def __repr__(self):
        return f"Concat({self.gens!r})"


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------


class _Wrap(Generator):
    """Base for single-child wrappers; update recurses by default."""

    def __init__(self, gen):
        self.gen = lift(gen)

    def _new(self, gen) -> "Generator":
        c = type(self).__new__(type(self))
        c.__dict__.update(self.__dict__)
        c.gen = gen
        return c

    def update(self, test, ctx, event):
        if self.gen is None:
            return self
        g2 = self.gen.update(test, ctx, event)
        # identity propagation: most generators ignore updates, so the
        # common completion event must not clone the whole wrapper chain
        if g2 is self.gen:
            return self
        return self._new(g2)


class Validate(_Wrap):
    """Assert generator contract on every emitted op
    (generator.clj:622-676)."""

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is not PENDING:
            if not isinstance(v, Op):
                raise ValueError(f"generator yielded non-op {v!r}")
            if v.process is None or v.time is None or v.f is None:
                raise ValueError(f"generator yielded incomplete op {v!r}")
            wm = ctx.worker_map()
            t = ctx.process_thread(v.process)
            if t is None:
                raise ValueError(
                    f"op process {v.process!r} is not a worker: {wm}")
        return (v, self._new(g2) if g2 is not None else None)


def validate(gen):
    return Validate(gen)


class Map(_Wrap):
    """Transform every emitted op with f (generator.clj map at 782)."""

    def __init__(self, f, gen):
        super().__init__(gen)
        self.f = f

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        v2 = v if v is PENDING else self.f(v)
        return (v2, self._new(g2) if g2 is not None else None)


def gen_map(f, gen):
    return Map(f, gen)


def f_map(fmap: Dict[Any, Any], gen):
    """Rewrite op :f values through a mapping (generator.clj:790; used by
    nemesis composition)."""
    return Map(lambda op: op.with_(f=fmap.get(op.f, op.f)), gen)


class Filter(_Wrap):
    """Drop emitted ops failing the predicate (generator.clj:812)."""

    def __init__(self, pred, gen):
        super().__init__(gen)
        self.pred = pred

    def op(self, test, ctx):
        gen = self.gen
        while gen is not None:
            r = gen.op(test, ctx)
            if r is None:
                return None
            v, g2 = r
            if v is PENDING or self.pred(v):
                return (v, self._new(g2) if g2 is not None else None)
            gen = g2
        return None


def gen_filter(pred, gen):
    return Filter(pred, gen)


class OnUpdate(_Wrap):
    """Call (f this test ctx event) on updates (generator.clj:836)."""

    def __init__(self, f, gen):
        super().__init__(gen)
        self.f = f

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        return (v, self._new(g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


class OnThreads(_Wrap):
    """Restrict a generator to a subset of threads (generator.clj:844-882);
    both op and update see a filtered context."""

    def __init__(self, pred, gen):
        super().__init__(gen)
        if callable(pred) and not isinstance(pred, (set, frozenset)):
            self.pred = pred
            self.tset = None
        else:
            s = frozenset(pred)
            self.tset = s
            self.pred = s.__contains__

    def _threads(self, ctx):
        # set-bound restrictions pass the set straight to restrict (its
        # fast path); predicate restrictions filter the workers
        if self.tset is not None:
            return self.tset
        return [t for t, _ in ctx.workers if self.pred(t)]

    def _restrict(self, ctx):
        """ctx.restrict memoized on the workers tuple: the worker map only
        changes on process crashes, while this runs for every op AND every
        completion — the scheduler's hottest allocation site."""
        cache = self.__dict__.get("_rcache")
        if cache is None:
            cache = self.__dict__["_rcache"] = {}
        ent = cache.get(ctx.workers)
        if ent is None:
            if self.tset is not None:
                tset = self.tset
            else:
                tset = frozenset(t for t, _ in ctx.workers if self.pred(t))
            workers = tuple((t, p) for t, p in ctx.workers if t in tset)
            if len(cache) > 64:
                cache.clear()
            cache[ctx.workers] = ent = (tset, workers)
        tset, workers = ent
        if workers == ctx.workers:
            return ctx
        return ctx._clone(
            free_threads=frozenset(t for t in ctx.free_threads
                                   if t in tset),
            workers=workers)

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, self._restrict(ctx))
        if r is None:
            return None
        v, g2 = r
        return (v, self._new(g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        t = ctx.process_thread(getattr(event, "process", None))
        if t is None or not self.pred(t):
            return self
        g2 = self.gen.update(test, self._restrict(ctx), event)
        if g2 is self.gen:
            return self
        return self._new(g2)


def on_threads(pred, gen):
    return OnThreads(pred, gen)


on = on_threads


def clients(gen):
    """Ops only on client threads (generator.clj:1093)."""
    return OnThreads(lambda t: t != NEMESIS, gen)


def nemesis(gen):
    """Ops only on the nemesis thread (generator.clj:1105)."""
    return OnThreads(lambda t: t == NEMESIS, gen)


class Any(Generator):
    """Race: each call takes an op from whichever child can produce the
    soonest one (generator.clj:946)."""

    def __init__(self, *gens):
        self.gens = [lift(g) for g in gens if g is not None]

    def op(self, test, ctx):
        best = None
        best_i = -1
        soonest = math.inf
        pending_any = False
        # Pending children's continuations must survive even when another
        # child wins the draw: a Sleep (or any self-timing generator)
        # anchors its deadline in the continuation, and discarding it
        # whenever a sibling produced an op re-anchors the timer on every
        # dispense — a nemesis `sleep 1s; start-fault` inside any_gen with
        # a busy client stream then fires arbitrarily late (observed 1-8 s
        # of drift).  Ready-but-not-chosen children keep their PRE-draw
        # state (the op was not taken from them), matching
        # generator.clj:946's `any`.
        gens = list(self.gens)
        for i, g in enumerate(self.gens):
            r = g.op(test, ctx)
            if r is None:
                continue
            v, g2 = r
            if v is PENDING:
                pending_any = True
                if g2 is not None:
                    gens[i] = g2
                continue
            if v.time < soonest:
                soonest = v.time
                best = (v, g2)
                best_i = i
        if best is None:
            return (PENDING, Any(*gens)) if pending_any else None
        v, g2 = best
        if g2 is None:
            gens.pop(best_i)
        else:
            gens[best_i] = g2
        if not gens:
            return (v, None)
        return (v, Any(*gens))

    def update(self, test, ctx, event):
        gens2 = [g.update(test, ctx, event) for g in self.gens]
        if all(a is b for a, b in zip(gens2, self.gens)):
            return self
        return Any(*gens2)


def any_gen(*gens):
    return Any(*gens)


class EachThread(_Wrap):
    """Every thread runs its own fresh copy of the generator
    (generator.clj:1001)."""

    def __init__(self, gen):
        self.proto = lift(gen)
        self.per: Dict[Any, Optional[Generator]] = {}
        self.started: set = set()

    def _copy(self):
        c = EachThread.__new__(EachThread)
        c.proto = self.proto
        c.per = dict(self.per)
        c.started = set(self.started)
        return c

    def _gen_for(self, t):
        if t not in self.started:
            return self.proto
        return self.per.get(t)

    def op(self, test, ctx):
        pending = False
        cur = self
        for t in ctx.sorted_free_threads():
            g = cur._gen_for(t)
            if g is None:
                continue
            sub = ctx.restrict([t])
            r = g.op(test, sub)
            if r is None:
                # the thread's copy is exhausted: RECORD that, or a copy
                # that dies on its first draw keeps _gen_for returning the
                # prototype and all_done never fires — each_thread of an
                # immediately-empty generator then pends forever
                cur = cur._copy()
                cur.started.add(t)
                cur.per[t] = None
                continue
            v, g2 = r
            if v is PENDING:
                pending = True
                if g2 is not None:
                    cur = cur._copy()
                    cur.started.add(t)
                    cur.per[t] = g2
                continue
            c = cur._copy()
            c.started.add(t)
            c.per[t] = g2
            return (v, c)
        all_done = all(cur._gen_for(t) is None for t in ctx.all_threads())
        if all_done:
            return None
        return (PENDING, cur)

    def update(self, test, ctx, event):
        t = ctx.process_thread(getattr(event, "process", None))
        if t is None:
            return self
        g = self._gen_for(t)
        if g is None:
            return self
        c = self._copy()
        c.started.add(t)
        c.per[t] = g.update(test, ctx.restrict([t]), event)
        return c


def each_thread(gen):
    return EachThread(gen)


class Reserve(Generator):
    """Partition client threads into ranges, each with its own sub-generator;
    remaining threads run the default (generator.clj:1056-1092)."""

    def __init__(self, *args):
        if len(args) % 2 != 1:
            raise ValueError("reserve takes n1, gen1, n2, gen2, ..., default")
        self.counts = [int(args[i]) for i in range(0, len(args) - 1, 2)]
        gens = [lift(args[i]) for i in range(1, len(args) - 1, 2)]
        self.default = lift(args[-1])
        self.gens = gens

    def _ranges(self, ctx):
        threads = [t for t in ctx.all_threads() if t != NEMESIS]
        out = []
        i = 0
        for n in self.counts:
            out.append(threads[i:i + n])
            i += n
        rest = threads[i:] + [NEMESIS]
        return out, rest

    def op(self, test, ctx):
        ranges, rest = self._ranges(ctx)
        soonest = None
        pending = False
        pieces = list(zip(ranges, self.gens)) + [(rest, self.default)]
        for i, (threads, g) in enumerate(pieces):
            if g is None:
                continue
            r = g.op(test, ctx.restrict(threads))
            if r is None:
                continue
            v, g2 = r
            if v is PENDING:
                pending = True
                continue
            if soonest is None or v.time < soonest[0].time:
                soonest = (v, i, g2)
        if soonest is None:
            return (PENDING, self) if pending else None
        v, i, g2 = soonest
        c = Reserve.__new__(Reserve)
        c.counts = self.counts
        c.default = self.default
        c.gens = list(self.gens)
        if i == len(pieces) - 1:
            c.default = g2
        else:
            c.gens[i] = g2
        return (v, c)

    def update(self, test, ctx, event):
        t = ctx.process_thread(getattr(event, "process", None))
        if t is None:
            return self
        ranges, rest = self._ranges(ctx)
        c = Reserve.__new__(Reserve)
        c.counts = self.counts
        c.default = self.default
        c.gens = list(self.gens)
        for i, threads in enumerate(ranges):
            if t in threads and c.gens[i] is not None:
                c.gens[i] = c.gens[i].update(test, ctx.restrict(threads), event)
                return c
        if c.default is not None:
            c.default = c.default.update(test, ctx.restrict(rest), event)
        return c


def reserve(*args):
    return Reserve(*args)


class Mix(Generator):
    """Uniformly choose among sub-generators per op; exhausted children drop
    out (generator.clj:1140)."""

    def __init__(self, gens):
        self.gens = [lift(g) for g in gens if g is not None]

    def op(self, test, ctx):
        # one uniform draw covers the common case; only if that child
        # can't produce do we pay for shuffling the rest (keeps fallback
        # selection uniform, unlike plain rotation)
        gens = list(self.gens)
        n = len(gens)
        if n == 0:
            return None
        order = [RNG.randrange(n) if n > 1 else 0]
        rest = None
        pending = False
        k = 0
        while k < len(order) or rest is None:
            if k >= len(order):
                rest = [i for i in range(n) if i != order[0]]
                RNG.shuffle(rest)
                order.extend(rest)
                if k >= len(order):
                    break
            i = order[k]
            k += 1
            r = gens[i].op(test, ctx)
            if r is None:
                gens2 = gens[:i] + gens[i + 1:]
                if not gens2:
                    return None
                return Mix(gens2).op(test, ctx)
            v, g2 = r
            if v is PENDING:
                pending = True
                if g2 is not None:
                    gens[i] = g2
                continue
            if g2 is None:
                gens2 = gens[:i] + gens[i + 1:]
            else:
                gens2 = gens
                gens2[i] = g2
            return (v, Mix(gens2) if gens2 else None)
        return (PENDING, Mix(gens)) if pending else None

    def update(self, test, ctx, event):
        return self


def mix(gens):
    return Mix(gens)


class Limit(_Wrap):
    """At most n ops (generator.clj:1166)."""

    def __init__(self, n, gen):
        super().__init__(gen)
        self.n = n

    def op(self, test, ctx):
        if self.n <= 0 or self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        c = self._new(g2)
        c.n = self.n - 1
        return (v, c if (c.gen is not None and c.n > 0) else None)


def limit(n, gen):
    return Limit(n, gen)


def once(gen):
    return Limit(1, gen)


class Repeat(_Wrap):
    """Repeat the generator's next op forever (or n times): like the
    reference's repeat (generator.clj:1196), each emitted op comes from the
    same (non-advancing) generator."""

    def __init__(self, gen, n=None):
        super().__init__(gen)
        self.n = n

    def op(self, test, ctx):
        if self.gen is None or (self.n is not None and self.n <= 0):
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        c = self._new(self.gen)
        if self.n is not None:
            c.n = self.n - 1
            if c.n <= 0:
                return (v, None)
        return (v, c)


def repeat(gen, n=None):
    return Repeat(gen, n)


class Cycle(_Wrap):
    """Restart the generator when it exhausts (generator.clj:1228)."""

    def __init__(self, gen, n=None):
        super().__init__(gen)
        self.proto = self.gen
        self.n = n

    def op(self, test, ctx):
        if self.n is not None and self.n <= 0:
            return None
        r = self.gen.op(test, ctx) if self.gen is not None else None
        if r is None:
            n2 = None if self.n is None else self.n - 1
            if n2 is not None and n2 <= 0:
                return None
            c = Cycle.__new__(Cycle)
            c.proto = self.proto
            c.gen = self.proto
            c.n = n2
            r = c.gen.op(test, ctx)
            if r is None:
                return None
            v, g2 = r
            c2 = c._new(g2 if g2 is not None else None)
            c2.proto = self.proto
            return (v, c2)
        v, g2 = r
        c = self._new(g2)
        c.proto = self.proto
        return (v, c)


def cycle(gen, n=None):
    return Cycle(gen, n)


class ProcessLimit(_Wrap):
    """Stop after n distinct processes have participated
    (generator.clj:1253)."""

    def __init__(self, n, gen):
        super().__init__(gen)
        self.n = n
        self.seen: frozenset = frozenset()

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        seen = self.seen | {v.process}
        if len(seen) > self.n:
            return None
        c = self._new(g2)
        c.seen = seen
        return (v, c if c.gen is not None else None)


def process_limit(n, gen):
    return ProcessLimit(n, gen)


class TimeLimit(_Wrap):
    """Cut off after dt seconds of logical time (generator.clj:1286)."""

    def __init__(self, dt_s, gen):
        super().__init__(gen)
        self.deadline: Optional[int] = None
        self.dt = int(dt_s * 1e9)

    def op(self, test, ctx):
        if self.gen is None:
            return None
        deadline = self.deadline if self.deadline is not None \
            else ctx.time + self.dt
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is not PENDING and v.time >= deadline:
            return None
        c = self._new(g2)
        c.deadline = deadline
        if v is PENDING:
            return (PENDING, c)
        return (v, c if c.gen is not None else None)


def time_limit(dt_s, gen):
    return TimeLimit(dt_s, gen)


class Stagger(_Wrap):
    """Poisson-ish pacing: uniform random delay with mean dt seconds between
    ops across the whole generator (generator.clj:1315)."""

    def __init__(self, dt_s, gen):
        super().__init__(gen)
        self.dt2 = 2 * dt_s * 1e9
        self.next_time: Optional[int] = None

    def op(self, test, ctx):
        if self.gen is None:
            return None
        nt = self.next_time if self.next_time is not None else ctx.time
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            c = self._new(g2)
            c.next_time = nt
            return (PENDING, c)
        t = max(nt, v.time)
        c = self._new(g2)
        c.next_time = t + int(RNG.random() * self.dt2)
        v = v.with_(time=t)
        return (v, c if c.gen is not None else c)


def stagger(dt_s, gen):
    return Stagger(dt_s, gen)


class DelayGen(_Wrap):
    """Exactly dt seconds between ops (generator.clj:1385)."""

    def __init__(self, dt_s, gen):
        super().__init__(gen)
        self.dt = int(dt_s * 1e9)
        self.next_time: Optional[int] = None

    def op(self, test, ctx):
        if self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        nt = self.next_time if self.next_time is not None else v.time
        t = max(nt, v.time)
        c = self._new(g2)
        c.next_time = t + self.dt
        return (v.with_(time=t), c if c.gen is not None else None)


def delay(dt_s, gen):
    return DelayGen(dt_s, gen)


class Sleep(Generator):
    """Emit nothing for dt seconds, then exhaust (generator.clj:1397)."""

    def __init__(self, dt_s):
        self.dt = int(dt_s * 1e9)
        self.deadline: Optional[int] = None

    def op(self, test, ctx):
        deadline = self.deadline if self.deadline is not None \
            else ctx.time + self.dt
        if ctx.time >= deadline:
            return None
        c = Sleep.__new__(Sleep)
        c.dt = self.dt
        c.deadline = deadline
        return (PENDING, c)


def sleep(dt_s):
    return Sleep(dt_s)


class Synchronize(_Wrap):
    """Wait for all threads to be free before the wrapped generator starts
    (generator.clj:1420)."""

    def __init__(self, gen):
        super().__init__(gen)
        self.released = False

    def op(self, test, ctx):
        if self.gen is None:
            return None
        if not self.released and len(ctx.free_threads) < len(ctx.workers):
            return (PENDING, self)
        c = self._new(self.gen)
        c.released = True
        return c.gen_op_through(test, ctx)

    def gen_op_through(self, test, ctx):
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        c = self._new(g2)
        c.released = True
        return (v, c if c.gen is not None else None)


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Each phase waits for quiescence before starting
    (generator.clj:1425)."""
    return Concat([Synchronize(g) for g in gens])


def then(a, b):
    """b, then a — argument order matches the reference's ->> threading
    (generator.clj:1432)."""
    return Concat([lift(b), Synchronize(a)])


class LogGen(Generator):
    """Emit a log message into the interpreter's logging (generator.clj:1177);
    modeled as a :log op on no thread — interpreters treat it specially."""

    def __init__(self, msg):
        self.msg = msg
        self.done = False

    def op(self, test, ctx):
        if self.done:
            return None
        op = Op(process=NEMESIS, type="log", f="log", value=self.msg,
                time=ctx.time)
        return (op, None)


def log(msg):
    return LogGen(msg)


class Trace(_Wrap):
    """Print every op/update flowing through (generator.clj:720-764)."""

    def __init__(self, name, gen):
        super().__init__(gen)
        self.name = name

    def op(self, test, ctx):
        r = self.gen.op(test, ctx) if self.gen is not None else None
        print(f"[gen-trace {self.name}] op -> "
              f"{None if r is None else r[0]!r}")
        if r is None:
            return None
        v, g2 = r
        return (v, self._new(g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        print(f"[gen-trace {self.name}] update <- {event!r}")
        return super().update(test, ctx, event)


def trace(name, gen):
    return Trace(name, gen)


class UntilOk(_Wrap):
    """Retry the generator's ops until one completes :ok
    (generator.clj:1469)."""

    def __init__(self, gen):
        super().__init__(gen)
        self.done = False

    def op(self, test, ctx):
        if self.done or self.gen is None:
            return None
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        # Keep our own generator alive; completion flips done.
        c = self._new(g2 if g2 is not None else self.gen)
        return (v, c)

    def update(self, test, ctx, event):
        c = self._new(self.gen.update(test, ctx, event)
                      if self.gen is not None else None)
        if getattr(event, "type", None) == OK:
            c.done = True
        return c


def until_ok(gen):
    return UntilOk(gen)


class FlipFlop(Generator):
    """Alternate between two generators on each op (generator.clj:1485)."""

    def __init__(self, a, b, turn=0):
        self.gens = [lift(a), lift(b)]
        self.turn = turn

    def op(self, test, ctx):
        g = self.gens[self.turn]
        if g is None:
            return None
        r = g.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            pair = list(self.gens)
            pair[self.turn] = g2
            return (PENDING, FlipFlop(pair[0], pair[1], self.turn))
        pair = list(self.gens)
        pair[self.turn] = g2
        return (v, FlipFlop(pair[0], pair[1], (self.turn + 1) % 2))

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b):
    return FlipFlop(a, b)


class CycleTimes(Generator):
    """Rotate between generators on a wall-clock schedule: spend t_i seconds
    in gen_i, cycling (generator.clj:1557)."""

    def __init__(self, *args, _start=None, _i=0):
        if len(args) % 2 != 0:
            raise ValueError("cycle_times takes t1, gen1, t2, gen2, ...")
        self.durations = [int(args[i] * 1e9) for i in range(0, len(args), 2)]
        self.gens = [lift(args[i]) for i in range(1, len(args), 2)]
        self.start = _start
        self.i = _i

    def _clone(self, **kw):
        c = CycleTimes.__new__(CycleTimes)
        c.durations = self.durations
        c.gens = list(self.gens)
        c.start = kw.get("start", self.start)
        c.i = kw.get("i", self.i)
        return c

    def op(self, test, ctx):
        start = self.start if self.start is not None else ctx.time
        i = self.i
        # advance phase by logical time
        while ctx.time >= start + self.durations[i]:
            start += self.durations[i]
            i = (i + 1) % len(self.gens)
        g = self.gens[i]
        if g is None:
            return None
        r = g.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        c = self._clone(start=start, i=i)
        c.gens[i] = g2 if g2 is not None else c.gens[i]
        if v is PENDING:
            return (PENDING, c)
        return (v, c)

    def update(self, test, ctx, event):
        c = self._clone()
        c.gens = [g.update(test, ctx, event) if g is not None else None
                  for g in self.gens]
        return c


def cycle_times(*args):
    return CycleTimes(*args)


class ConcurrencyLimit(_Wrap):
    """At most n of this generator's ops outstanding at once."""

    def __init__(self, n, gen):
        super().__init__(gen)
        self.n = n
        self.outstanding: frozenset = frozenset()

    def op(self, test, ctx):
        if self.gen is None:
            return None
        if len(self.outstanding) >= self.n:
            return (PENDING, self)
        r = self.gen.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is PENDING:
            return (PENDING, self._new(g2))
        c = self._new(g2)
        c.outstanding = self.outstanding | {v.process}
        return (v, c if c.gen is not None or c.outstanding else None)

    def update(self, test, ctx, event):
        c = self._new(self.gen.update(test, ctx, event)
                      if self.gen is not None else None)
        if getattr(event, "type", None) in (OK, "fail", "info"):
            c.outstanding = self.outstanding - {event.process}
        return c


def concurrency_limit(n, gen):
    return ConcurrencyLimit(n, gen)
