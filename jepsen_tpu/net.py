"""Network manipulation — partitions and packet shaping.

Parity: jepsen.net (jepsen/src/jepsen/net.clj, net/proto.clj:5-12): a Net
implementation can sever links (drop), heal everything, and shape traffic
(slow/flaky/fast/shape) between nodes.  The iptables implementation includes
the batched all-grudges fast path (net.clj:176-186); tc-netem behaviors
mirror net.clj:49-71's defaults.

A *grudge* maps each node to the collection of nodes it refuses to hear
from (nemesis.clj's partition language).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from jepsen_tpu.control import Lit, session

# tc-netem behavior defaults (net.clj:49-71)
DEFAULT_SLOW = {"delay": "50ms", "jitter": "10ms", "correlation": "25%"}
DEFAULT_FLAKY = {"loss": "20%", "correlation": "75%"}


class Net:
    def drop(self, test, src: str, dst: str) -> None:
        """dst stops accepting traffic from src."""
        raise NotImplementedError

    def drop_all(self, test, grudge: Dict[str, Iterable[str]]) -> None:
        """Apply a whole grudge: node -> senders to ignore."""
        for dst, srcs in grudge.items():
            for src in srcs:
                self.drop(test, src, dst)

    def heal(self, test) -> None:
        raise NotImplementedError

    def slow(self, test, opts: Optional[Dict] = None) -> None:
        raise NotImplementedError

    def flaky(self, test) -> None:
        raise NotImplementedError

    def fast(self, test) -> None:
        raise NotImplementedError

    def shape(self, test, nodes: Optional[Sequence[str]] = None,
              behavior: Optional[Dict] = None) -> None:
        raise NotImplementedError


class NoopNet(Net):
    def drop(self, test, src, dst):
        pass

    def heal(self, test):
        pass

    def slow(self, test, opts=None):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass

    def shape(self, test, nodes=None, behavior=None):
        pass


noop = NoopNet


class IptablesNet(Net):
    """INPUT-chain DROP rules (net.clj:130-186)."""

    def drop(self, test, src, dst):
        s = session(test, dst).sudo()
        s.exec("iptables", "-A", "INPUT", "-s", src, "-j", "DROP",
               "-w")

    def drop_all(self, test, grudge):
        # Batched fast path: one shell invocation per node
        # (net.clj:176-186 PartitionAll).
        from jepsen_tpu.control import on_nodes

        def apply_(t, node):
            srcs = list(grudge.get(node) or [])
            if not srcs:
                return
            s = session(t, node).sudo()
            cmds = " && ".join(
                f"iptables -A INPUT -s {src} -j DROP -w" for src in srcs)
            s.exec("bash", "-c", cmds)

        on_nodes(test, apply_, list(grudge.keys()))

    def heal(self, test):
        from jepsen_tpu.control import on_nodes

        def heal_(t, node):
            s = session(t, node).sudo()
            s.exec("iptables", "-F", "-w")
            s.exec("iptables", "-X", "-w")

        on_nodes(test, heal_)

    # -- tc packet shaping -------------------------------------------------
    def _netem_args(self, behavior: Dict) -> List[str]:
        out = []
        if "delay" in behavior:
            out += ["delay", behavior["delay"]]
            if "jitter" in behavior:
                out.append(behavior["jitter"])
            if "correlation" in behavior:
                out.append(behavior["correlation"])
        if "loss" in behavior:
            out += ["loss", behavior["loss"]]
            if "correlation" in behavior and "delay" not in behavior:
                out.append(behavior["correlation"])
        if "corrupt" in behavior:
            out += ["corrupt", behavior["corrupt"]]
        if "duplicate" in behavior:
            out += ["duplicate", behavior["duplicate"]]
        if "reorder" in behavior:
            out += ["reorder", behavior["reorder"]]
        if "rate" in behavior:
            out += ["rate", behavior["rate"]]
        return out

    def shape(self, test, nodes=None, behavior=None):
        from jepsen_tpu.control import on_nodes
        behavior = behavior or DEFAULT_SLOW

        def shape_(t, node):
            s = session(t, node).sudo()
            dev = _default_dev(s)
            s.exec_result("tc", "qdisc", "del", "dev", dev, "root")
            s.exec("tc", "qdisc", "add", "dev", dev, "root", "netem",
                   *self._netem_args(behavior))

        on_nodes(test, shape_, nodes)

    def slow(self, test, opts=None):
        self.shape(test, behavior={**DEFAULT_SLOW, **(opts or {})})

    def flaky(self, test):
        self.shape(test, behavior=DEFAULT_FLAKY)

    def fast(self, test):
        from jepsen_tpu.control import on_nodes

        def fast_(t, node):
            s = session(t, node).sudo()
            dev = _default_dev(s)
            s.exec_result("tc", "qdisc", "del", "dev", dev, "root")

        on_nodes(test, fast_)


iptables = IptablesNet


class IpfilterNet(IptablesNet):
    """IPFilter rules for SmartOS/Solaris nodes (net.clj:188-223).  Shaping
    inherits the tc-netem paths; only drop/heal differ."""

    def drop(self, test, src, dst):
        s = session(test, dst).sudo()
        s.exec("bash", "-c",
               f"echo 'block in from {src} to any' | ipf -f -")

    def drop_all(self, test, grudge):
        from jepsen_tpu.control import on_nodes

        def apply_(t, node):
            srcs = list(grudge.get(node) or [])
            if not srcs:
                return
            rules = "\n".join(f"block in from {src} to any" for src in srcs)
            s = session(t, node).sudo()
            s.exec("bash", "-c", f"printf '%s\\n' '{rules}' | ipf -f -")

        on_nodes(test, apply_, list(grudge.keys()))

    def heal(self, test):
        from jepsen_tpu.control import on_nodes

        def heal_(t, node):
            session(t, node).sudo().exec("ipf", "-Fa")

        on_nodes(test, heal_)


ipfilter = IpfilterNet


def _default_dev(s) -> str:
    out = s.exec("bash", "-c",
                 "ip route show default | head -1 | grep -o 'dev [^ ]*' "
                 "| cut -d' ' -f2 || echo eth0")
    return out.strip() or "eth0"


# ---------------------------------------------------------------------------
# Grudge constructors (jepsen.nemesis partition language, nemesis.clj:109-285)
# ---------------------------------------------------------------------------


def complete_grudge(components: Sequence[Sequence[str]]) -> Dict[str, List[str]]:
    """Nodes in different components can't talk (nemesis.clj:121)."""
    grudge: Dict[str, List[str]] = {}
    for comp in components:
        others = [n for c in components if c is not comp for n in c]
        for n in comp:
            grudge[n] = list(others)
    return grudge


def bisect(nodes: Sequence[str]) -> List[List[str]]:
    """Split nodes into two halves (nemesis.clj:109)."""
    mid = len(nodes) // 2
    return [list(nodes[:mid]), list(nodes[mid:])]


def split_one(node: str, nodes: Sequence[str]) -> List[List[str]]:
    """Isolate one node (nemesis.clj:114)."""
    return [[node], [n for n in nodes if n != node]]


def bridge(nodes: Sequence[str]) -> Dict[str, List[str]]:
    """Two halves joined only through one bridge node (nemesis.clj:145)."""
    n = len(nodes)
    mid = n // 2
    bridge_node = nodes[mid]
    a = list(nodes[:mid])
    b = list(nodes[mid + 1:])
    grudge = {}
    for x in a:
        grudge[x] = list(b)
    for x in b:
        grudge[x] = list(a)
    grudge[bridge_node] = []
    return grudge


def majorities_ring(nodes: Sequence[str]) -> Dict[str, List[str]]:
    """Every node sees a majority, but no two nodes see the same majority
    (nemesis.clj:261): node i hears from the floor(n/2) nodes around it."""
    n = len(nodes)
    k = n // 2
    grudge = {}
    for i, node in enumerate(nodes):
        visible = {nodes[(i + d) % n] for d in range(-(k // 2), k - k // 2 + 1)}
        grudge[node] = [m for m in nodes if m not in visible]
    return grudge
