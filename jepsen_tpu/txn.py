"""Micro-op transaction utilities.

Parity: jepsen.txn (vendored at txn/src/jepsen/txn.clj:1-40 in the
reference): transactions are sequences of micro-ops ("mops")
``[f, k, v]`` — e.g. ``["r", "x", [1, 2]]`` or ``["append", "x", 3]`` —
and these helpers extract external reads/writes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence, Tuple

Mop = Sequence  # [f, k, v]

WRITE_FS = {"w", "write", "append"}
READ_FS = {"r", "read"}


def ext_reads(txn: Sequence[Mop]) -> Dict[Any, Any]:
    """External reads: the first read of each key *before* any write to it
    (txn.clj ext-reads)."""
    reads: Dict[Any, Any] = {}
    written = set()
    for f, k, v in txn:
        if f in READ_FS:
            if k not in written and k not in reads:
                reads[k] = v
        elif f in WRITE_FS:
            written.add(k)
    return reads


def ext_writes(txn: Sequence[Mop]) -> Dict[Any, Any]:
    """External writes: the last write of each key (txn.clj ext-writes)."""
    writes: Dict[Any, Any] = {}
    for f, k, v in txn:
        if f in WRITE_FS:
            writes[k] = v
    return writes


def reads_of(txn: Sequence[Mop]) -> List[Mop]:
    return [m for m in txn if m[0] in READ_FS]


def writes_of(txn: Sequence[Mop]) -> List[Mop]:
    return [m for m in txn if m[0] in WRITE_FS]


def keys_of(txn: Sequence[Mop]) -> List[Any]:
    seen, out = set(), []
    for _, k, _ in txn:
        if k not in seen:
            seen.add(k)
            out.append(k)
    return out
