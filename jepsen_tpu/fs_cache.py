"""Control-node persistent cache for files, strings, and structured data.

Parity: jepsen.fs-cache (jepsen/src/jepsen/fs_cache.clj): cache expensive
artifacts (package downloads, built binaries) across runs, keyed by logical
paths, with atomic writes and per-key locking.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from typing import Any, List, Optional, Sequence, Union

DEFAULT_DIR = os.path.join("store", "cache")

_locks: dict = {}
_locks_guard = threading.Lock()


def _lock_for(key: str) -> threading.Lock:
    with _locks_guard:
        return _locks.setdefault(key, threading.Lock())


class Cache:
    def __init__(self, base: str = DEFAULT_DIR):
        self.base = base

    def _path(self, key: Sequence[Any]) -> str:
        parts = [str(k).replace(os.sep, "_") for k in key]
        return os.path.join(self.base, *parts)

    def locking(self, key: Sequence[Any]):
        return _lock_for(self._path(key))

    # -- presence ----------------------------------------------------------
    def cached(self, key: Sequence[Any]) -> bool:
        return os.path.exists(self._path(key))

    def clear(self, key: Optional[Sequence[Any]] = None) -> None:
        p = self._path(key) if key else self.base
        if os.path.isdir(p):
            shutil.rmtree(p, ignore_errors=True)
        elif os.path.exists(p):
            os.unlink(p)

    # -- files -------------------------------------------------------------
    def save_file(self, src: str, key: Sequence[Any]) -> str:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        shutil.copy(src, tmp)
        os.replace(tmp, dst)
        return dst

    def file_path(self, key: Sequence[Any]) -> Optional[str]:
        p = self._path(key)
        return p if os.path.exists(p) else None

    # -- strings / data ----------------------------------------------------
    def save_string(self, s: str, key: Sequence[Any]) -> None:
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "w") as f:
            f.write(s)
        os.replace(tmp, dst)

    def load_string(self, key: Sequence[Any]) -> Optional[str]:
        p = self.file_path(key)
        if p is None:
            return None
        with open(p) as f:
            return f.read()

    def save_data(self, value: Any, key: Sequence[Any]) -> None:
        self.save_string(json.dumps(value, default=str), key)

    def load_data(self, key: Sequence[Any]) -> Any:
        s = self.load_string(key)
        return None if s is None else json.loads(s)


cache = Cache()
