"""Incremental checker state, carried across monitor epochs.

The WGL side is a true incremental frontier: :class:`KeyFrontier` is the
streaming configuration search of :mod:`jepsen_tpu.checker.wgl_cpu`
(same closure, same ghost subsumption — it *imports* ``_closure``) with
the event loop turned inside out, so state persists between feeds and
each epoch flush pays only for ops that arrived since the last one.

Why that is sound: the WGL scan refutes at a RETURN event when no
surviving configuration linearized the returning op — and nothing a
*later* event does can resurrect a dead configuration set, so a
refutation on a prefix is final for every extension of that prefix.
(Validity of a prefix, by contrast, implies nothing about the full
history — hence the resumed authoritative check in resume.py.)

The stream-order subtlety: an ENTER event needs its op's *completed*
view (observed read values; ok/fail/info class), which is unknown at
invoke time.  The frontier therefore advances only up to its
*horizon* — the earliest invocation whose completion has not yet
arrived — and buffers everything after it.  Ops consumed past the
horizon produce exactly the event stream :func:`checker.prep.prepare`
would build for the same history (fail pairs removed, crashed ops
entering as ghosts, unconstraining crashed reads dropped, free-list
slot reuse), so the final frontier verdict is wgl_cpu's verdict by
construction — the parity the fuzz tests assert op-for-op, including
``configs-explored``.

Per-key decomposition (P-compositionality, the same split
serve/decompose.py and independent.py use) keeps each frontier's
pending window at per-key concurrency: :class:`WglEpochEngine` routes
ops to per-key frontiers exactly as ``independent.subhistory`` would.

The Elle side (:class:`ElleEpochEngine`) carries the completed-txn
prefix across epochs — ingest is incremental (each flush appends only
new ops) — and checks the accumulated prefix as a run-ended-here
history: invocations still open at the cut are included as ``info``
(indeterminate) txns, which is precisely what the history would look
like had the run stopped at the cut, so anomaly sets on the prefix are
anomaly sets of a legitimate history, never artifacts of the cut.
Epoch checks ride the shared serve.CheckService lanes when a service is
attached (bounded-shape engine cache, continuous batching with the rest
of the fleet's traffic) and fall back to the host elle engine when not.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional

from jepsen_tpu.checker.wgl_cpu import SearchExploded, _closure, \
    _render_configs
from jepsen_tpu.history import FAIL, History, INFO, INVOKE, NEMESIS, OK, Op
from jepsen_tpu.independent import key_of
from jepsen_tpu.models.base import Model

PURE_READ_NAMES = ("read", "r")  # checker.prep's host-tier default


class KeyFrontier:
    """The resumable WGL configuration frontier for one key's stream.

    Feed ops in history order (invocations and completions); call
    :meth:`advance` to consume everything up to the horizon.  A
    refutation (``self.result``) is final; an exploded search
    (``self.exploded``) poisons this key's verdict to unknown."""

    def __init__(self, model: Model, max_configs: int = 2_000_000,
                 keep_prefix: bool = False):
        self.model = model
        self.max_configs = max_configs
        # With keep_prefix the frontier retains every fed op (for
        # service-side confirmation of a refutation); off by default so
        # the frontier's memory stays bounded by pending concurrency.
        self.keep_prefix = keep_prefix
        self.prefix: List[Op] = []
        self.window: Dict[int, Op] = {}     # slot -> pending effective op
        self.configs = {(0, model)}
        self.ghost_mask = 0
        self.n_ghosts = 0
        self.n_explored = 0
        self.ops_entered = 0                # ENTER events consumed
        self.ops_checked = 0                # RETURN events consumed
        self.result: Optional[Dict[str, Any]] = None
        self.exploded: Optional[str] = None
        self._gclasses: Dict[Any, List[int]] = {}  # semantic key -> slots
        self._free: List[int] = []
        self._next_slot = 0
        self._stream: deque = deque()       # unconsumed ops, history order
        self._open: Dict[Any, int] = {}     # process -> open invoke index
        self._resolution: Dict[int, Op] = {}  # invoke index -> completion
        self._return_slot: Dict[int, int] = {}  # ok-completion index -> slot
        self._finalizing = False

    # -- ingest -----------------------------------------------------------
    def feed(self, op: Op) -> None:
        if self.keep_prefix:
            self.prefix.append(op)
        if op.type == INVOKE:
            self._open[op.process] = op.index
        else:
            j = self._open.pop(op.process, None)
            if j is not None:
                self._resolution[j] = op
        self._stream.append(op)

    # -- the incremental event loop ---------------------------------------
    def _alloc_slot(self) -> int:
        if self._free:
            return self._free.pop()
        s = self._next_slot
        self._next_slot += 1
        return s

    def _enter(self, eff: Op, ghost: bool, comp: Optional[Op]) -> None:
        s = self._alloc_slot()
        self.window[s] = eff
        self.ops_entered += 1
        if ghost:
            self.ghost_mask |= 1 << s
            self._gclasses.setdefault((eff.f, repr(eff.value)), []).append(s)
            self.n_ghosts += 1
        elif comp is not None:
            self._return_slot[comp.index] = s

    def _return(self, slot: int, comp: Op) -> None:
        self.configs = _closure(self.configs, self.window, self.max_configs,
                                None, self.ghost_mask, self._gclasses)
        self.n_explored += len(self.configs)
        bit = 1 << slot
        survivors = {(m & ~bit, st) for (m, st) in self.configs if m & bit}
        if not survivors:
            # witness: refuting op, final configs, pending window attached
            self.result = {
                "valid": False,
                "analyzer": "wgl-cpu",          # same search, same shape
                "op": self.window[slot].to_dict(),
                "op-index": comp.index,          # refuting completion index
                "previous-ok": True,
                "final-configs": _render_configs(self.configs, self.window,
                                                 limit=10),
                "pending": [o.to_dict() for o in self.window.values()],
                "configs-explored": self.n_explored,
            }
            return
        del self.window[slot]
        self._free.append(slot)
        self.configs = survivors
        self.ops_checked += 1

    def advance(self) -> Optional[Dict[str, Any]]:
        """Consume the stream up to the horizon; returns the refutation
        result if this advance produced one (already stored on
        ``self.result``)."""
        if self.result is not None or self.exploded is not None:
            self._stream.clear()
            return None
        before = self.result
        try:
            self._advance()
        except SearchExploded as e:
            self.exploded = str(e)
        return self.result if self.result is not before else None

    def _advance(self) -> None:
        while self._stream and self.result is None:
            op = self._stream[0]
            if op.type == INVOKE:
                comp = self._resolution.get(op.index)
                if comp is None:
                    if not self._finalizing:
                        return  # horizon: completion class still unknown
                    # run over: the op never completed — indeterminate,
                    # exactly prepare()'s unmatched-invoke rule
                    comp = op.with_(type=INFO)
                else:
                    del self._resolution[op.index]
                self._stream.popleft()
                if comp.type == FAIL:
                    continue  # never took effect: pair removed outright
                eff = op
                if comp.type == OK and comp.value is not None:
                    eff = op.with_(value=comp.value)
                if comp.type != OK and eff.f in PURE_READ_NAMES \
                        and eff.value is None:
                    continue  # crashed read, unknown value: unconstraining
                self._enter(eff, ghost=comp.type != OK, comp=comp)
            else:
                self._stream.popleft()
                if op.type == OK:
                    slot = self._return_slot.pop(op.index, None)
                    if slot is not None:
                        self._return(slot, op)
                # fail/info completions generate no event

    # -- epoch boundary / run end -----------------------------------------
    def finalize(self) -> None:
        """The run is over: remaining open invocations resolve as
        indeterminate (ghosts), then the frontier drains completely."""
        self._finalizing = True
        self.advance()

    def pending_ops(self) -> int:
        """Invocations buffered past the horizon (not yet paid for).
        Every open invocation is necessarily still in the stream (it
        cannot be consumed before its completion class is known), so the
        stream count alone covers both the open and the blocked-behind-
        the-horizon cases."""
        return sum(1 for o in self._stream if o.type == INVOKE)

    def verdict(self) -> Dict[str, Any]:
        if self.result is not None:
            return dict(self.result)
        if self.exploded is not None:
            return {"valid": "unknown", "analyzer": "wgl-cpu",
                    "error": self.exploded,
                    "configs-explored": self.n_explored}
        return {"valid": True, "analyzer": "wgl-cpu",
                "configs-explored": self.n_explored,
                "final-configs-count": len(self.configs)}


class WglEpochEngine:
    """Per-key frontier routing for the wgl kind.

    ``independent=True`` mirrors ``independent.subhistory`` exactly: ops
    route by their ``(key, value)`` tuple's key, values are unwrapped,
    unkeyed client ops are dropped (as the cold per-key split drops
    them); nemesis ops never reach a frontier (prepare strips them).

    ``model`` may be a host :class:`Model` or a registered device-model
    name (the engine plugin seam): a string resolves through
    ``models.get_model(name)`` and the frontier runs its host oracle —
    so any model added as an engine plugin is monitorable for free."""

    def __init__(self, model, independent: bool = False,
                 max_configs: int = 2_000_000, keep_prefix: bool = False):
        if isinstance(model, str):
            from jepsen_tpu.models import get_model
            model = get_model(model).cpu_model()
        self.model = model
        self.independent = independent
        self.max_configs = max_configs
        self.keep_prefix = keep_prefix
        self.frontiers: Dict[Any, KeyFrontier] = {}

    def feed(self, ops: List[Op]) -> None:
        for op in ops:
            if op.process == NEMESIS:
                continue
            if self.independent:
                k = key_of(op)
                if k is None:
                    continue
                op = op.with_(value=op.value[1])
            else:
                k = None
            f = self.frontiers.get(k)
            if f is None:
                f = self.frontiers[k] = self._new_frontier()
            f.feed(op)

    def _new_frontier(self):
        """Frontier factory — the stream-engine seam.  The device-resident
        tier (engine/stream.py's ``StreamWglEpochEngine``) overrides this
        to hand out ``DeviceKeyFrontier`` facades; everything else about
        per-key routing is shared."""
        return KeyFrontier(self.model, max_configs=self.max_configs,
                           keep_prefix=self.keep_prefix)

    def advance(self) -> List[Any]:
        """Advance every frontier; returns the keys newly refuted by this
        epoch (their results are on the frontiers)."""
        refuted = []
        for k, f in self.frontiers.items():
            if f.advance() is not None:
                refuted.append(k)
        return refuted

    def finalize(self) -> None:
        for f in self.frontiers.values():
            f.finalize()

    def counters(self) -> Dict[str, int]:
        return {
            "keys": len(self.frontiers),
            "ops-entered": sum(f.ops_entered
                               for f in self.frontiers.values()),
            "ops-checked": sum(f.ops_checked
                               for f in self.frontiers.values()),
            "configs-explored": sum(f.n_explored
                                    for f in self.frontiers.values()),
            "pending-ops": sum(f.pending_ops()
                               for f in self.frontiers.values()),
        }


class ElleEpochEngine:
    """Accumulates the completed-txn prefix and re-derives the dependency
    graph each epoch (ingest is incremental; the graph check covers the
    accumulated prefix).  Pending invocations are included as ``info``
    txns so the prefix is a legitimate run-ended-here history."""

    def __init__(self, workload: str = "list-append",
                 realtime: bool = False, service=None,
                 budget_s: Optional[float] = None):
        self.workload = workload
        self.realtime = realtime
        self.service = service
        self.budget_s = budget_s
        self._ops: List[Op] = []            # arrival-order client ops
        self._open: Dict[Any, Op] = {}      # process -> open invocation
        self._epochs = 0                    # completed epoch checks
        self.new_since_check = 0
        self.checked_ops = 0                # prefix length at last check
        self.result: Optional[Dict[str, Any]] = None
        self.last: Optional[Dict[str, Any]] = None

    def feed(self, ops: List[Op]) -> None:
        for op in ops:
            if op.process == NEMESIS:
                continue
            self._ops.append(op)
            if op.type == INVOKE:
                self._open[op.process] = op
            else:
                self._open.pop(op.process, None)
            self.new_since_check += 1

    def _prefix(self) -> History:
        cut = list(self._ops)
        # The cut txns carry the 1-based epoch index as a trailing
        # ``["monitor-cut", None, epoch]`` micro-op, so resumed/forensic
        # histories can attribute WHICH epoch cut them (the cuts are
        # otherwise indistinguishable).  Safe for the analyzers: micro-op
        # fs they don't know are skipped, and info txns only contribute
        # their write mops.
        marker = ["monitor-cut", None, self._epochs + 1]
        for inv in self._open.values():
            val = (list(inv.value) + [marker]
                   if isinstance(inv.value, (list, tuple)) else [marker])
            cut.append(inv.with_(type=INFO, error=":monitor-cut",
                                 value=val))
        return History(cut, reindex=True)

    def _check(self, h: History) -> Dict[str, Any]:
        if self.service is not None:
            return self.service.check(h, kind="elle",
                                      workload=self.workload,
                                      realtime=self.realtime,
                                      deadline_s=self.budget_s)
        from jepsen_tpu.elle_tpu.engine import check_batch
        return check_batch([h], workload=self.workload,
                           realtime=self.realtime,
                           budget_s=self.budget_s)[0]

    def advance(self) -> Optional[Dict[str, Any]]:
        """Check the accumulated prefix; returns a refutation result the
        first time the prefix goes definitely invalid."""
        if self.result is not None or not self.new_since_check:
            return None
        h = self._prefix()
        self._epochs += 1
        self.new_since_check = 0
        self.checked_ops = len(self._ops)
        try:
            res = self._check(h)
        except Exception as e:  # noqa: BLE001 — a check crash never ends
            self.last = {"valid": "unknown", "error": str(e)}
            return None
        self.last = res
        if res.get("valid") is False:
            last_done = max((o.index for o in self._ops
                             if o.type != INVOKE), default=None)
            self.result = {**res, "op-index": last_done}
            return self.result
        return None

    def finalize(self) -> None:
        # The authoritative elle verdict comes from the offline path over
        # the full history (the graph is not prefix-resumable); nothing
        # to drain here beyond the early-refutation state we already hold.
        pass

    def counters(self) -> Dict[str, int]:
        return {"ops-ingested": len(self._ops),
                "ops-at-last-check": self.checked_ops,
                "pending-ops": len(self._open)}
