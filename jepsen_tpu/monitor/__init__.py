"""Online monitoring: incremental checking riding the run's op stream.

The framework's post-hoc shape — run for minutes, then check — burns
wall clock on runs that are already doomed: a history that violates
linearizability at op 900 keeps generating ops until the time limit,
then pays a cold full-history check.  The monitor turns the checker
into a live oracle (see docs/monitoring.md):

- the interpreter's scheduler loop taps every op it appends into a
  bounded ring buffer (:mod:`tap` — the run never blocks on the
  monitor);
- a flusher thread drains the tap on an epoch cadence into incremental
  per-key checker state (:mod:`epochs` — the WGL configuration frontier
  or the Elle completed-prefix), so each epoch pays only for new ops;
- a refuting epoch goes through the verdict channel (:mod:`verdict`):
  confirmed via the serve.CheckService lanes when one is attached,
  recorded with the refuting op index, snapshotted to the store, and —
  with the ``monitor_abort`` test opt — the generator is cut so the run
  ends early;
- at analyze time the final authoritative check *resumes* from the
  monitor's frontier (:mod:`resume`) instead of re-checking from op 0:
  same verdict as the cold offline check by construction, paying only
  for the ops after the last monitor epoch.

Invariant inherited from the rest of the stack: partial state never
degrades a verdict toward ``false``.  Dropped tap ops disable
refutation and resume (the analyze phase falls back to the cold path);
an exploded frontier yields ``unknown`` for its key; an unconfirmed
refutation never aborts the run.

Usage — test opts (all wired through cli.py)::

    test["monitor"] = True          # enable (needs a monitorable checker)
    test["monitor_epoch"] = 256     # epoch size in ops (default 256)
    test["monitor_abort"] = True    # cut the generator on refutation
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Dict, List, Optional

import os

from jepsen_tpu.history import History, Op
from jepsen_tpu.monitor.epochs import ElleEpochEngine, WglEpochEngine
from jepsen_tpu.monitor.tap import DEFAULT_CAPACITY, OpTap
from jepsen_tpu.monitor.verdict import VerdictChannel
from jepsen_tpu.obs.hist import observe_monitor_epoch
from jepsen_tpu.obs.recorder import RECORDER
from jepsen_tpu.obs.telemetry import set_gauge
from jepsen_tpu.serve.metrics import mono_now


def stream_engine_enabled() -> bool:
    """The ``JTPU_STREAM_ENGINE`` knob, read at call time (tests and the
    CLI flip it per monitor): route epoch advances through the
    device-resident stream tier (engine/stream.py wgl frontiers,
    elle_tpu/incremental.py extended closures).  Off by default — the
    host tier stays the reference; the stream tier degrades back to it
    per frontier on any device trouble."""
    return os.environ.get("JTPU_STREAM_ENGINE", "") not in ("", "0",
                                                            "false", "off")

logger = logging.getLogger("jepsen.monitor")

DEFAULT_EPOCH_OPS = 256
DEFAULT_EPOCH_S = 1.0

# Live monitors, for web.py's /monitor endpoint (a run registers its
# monitor while active; the last few finished ones keep their final
# status visible).
_ACTIVE: Dict[int, "Monitor"] = {}
_RECENT: deque = deque(maxlen=8)
_REG_LOCK = threading.Lock()
_ids = iter(range(1, 1 << 62))


def active_statuses() -> List[Dict[str, Any]]:
    # snapshot the membership under the registry lock, but build each
    # status OUTSIDE it: status() takes the flush lock, which sits
    # ABOVE monitor-registry in the manifest
    with _REG_LOCK:
        live_monitors = list(_ACTIVE.values())
        recent = list(_RECENT)
    return [m.status() for m in live_monitors] + recent


class Monitor:
    """One run's online monitor: tap -> epochs -> verdict -> resume."""

    def __init__(self, *, kind: str,
                 model=None, jax_model=None,
                 workload: str = "list-append", realtime: bool = False,
                 independent: bool = False,
                 epoch_ops: int = DEFAULT_EPOCH_OPS,
                 epoch_s: float = DEFAULT_EPOCH_S,
                 service=None, abort: bool = False,
                 tap_capacity: int = DEFAULT_CAPACITY,
                 max_configs: int = 2_000_000,
                 store_dir: Optional[str] = None,
                 budget_s: Optional[float] = None,
                 name: str = "monitor"):
        if kind not in ("wgl", "elle"):
            raise ValueError(f"unknown monitor kind {kind!r}")
        self.id = next(_ids)
        self.name = name
        self.kind = kind
        self.independent = independent
        self.jax_model = jax_model
        self.epoch_ops = max(1, int(epoch_ops))
        self.epoch_s = epoch_s
        self.service = service
        self.store_dir = store_dir
        self.tap = OpTap(tap_capacity)
        streaming = stream_engine_enabled()
        if kind == "wgl":
            if streaming and jax_model is not None:
                from jepsen_tpu.engine.stream import StreamWglEpochEngine
                self.engine = StreamWglEpochEngine(
                    model, jax_model=jax_model, independent=independent,
                    max_configs=max_configs,
                    keep_prefix=service is not None, service=service)
            else:
                self.engine = WglEpochEngine(
                    model, independent=independent,
                    max_configs=max_configs,
                    keep_prefix=service is not None)
        else:
            if streaming:
                from jepsen_tpu.elle_tpu.incremental import \
                    IncrementalElleEngine
                self.engine = IncrementalElleEngine(workload=workload,
                                                    realtime=realtime,
                                                    service=service,
                                                    budget_s=budget_s)
            else:
                self.engine = ElleEpochEngine(workload=workload,
                                              realtime=realtime,
                                              service=service,
                                              budget_s=budget_s)
        self.channel = VerdictChannel(abort=abort, store_dir=store_dir,
                                      service=service)
        self.epochs: List[Dict[str, Any]] = []
        self.t0 = mono_now()
        self.finalized = False
        self.final_delta: Optional[Dict[str, Any]] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._flush_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self.tap.bind_wake(self._wake, self.epoch_ops)

    # -- construction from a test map -------------------------------------
    @classmethod
    def from_test(cls, test: Dict[str, Any],
                  service=None) -> Optional["Monitor"]:
        """Build a monitor for a test map, or None when the test didn't
        ask for one / its checker has no monitorable core."""
        if not test.get("monitor"):
            return None
        checker = test.get("checker")
        if checker is None:
            return None
        from jepsen_tpu.checker.core import Checker, resolve_checker
        if not isinstance(checker, Checker):
            checker = resolve_checker(checker)
        spec = cls._monitorable(checker)
        if spec is None:
            logger.warning("monitor requested but checker %r has no "
                           "monitorable core; running unmonitored",
                           type(checker).__name__)
            return None
        return cls(service=service if service is not None
                   else test.get("service"),
                   epoch_ops=int(test.get("monitor_epoch")
                                 or DEFAULT_EPOCH_OPS),
                   abort=bool(test.get("monitor_abort")),
                   store_dir=test.get("store_dir"),
                   budget_s=test.get("checker_budget_s"),
                   name=test.get("name", "monitor"),
                   **spec)

    @staticmethod
    def _monitorable(checker) -> Optional[Dict[str, Any]]:
        """Map a checker onto a monitor spec: Linearizable (host model
        required — the frontier is the host search), an IndependentChecker
        around one, an ElleChecker, or the first monitorable child of a
        Compose."""
        from jepsen_tpu.checker.core import Compose
        from jepsen_tpu.checker.linearizable import Linearizable
        from jepsen_tpu.independent import IndependentChecker
        if isinstance(checker, Compose):
            for c in checker.checkers.values():
                spec = Monitor._monitorable(c)
                if spec is not None:
                    return spec
            return None
        if isinstance(checker, IndependentChecker):
            inner = checker.inner
            if isinstance(inner, Linearizable) \
                    and inner._cpu_model() is not None:
                return {"kind": "wgl", "model": inner._cpu_model(),
                        "jax_model": inner._jax_model(),
                        "independent": True}
            return None
        if isinstance(checker, Linearizable):
            if checker._cpu_model() is None:
                return None
            return {"kind": "wgl", "model": checker._cpu_model(),
                    "jax_model": checker._jax_model()}
        try:
            from jepsen_tpu.checker.elle import ElleChecker
        except Exception:  # noqa: BLE001
            return None
        if isinstance(checker, ElleChecker):
            return {"kind": "elle", "workload": checker.workload,
                    "realtime": checker.realtime,
                    "budget_s": checker.budget_s}
        return None

    # -- the run-side surface (called from the scheduler loop) ------------
    def offer(self, op: Op) -> None:
        self.tap.offer(op)

    def should_abort(self) -> bool:
        return self.channel.should_abort()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Monitor":
        with _REG_LOCK:
            _ACTIVE[self.id] = self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"jepsen-monitor-{self.id}")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.epoch_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — the run must not care
                logger.exception("monitor flush failed")

    def flush(self) -> Optional[Dict[str, Any]]:
        """Drain the tap and advance the incremental state by one epoch.
        Returns the epoch record when new ops were processed."""
        with self._flush_lock:
            ops = self.tap.drain()
            if not ops:
                return None
            t_start = mono_now()
            self.engine.feed(ops)
            n = len(self.epochs) + 1
            refutations = self._advance(n)
            wall = mono_now() - t_start
            rec = {"epoch": n, "t": round(mono_now() - self.t0, 6),
                   "new-ops": len(ops), **self.engine.counters()}
            if refutations:
                rec["refuted"] = refutations
            self.epochs.append(rec)
        # Instrumentation rides outside the flush lock (recorder and
        # gauge table are leaf locks, but there is no reason to hold the
        # epoch state across them): one "monitor" span per epoch in the
        # flight recorder — visible in the merged Perfetto export — and
        # the monitor-lag gauge (ops accepted but not yet folded into a
        # verdict epoch) for the telemetry plane.
        pending = int(rec.get("pending-ops", 0))
        set_gauge("epochs-behind-live", pending)
        # per-stream lag, measured in epochs (ceil of pending / epoch
        # size) — the unit the monitor-lag SLO burns in — plus the
        # epoch-wall histogram the stream bench reads for flatness
        set_gauge(f"monitor-lag-epochs:{self.name}",
                  -(-pending // self.epoch_ops))
        observe_monitor_epoch(f"monitor-epoch:{self.kind}:{self.name}",
                              wall)
        RECORDER.record(
            "monitor", f"epoch:{self.kind}:{self.name}:{n}", dur_s=wall,
            args={"epoch": n, "new-ops": rec["new-ops"],
                  "pending-ops": rec.get("pending-ops", 0),
                  "refuted": bool(refutations)})
        return rec

    def _advance(self, epoch: int) -> List[Any]:
        if self.kind == "wgl":
            refuted_keys = self.engine.advance()
            for k in refuted_keys:
                f = self.engine.frontiers[k]
                prefix = History(list(f.prefix)) if f.prefix else None
                self.channel.report(kind="wgl", key=k, result=f.result,
                                    epoch=epoch, prefix=prefix,
                                    model=self.jax_model)
            return refuted_keys
        res = self.engine.advance()
        if res is not None:
            self.channel.report(kind="elle", key=None, result=res,
                                epoch=epoch)
            return [None]
        return []

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)

    def finalize(self) -> None:
        """Run over: stop the flusher, drain the tail, settle per-key
        verdicts, persist the checkpoint.  The tail consumed here is
        exactly what the resumed final check re-checks — everything
        before it was already paid for during the run."""
        if self.finalized:
            return
        self.stop()
        with self._flush_lock:
            pre = self.engine.counters()
            ops = self.tap.drain()
            if ops:
                self.engine.feed(ops)
            n = len(self.epochs) + 1
            refutations = self._advance(n) if ops else []
            self.engine.finalize()
            if self.kind == "wgl":
                # finalize() can itself refute (ghost-closing the tail)
                for k, f in self.engine.frontiers.items():
                    if f.result is not None and k not in refutations:
                        prefix = History(list(f.prefix)) if f.prefix \
                            else None
                        self.channel.report(kind="wgl", key=k,
                                            result=f.result, epoch=n,
                                            prefix=prefix,
                                            model=self.jax_model)
            post = self.engine.counters()
            self.final_delta = {
                "tail-ops": len(ops),
                **{k: post.get(k, 0) - pre.get(k, 0)
                   for k in ("ops-checked", "ops-entered",
                             "configs-explored") if k in post},
            }
            self.finalized = True
            tail = len(ops)
            # final drain folded everything in: the lag gauge settles
            # at the engine's residual (0 for wgl, open invocations for
            # elle) — read from `post`, sampled under the flush lock
            residual = int(post.get("pending-ops", 0))
        set_gauge("epochs-behind-live", residual)
        set_gauge(f"monitor-lag-epochs:{self.name}",
                  -(-residual // self.epoch_ops))
        RECORDER.record(
            "monitor", f"epoch:{self.kind}:{self.name}:final",
            args={"tail-ops": tail})
        from jepsen_tpu.monitor import resume
        resume.save(self)
        snap = self.status()      # takes the flush lock: build it
        snap["active"] = False    # BEFORE entering the registry lock;
        with _REG_LOCK:           # the retained snapshot describes the
            _ACTIVE.pop(self.id, None)   # deregistered state
            _RECENT.appendleft(snap)

    def close(self) -> None:
        """Idempotent teardown (also safe before finalize on a crashed
        run): stops the flusher and deregisters."""
        self.stop()
        snap = self.status()      # flush lock sits above _REG_LOCK
        snap["active"] = False
        with _REG_LOCK:
            if self.id in _ACTIVE:
                _RECENT.appendleft(snap)
            _ACTIVE.pop(self.id, None)

    # -- observability ----------------------------------------------------
    @property
    def poisoned(self) -> Optional[str]:
        """Why refutation/resume is disabled, or None when sound."""
        if self.tap.dropped:
            return f"tap dropped {self.tap.dropped} op(s): the monitored " \
                   f"stream has a gap"
        return None

    def status(self) -> Dict[str, Any]:
        # built under the flush lock: the epoch ring and engine
        # frontiers are mutated by flush() under the same lock, so this
        # is a consistent point-in-time view.  The verdict/tap locks
        # acquired by channel.status()/tap.stats() sit BELOW
        # monitor-flush in the manifest, so holding flush here is safe;
        # callers must NOT hold monitor-registry (it orders after flush)
        with self._flush_lock:
            return {
                "id": self.id,
                "name": self.name,
                "kind": self.kind,
                "independent": self.independent,
                "active": self.id in _ACTIVE,
                "finalized": self.finalized,
                "t": round(mono_now() - self.t0, 6),
                "epoch-ops": self.epoch_ops,
                "epochs": len(self.epochs),
                "last-epoch": self.epochs[-1] if self.epochs else None,
                "counters": self.engine.counters(),
                "tap": self.tap.stats(),
                "poisoned": self.poisoned,
                "verdict": self.channel.status(),
                "final-delta": self.final_delta,
            }
