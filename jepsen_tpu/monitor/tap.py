"""The op tap: a bounded, non-blocking ring buffer on the run's op stream.

The interpreter's scheduler loop calls :meth:`OpTap.offer` for every op it
appends to the history (invocations and completions alike).  The contract
is one-sided by design: **the run never blocks on the monitor**.  ``offer``
takes one short lock, appends, and returns — no allocation beyond the
deque node, no waiting, no exceptions escaping into the scheduler.  The
monitor's flusher thread drains the buffer on its own cadence.

When the flusher falls behind and the buffer fills, new ops are *dropped*
(and counted) rather than stalling the run or evicting older ops — older
ops are the ones the incremental frontier still needs, and a gap anywhere
in the stream poisons the monitor's ability to refute (a refutation is
only sound on a contiguous prefix).  The drop counter is therefore also
the monitor's "refutations disabled" signal: any drop makes the verdict
channel report ``unknown`` at worst, never ``false`` — the
never-false-on-partial-state invariant starts here.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from jepsen_tpu.history import Op

DEFAULT_CAPACITY = 1 << 16


class OpTap:
    """Bounded MPSC op buffer between the run and the monitor flusher."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = max(1, int(capacity))
        self._buf: deque = deque()
        self._lock = threading.Lock()
        self.offered = 0
        self.dropped = 0
        self._wake: Optional[threading.Event] = None
        self._wake_at = self.capacity  # backlog that triggers a wake

    def bind_wake(self, event: threading.Event, backlog: int) -> None:
        """Ask the tap to set ``event`` once the backlog reaches
        ``backlog`` ops (the monitor's epoch size), so the flusher wakes
        on data rather than polling a short timer."""
        self._wake = event
        self._wake_at = max(1, int(backlog))

    def offer(self, op: Op) -> bool:
        """Append one op; False (and a counted drop) when full.  Never
        blocks, never raises."""
        with self._lock:
            self.offered += 1
            if len(self._buf) >= self.capacity:
                self.dropped += 1
                return False
            self._buf.append(op)
            backlog = len(self._buf)
        if self._wake is not None and backlog >= self._wake_at:
            self._wake.set()
        return True

    def drain(self) -> List[Op]:
        """Take everything buffered, in offer order."""
        with self._lock:
            out = list(self._buf)
            self._buf.clear()
        return out

    def backlog(self) -> int:
        with self._lock:
            return len(self._buf)

    def stats(self) -> dict:
        with self._lock:
            return {"offered": self.offered, "dropped": self.dropped,
                    "backlog": len(self._buf), "capacity": self.capacity}
