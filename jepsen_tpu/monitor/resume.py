"""Resume the final authoritative check from the monitor's last epoch.

The monitor's WGL frontier IS the checker's search — same closure, same
event preparation, same configuration sets (epochs.py documents the
parity argument).  So when the run ends, ``core.analyze`` does not need
to re-check from op 0: :func:`resume_final_check` finalizes the frontier
(consuming only the tail ops that arrived after the last monitor epoch)
and assembles the verdict from per-key frontier state.  The verdict is
the cold offline verdict by construction; the work is proportional to
the tail.

Strictness over savings: any condition that could make the resumed
verdict diverge from the cold one returns ``None`` and the caller runs
the cold path — a gap in the tapped stream (dropped ops), an op-count
mismatch between tap and history, a checker shape the monitor wasn't
built from, an elle monitor (the dependency graph is not
prefix-resumable, so elle's authoritative verdict always comes from the
offline full-history path).  And per the framework-wide invariant, a
resumed verdict is never ``false`` except from an actual frontier
refutation — exploded or partial keys degrade to ``unknown``.

A ``Compose`` — the shape every suite builds (stats + workload + perf) —
resumes through its *monitored* child: the child the monitor was built
from (``Monitor._monitorable``'s first-match order) gets the resumed
verdict, every sibling runs its normal cold check, and the results merge
under Compose's own semantics (same ``merge_valid``, same crashed-child
surfacing).  The siblings were never covered by the monitor, so nothing
is resumed for them — only the expensive linearizability search skips
its re-check.

:func:`save` persists a ``monitor.json`` checkpoint into the run's store
directory (atomic write — a torn checkpoint must never shadow a good
one) recording epochs, counters, per-key verdicts, and the refutation
record; :func:`load` reads it back.  The checkpoint is the *artifact*
trail (web UI, post-mortems, the smoke script's metrics dump); the
in-process resume path uses the live monitor object on
``test["_monitor"]``.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, Optional

from jepsen_tpu.checker.core import UNKNOWN, merge_valid
from jepsen_tpu.history import History

logger = logging.getLogger("jepsen.monitor")

CHECKPOINT = "monitor.json"
VERSION = 1


# ---------------------------------------------------------------------------
# Checkpoint persistence

def save(monitor) -> Optional[str]:
    """Write the monitor checkpoint into its store dir; returns the path
    (None when the monitor has no store dir).  Best-effort: a checkpoint
    write failure never fails the run."""
    if not monitor.store_dir:
        return None
    record = checkpoint_record(monitor)
    path = os.path.join(monitor.store_dir, CHECKPOINT)
    try:
        from jepsen_tpu.atomic_io import atomic_write
        os.makedirs(monitor.store_dir, exist_ok=True)
        atomic_write(path, lambda f: json.dump(record, f, indent=2,
                                               default=str))
    except Exception:  # noqa: BLE001
        logger.exception("writing monitor checkpoint")
        return None
    return path


def checkpoint_record(monitor) -> Dict[str, Any]:
    rec = {
        "version": VERSION,
        "kind": monitor.kind,
        "independent": monitor.independent,
        "finalized": monitor.finalized,
        "epoch-ops": monitor.epoch_ops,
        "epochs": list(monitor.epochs),
        "counters": monitor.engine.counters(),
        "tap": monitor.tap.stats(),
        "poisoned": monitor.poisoned,
        "verdict": monitor.channel.status(),
        "final-delta": monitor.final_delta,
    }
    if monitor.kind == "wgl":
        rec["keys"] = {repr(k): f.verdict()
                       for k, f in monitor.engine.frontiers.items()}
    return rec


def load(store_dir: str) -> Optional[Dict[str, Any]]:
    """Read a run's monitor checkpoint, or None when absent/unreadable."""
    path = os.path.join(store_dir, CHECKPOINT)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# The resumed final check

def resume_final_check(test, checker, history: History, monitor,
                       opts=None) -> Optional[Dict[str, Any]]:
    """Produce the final verdict from the monitor's frontier state, or
    None when the cold path must run instead (any soundness doubt)."""
    if monitor is None or monitor.kind != "wgl":
        return None
    if monitor.poisoned is not None:
        logger.warning("monitor resume disabled (%s); cold analyze",
                       monitor.poisoned)
        return None
    from jepsen_tpu.checker.core import Compose
    if isinstance(checker, Compose):
        return _resume_compose(test, checker, history, monitor, opts)
    if not _checker_matches(checker, monitor):
        return None
    if not monitor.finalized:
        monitor.finalize()
    # Defense in depth: the tap must have seen exactly the history being
    # analyzed.  A mismatch (an append site the tap missed, a re-analysis
    # of a different stored history) silently invalidates the frontier's
    # claim to cover this history — fall back cold.
    if monitor.tap.offered != len(history):
        logger.warning(
            "monitor tap saw %d op(s) but the analyzed history has %d; "
            "cold analyze", monitor.tap.offered, len(history))
        return None

    frontiers = monitor.engine.frontiers
    per_key = {k: f.verdict() for k, f in frontiers.items()}
    valid = merge_valid([r.get("valid") for r in per_key.values()])
    delta = monitor.final_delta or {}
    meta = {
        "analyzer": "monitor-resume",
        "resumed-from-epoch": len(monitor.epochs),
        "ops-rechecked": delta.get("ops-checked", 0),
        "tail-ops": delta.get("tail-ops", 0),
        "configs-explored": sum(f.n_explored for f in frontiers.values()),
    }
    if monitor.independent:
        bad = {k: r for k, r in per_key.items()
               if r.get("valid") is not True}
        return {"valid": valid,
                "key-count": len(frontiers),
                "results": per_key,
                "failures": sorted(bad, key=repr),
                **meta}
    f = frontiers.get(None)
    if f is None:
        # No client ops ever reached the frontier: an empty history is
        # vacuously linearizable, same as the cold checker's answer.
        return {"valid": True, **meta}
    return {**f.verdict(), **meta}


def _resume_compose(test, checker, history: History, monitor,
                    opts=None) -> Optional[Dict[str, Any]]:
    """Resume a Compose: the monitored child resumes from frontier state,
    every sibling runs its normal cold check concurrently, and the merge
    is exactly ``Compose.check``'s (merge_valid over children, crashed
    children surfaced under ``errors``).  None — whole compose goes
    cold — when no child resumes, so a partially-resumed compose can
    never diverge from the cold verdict."""
    from concurrent.futures import ThreadPoolExecutor

    from jepsen_tpu.checker.core import check_safe
    from jepsen_tpu.monitor import Monitor

    # Mirror Monitor._monitorable's selection: the monitor was built from
    # the first child (dict order, depth-first) with a monitorable spec.
    target = next((n for n, c in checker.checkers.items()
                   if Monitor._monitorable(c) is not None), None)
    if target is None:
        return None
    resumed = resume_final_check(test, checker.checkers[target], history,
                                 monitor, opts)
    if resumed is None:
        return None
    opts = dict(opts or {})
    if checker.budget_s is not None and "budget_s" not in opts:
        opts["budget_s"] = checker.budget_s
    rest = [n for n in checker.checkers if n != target]
    results = {}
    if rest:
        with ThreadPoolExecutor(max_workers=len(rest)) as ex:
            futs = {n: ex.submit(check_safe, checker.checkers[n], test,
                                 history, opts)
                    for n in rest}
            results = {n: f.result() for n, f in futs.items()}
    results[target] = resumed
    out = {"valid": merge_valid([r.get("valid")
                                 for r in results.values()]),
           **{n: results[n] for n in checker.checkers},
           "analyzer": "monitor-resume",
           "monitored-child": target,
           "resumed-from-epoch": resumed.get("resumed-from-epoch"),
           "ops-rechecked": resumed.get("ops-rechecked"),
           "tail-ops": resumed.get("tail-ops")}
    crashed = {n: r["traceback"] for n, r in results.items()
               if r.get("valid") == UNKNOWN and "traceback" in r}
    if crashed:
        out["errors"] = crashed
    return out


def _checker_matches(checker, monitor) -> bool:
    """The resumed verdict only stands in for checkers whose cold path is
    exactly the search the frontier ran: a bare Linearizable (host model)
    or an IndependentChecker around one, matching the monitor's per-key
    mode (Compose routes through :func:`_resume_compose` before reaching
    here).  Everything else goes cold."""
    from jepsen_tpu.checker.linearizable import Linearizable
    from jepsen_tpu.independent import IndependentChecker
    if isinstance(checker, IndependentChecker):
        return monitor.independent \
            and isinstance(checker.inner, Linearizable) \
            and checker.inner._cpu_model() is not None
    if isinstance(checker, Linearizable):
        return not monitor.independent \
            and checker._cpu_model() is not None
    return False
