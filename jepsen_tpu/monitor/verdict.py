"""The early-refutation channel: confirm, record, snapshot, maybe abort.

A monitor epoch that refutes hands its result here.  The channel:

1. **confirms** the refutation when a serve.CheckService is attached —
   the refuted key's consumed prefix is re-submitted through the service
   lanes, so the device engine independently re-derives the verdict
   before anything irreversible (an abort) happens.  Without a service,
   a WGL frontier refutation is already the host oracle's own verdict
   (the frontier *is* wgl_cpu's search) and counts as confirmed; elle
   epoch results already came through an engine.  A disagreeing
   confirmation leaves the finding recorded as *unconfirmed* and never
   fires the abort — the never-false-on-partial-state invariant applies
   to the run-control side effects too.
2. **records** the refuting op index and result, exposed on the monitor
   status (web ``/monitor``) and in the resume checkpoint.
3. **snapshots** a ``monitor-refutation.json`` artifact into the run's
   store directory via the atomic writers (a torn write must never
   shadow a complete refutation record).
4. optionally signals the interpreter to **abort** the run
   (``monitor_abort`` test opt): the generator is cut, outstanding ops
   drain, and the run proceeds straight to the authoritative check.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Optional

logger = logging.getLogger("jepsen.monitor")


class VerdictChannel:
    def __init__(self, abort: bool = False,
                 store_dir: Optional[str] = None, service=None):
        self.abort_enabled = abort
        self.store_dir = store_dir
        self.service = service
        self.refuted = threading.Event()
        self.verdict: Optional[Dict[str, Any]] = None
        self.unconfirmed: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()

    # -- the refutation path ----------------------------------------------
    def report(self, *, kind: str, key: Any, result: Dict[str, Any],
               epoch: int, prefix=None, model=None) -> bool:
        """Handle one epoch refutation; True if it was confirmed (and the
        channel is now refuted).  ``prefix`` is the refuted key's consumed
        op prefix (a History) for service confirmation, when available."""
        with self._lock:
            if self.verdict is not None:
                return True  # already refuted; first finding stands
        confirmed, confirmation = self._confirm(kind, result, prefix, model)
        record = {
            "kind": kind,
            "key": key,
            "epoch": epoch,
            "op-index": result.get("op-index"),
            "confirmed": confirmed,
            "result": result,
        }
        if confirmation is not None:
            record["confirmation"] = confirmation
        with self._lock:
            if self.verdict is not None:
                return True
            if not confirmed:
                self.unconfirmed = record
            else:
                self.verdict = record
                self.refuted.set()
        self._snapshot(record)
        if confirmed:
            logger.error(
                "monitor refuted the run at epoch %d (key=%r, op-index=%s)%s",
                epoch, key, result.get("op-index"),
                "; aborting generator" if self.abort_enabled else "")
        else:
            logger.warning(
                "monitor found an UNCONFIRMED refutation at epoch %d "
                "(key=%r); not aborting", epoch, key)
        return confirmed

    def _confirm(self, kind, result, prefix, model):
        """Independent re-derivation through the service lanes (device
        engine), when possible.  Unknown/crashed confirmations do not
        veto: the host refutation stands (the host frontier is the
        oracle); only a definite ``valid=True`` disagreement blocks."""
        if self.service is None or prefix is None or kind != "wgl":
            return True, None
        try:
            res = self.service.check(prefix, kind="wgl", model=model,
                                     timeout=60.0)
        except Exception as e:  # noqa: BLE001 — service trouble never vetoes
            return True, {"valid": "unknown", "error": str(e)}
        if res.get("valid") is True:
            return False, res
        return True, res

    # -- run control ------------------------------------------------------
    def should_abort(self) -> bool:
        return self.abort_enabled and self.refuted.is_set()

    # -- artifacts --------------------------------------------------------
    def _snapshot(self, record: Dict[str, Any]) -> None:
        if not self.store_dir:
            return
        try:
            from jepsen_tpu.atomic_io import atomic_write
            path = os.path.join(self.store_dir, "monitor-refutation.json")
            atomic_write(path, lambda f: json.dump(record, f, indent=2,
                                                   default=str))
        except Exception:  # noqa: BLE001 — artifacts never mask the run
            logger.exception("writing monitor refutation snapshot")

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "refuted": self.refuted.is_set(),
                "abort-enabled": self.abort_enabled,
                "verdict": {k: v for k, v in (self.verdict or {}).items()
                            if k != "result"} or None,
                "unconfirmed": bool(self.unconfirmed),
            }
