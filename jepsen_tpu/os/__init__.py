"""OS protocol — preparing cluster nodes' operating systems.

Parity: jepsen.os (jepsen/src/jepsen/os.clj:4-8) plus the distro
implementations (os/debian.clj, os/centos.clj, os/ubuntu.clj).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

from jepsen_tpu.control import Session, session


class OS:
    def setup(self, test: Dict[str, Any], node: str) -> None:
        """Prepare the OS: packages, hostnames, users."""

    def teardown(self, test: Dict[str, Any], node: str) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS


class Debian(OS):
    """Debian/Ubuntu node prep (os/debian.clj:13-197): apt packages,
    /etc/hosts population."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test, node):
        s = session(test, node).sudo()
        s.env(DEBIAN_FRONTEND="noninteractive").exec(
            "apt-get", "install", "-y", "--no-install-recommends",
            "curl", "wget", "unzip", "iptables", "iproute2", "psmisc",
            "gcc", "libc6-dev", *self.packages)
        self._setup_hosts(test, s)

    def _setup_hosts(self, test, s: Session):
        nodes = test.get("nodes") or []
        lines = []
        for n in nodes:
            ip = self.ip_of(s, n)
            if ip:
                lines.append(f"{ip} {n}")
        if lines:
            from jepsen_tpu.control import util as cu
            hosts = s.exec("cat", "/etc/hosts")
            add = [l for l in lines if l not in hosts]
            if add:
                s.exec("tee", "-a", "/etc/hosts",
                       stdin="\n".join(add) + "\n")

    @staticmethod
    def ip_of(s: Session, hostname: str):
        """Resolve a hostname from the node (control/net.clj:19-38)."""
        r = s.exec_result("getent", "hosts", hostname)
        if r.ok and r.out.strip():
            return r.out.split()[0]
        return None


debian = Debian


class Ubuntu(Debian):
    """Ubuntu node prep (os/ubuntu.clj — a Debian variant that also ensures
    the deadline scheduler / ntp bits cockroach wants; here: apt update
    before install)."""

    def setup(self, test, node):
        s = session(test, node).sudo()
        s.env(DEBIAN_FRONTEND="noninteractive").exec_result(
            "apt-get", "update", "-y")
        super().setup(test, node)


ubuntu = Ubuntu


class Smartos(OS):
    """SmartOS node prep (os/smartos.clj): pkgin packages and a loopback
    hostfile entry for the local hostname."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test, node):
        s = session(test, node).sudo()
        self._setup_hostfile(s)
        if self._stale_pkgin(s):
            s.exec("pkgin", "update")
        if self.packages:
            s.exec("pkgin", "-y", "install", *self.packages)

    def _setup_hostfile(self, s: Session):
        # Append the local hostname to the 127.0.0.1 line if missing
        # (smartos.clj:13-26).
        name = s.exec("hostname").strip()
        hosts = s.exec("cat", "/etc/hosts")
        out = []
        for line in hosts.splitlines():
            if line.startswith("127.0.0.1") and name not in line.split():
                line = f"{line} {name}"
            out.append(line)
        new = "\n".join(out)
        if new != hosts:
            s.exec("tee", "/etc/hosts", stdin=new + "\n")

    @staticmethod
    def _stale_pkgin(s: Session) -> bool:
        """Has pkgin update run within a day? (smartos.clj:28-40).  POSIX
        find -mtime, since illumos stat has no GNU -c."""
        r = s.exec_result(
            "bash", "-c",
            "find /var/db/pkgin/sql.log -mtime +0 2>/dev/null")
        return (not r.ok) or bool(r.out.strip())


smartos = Smartos


class Centos(OS):
    """RHEL-family prep (os/centos.clj): yum packages."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test, node):
        s = session(test, node).sudo()
        s.exec("yum", "install", "-y",
               "curl", "wget", "unzip", "iptables", "iproute",
               "psmisc", "gcc", *self.packages)
