"""EDN codec — byte-level (de)serialization for reference interop.

Parity: jepsen.codec (jepsen/src/jepsen/codec.clj): encode/decode values to
bytes.  We add an EDN *writer* to complement the reader in history.py, so
histories round-trip with reference-format tooling (history.edn files).
"""

from __future__ import annotations

import re
from typing import Any

from jepsen_tpu.history import History, Op, parse_edn

# EDN keyword-safe names: symbol chars only, no whitespace/delimiters.
_KEYWORD_SAFE = re.compile(r"[A-Za-z0-9*+!\-_?.%&=<>/][A-Za-z0-9*+!\-_?.#%&=<>/:']*")

KEYWORD_KEYS = {"type", "f"}


class Keyword:
    """An EDN keyword (:foo)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __repr__(self):
        return f":{self.name}"


def to_edn(value: Any) -> str:
    """Render a Python value as EDN text."""
    if isinstance(value, Keyword):
        return f":{value.name}"
    if value is None:
        return "nil"
    if value is True:
        return "true"
    if value is False:
        return "false"
    if isinstance(value, str):
        escaped = value.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (list, tuple)):
        return "[" + " ".join(to_edn(v) for v in value) + "]"
    if isinstance(value, (set, frozenset)):
        return ("#{" + " ".join(to_edn(v) for v in sorted(value, key=repr))
                + "}")
    if isinstance(value, dict):
        parts = []
        for k, v in value.items():
            # Bare-keyword a string key only when it's valid keyword syntax;
            # otherwise emit an EDN string so readers don't mis-pair the map.
            if isinstance(k, str) and _KEYWORD_SAFE.fullmatch(k):
                key = f":{k}"
            else:
                key = to_edn(k)
            parts.append(f"{key} {to_edn(v)}")
        return "{" + ", ".join(parts) + "}"
    return to_edn(repr(value))


def op_to_edn(op: Op) -> str:
    d = op.to_dict()
    out: dict = {}
    for k, v in d.items():
        if k in KEYWORD_KEYS and isinstance(v, str):
            out[k] = Keyword(v)
        elif k == "process" and v == "nemesis":
            out[k] = Keyword("nemesis")
        else:
            out[k] = v
    return to_edn(out)


def history_to_edn(history: History) -> str:
    """One op map per line, reference style."""
    return "\n".join(op_to_edn(op) for op in history) + "\n"


def encode(value: Any) -> bytes:
    return to_edn(value).encode()


def decode(data: bytes) -> Any:
    return parse_edn(data.decode())
