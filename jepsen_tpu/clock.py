"""The shared monotonic clock for every interval, deadline, and timeline.

One helper, one clock: request trace spans, scheduler aging, monitor
epochs, control-plane retry deadlines, and engine trace intervals all
stamp times off :func:`mono_now`, so a span at t=1.2s in a request trace
and a monitor epoch at t=1.2s in the same ``/metrics`` snapshot refer to
the same instant — timelines are directly comparable instead of each
subsystem free-running its own ``time.monotonic()`` call sites.

Discipline (enforced by the CONC01 lint rule, see
docs/static_analysis.md): ``time.time()`` is *wall* clock — NTP steps,
leap smears, and operator ``date`` calls move it in either direction, so
an interval or deadline computed from it can fire early, late, or never.
Inside ``jepsen_tpu/`` every interval/deadline uses :func:`mono_now`;
wall clock is reserved for user-facing timestamps (artifact metadata,
log lines) and those sites carry an explicit
``# lint: disable=CONC01(...)`` pragma.
"""

from __future__ import annotations

import time as _time


def mono_now() -> float:
    """Seconds on the process-wide monotonic clock (never steps back)."""
    return _time.monotonic()
