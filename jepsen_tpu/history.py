"""Op and history model — the substrate shared by the runtime and the checkers.

Mirrors the reference's operation model: a history is a flat sequence of op
maps, where each logical operation appears (up to) twice — once as an
``invoke`` entry when a process begins it, and once as a completion entry
(``ok`` / ``fail`` / ``info``) when the process hears back.  (Reference:
knossos op predicates used throughout jepsen/src/jepsen/checker.clj:157-159,
and history indexing at jepsen/src/jepsen/core.clj:223.)

Completion semantics (these leak into every checker, so they are fixed here):

- ``ok``    — the operation definitely took effect, exactly once, at some
              instant between its invocation and its completion.
- ``fail``  — the operation definitely did NOT take effect.
- ``info``  — indeterminate: the op may or may not have taken effect, at any
              instant from its invocation onward (the process crashed; the
              reference converts worker exceptions into ``:info`` ops at
              jepsen/src/jepsen/generator/interpreter.clj:142-157).

In addition to the friendly Python-object view (:class:`Op`, :class:`History`)
this module provides the struct-of-arrays encoding (:class:`HistorySOA`) that
the TPU checkers consume: fixed-width int32 columns, with model-specific value
encoding delegated to the model (see jepsen_tpu.models.base).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# Op
# ---------------------------------------------------------------------------

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

TYPES = (INVOKE, OK, FAIL, INFO)
TYPE_CODE = {t: i for i, t in enumerate(TYPES)}

# Reserved logical process for the nemesis, mirroring the reference where the
# nemesis runs as the :nemesis process (jepsen/src/jepsen/generator.clj:1105).
NEMESIS = "nemesis"


_OP_FIELDS = frozenset(
    ("process", "type", "f", "value", "time", "index", "error", "extra"))


@dataclass
class Op:
    """One history entry.

    ``value`` is free-form (model-specific); ``process`` is an int for client
    processes or the string ``"nemesis"``; ``time`` is nanoseconds since test
    start (relative clock, like util/relative-time in the reference).
    """

    process: Any
    type: str
    f: Any
    value: Any = None
    time: Optional[int] = None
    index: Optional[int] = None
    error: Any = None
    extra: Dict[str, Any] = field(default_factory=dict)

    # -- predicates (knossos.op parity: op/ok? fail? info? invoke?) --------
    @property
    def invoke_(self) -> bool:
        return self.type == INVOKE

    @property
    def ok_(self) -> bool:
        return self.type == OK

    @property
    def fail_(self) -> bool:
        return self.type == FAIL

    @property
    def info_(self) -> bool:
        return self.type == INFO

    def with_(self, **kw) -> "Op":
        # hand-rolled copy: dataclasses.replace re-runs __init__ and is
        # the scheduler's hottest call (hundreds of thousands per run)
        extra = kw.pop("extra", None)
        if not kw.keys() <= _OP_FIELDS:
            raise TypeError(
                f"unknown Op fields: {sorted(kw.keys() - _OP_FIELDS)}")
        new = object.__new__(Op)
        d = self.__dict__.copy()
        d.update(kw)
        if extra:
            d["extra"] = {**self.extra, **extra}
        new.__dict__ = d
        return new

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "index": self.index,
            "type": self.type,
            "process": self.process,
            "f": self.f,
            "value": self.value,
            "time": self.time,
        }
        if self.error is not None:
            d["error"] = self.error
        d.update(self.extra)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Op":
        known = {"index", "type", "process", "f", "value", "time", "error"}
        return cls(
            process=d.get("process"),
            type=d.get("type"),
            f=d.get("f"),
            value=d.get("value"),
            time=d.get("time"),
            index=d.get("index"),
            error=d.get("error"),
            extra={k: v for k, v in d.items() if k not in known},
        )

    def __repr__(self) -> str:  # compact, jepsen-log-style
        return (f"Op({self.index} {self.process} :{self.type} :{self.f} "
                f"{self.value!r}" + (f" err={self.error!r}" if self.error else "") + ")")


def invoke_op(process, f, value=None, **kw) -> Op:
    return Op(process=process, type=INVOKE, f=f, value=value, **kw)


# ---------------------------------------------------------------------------
# History
# ---------------------------------------------------------------------------


class History(Sequence):
    """An indexed sequence of :class:`Op` with pairing transforms.

    Construction assigns ``index`` to each op if absent (parity with
    history/index used at jepsen/src/jepsen/core.clj:223).
    """

    def __init__(self, ops: Iterable[Any], reindex: bool = False):
        self.ops: List[Op] = []
        for i, o in enumerate(ops):
            if isinstance(o, dict):
                o = Op.from_dict(o)
            if reindex or o.index is None:
                o = o.with_(index=i)
            self.ops.append(o)
        self._pairs: Optional[np.ndarray] = None

    # -- Sequence protocol -------------------------------------------------
    def __len__(self) -> int:
        return len(self.ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return History(self.ops[i])
        return self.ops[i]

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __eq__(self, other):
        return isinstance(other, History) and self.ops == getattr(other, "ops", None)

    def __repr__(self):
        return f"History<{len(self)} ops>"

    # -- transforms --------------------------------------------------------
    def pair_index(self) -> np.ndarray:
        """pair_index[i] = index of i's partner entry, or -1 (unmatched).

        An invoke's partner is its completion (same process, next entry);
        a completion's partner is its invoke.  Info ops emitted by the
        nemesis (no invoke) pair to -1.
        """
        if self._pairs is not None:
            return self._pairs
        pairs = np.full(len(self.ops), -1, dtype=np.int64)
        open_invokes: Dict[Any, int] = {}
        for i, op in enumerate(self.ops):
            if op.type == INVOKE:
                open_invokes[op.process] = i
            elif op.type in (OK, FAIL, INFO):
                j = open_invokes.pop(op.process, None)
                if j is not None:
                    pairs[i] = j
                    pairs[j] = i
        self._pairs = pairs
        return pairs

    def invocations(self) -> List[Op]:
        return [o for o in self.ops if o.type == INVOKE]

    def completions(self) -> List[Op]:
        return [o for o in self.ops if o.type in (OK, FAIL, INFO)]

    def oks(self) -> List[Op]:
        return [o for o in self.ops if o.type == OK]

    def client_ops(self) -> "History":
        return History([o for o in self.ops if o.process != NEMESIS])

    def complete(self) -> "History":
        """Knossos history/complete parity: an OK completion's value is
        adopted by its invocation unconditionally (knossos history/complete
        assoc's the completion :value onto the invoke), so reads invoked with
        structured placeholders like [[k, None], ...] step the model with the
        observed value, not the placeholder. Unmatched invokes stay open
        (treated as concurrent-to-the-end by the checkers)."""
        pairs = self.pair_index()
        out = []
        for i, op in enumerate(self.ops):
            if op.type == INVOKE:
                j = pairs[i]
                if j >= 0:
                    comp = self.ops[j]
                    if comp.type == OK and comp.value is not None:
                        op = op.with_(value=comp.value)
            out.append(op)
        return History(out)

    def pairs(self) -> List[Tuple[Op, Optional[Op]]]:
        """[(invoke, completion-or-None), ...] in invocation order."""
        idx = self.pair_index()
        out = []
        for i, op in enumerate(self.ops):
            if op.type == INVOKE:
                j = idx[i]
                out.append((op, self.ops[j] if j >= 0 else None))
        return out

    # -- I/O ---------------------------------------------------------------
    def to_jsonl(self, path: str) -> None:
        # Atomic publish (atomic_io): the history is the one artifact a
        # crashed analysis re-runs from; a torn write must never shadow a
        # previously complete copy.
        from jepsen_tpu.atomic_io import atomic_write

        def dump(f):
            for op in self.ops:
                f.write(json.dumps(op.to_dict(), default=str) + "\n")

        atomic_write(path, dump)

    @classmethod
    def from_jsonl(cls, path: str) -> "History":
        with open(path) as f:
            return cls([json.loads(line) for line in f if line.strip()])

    @classmethod
    def from_edn_file(cls, path: str) -> "History":
        """Read a reference-format history.edn (one op map per line, or one
        top-level vector)."""
        with open(path) as f:
            return cls.from_edn(f.read())

    @classmethod
    def from_edn(cls, text: str) -> "History":
        data = parse_edn_stream(text)
        if len(data) == 1 and isinstance(data[0], list):
            data = data[0]
        return cls([_edn_map_to_op(m) for m in data])


def _edn_map_to_op(m: Dict[str, Any]) -> Op:
    return Op.from_dict(m)


# ---------------------------------------------------------------------------
# Minimal EDN reader — enough for jepsen history files
# ---------------------------------------------------------------------------
# The reference persists histories as EDN (jepsen/src/jepsen/store.clj) using
# maps, vectors, keywords, strings, numbers, nil, booleans.  Keywords are
# decoded to plain strings ("read", not ":read"); map keys likewise.


class _EdnReader:
    def __init__(self, text: str):
        self.t = text
        self.i = 0
        self.n = len(text)

    def _skip_ws(self):
        while self.i < self.n:
            c = self.t[self.i]
            if c in " \t\r\n,":
                self.i += 1
            elif c == ";":  # comment to EOL
                while self.i < self.n and self.t[self.i] != "\n":
                    self.i += 1
            else:
                break

    def at_end(self) -> bool:
        self._skip_ws()
        return self.i >= self.n

    def read(self):
        self._skip_ws()
        if self.i >= self.n:
            raise ValueError("EDN: unexpected end of input")
        c = self.t[self.i]
        if c == "{":
            return self._read_map()
        if c == "[" or c == "(":
            return self._read_seq("]" if c == "[" else ")")
        if c == "#":
            return self._read_dispatch()
        if c == '"':
            return self._read_string()
        if c == ":":
            return self._read_keyword()
        return self._read_atom()

    def _read_map(self):
        self.i += 1  # {
        out = {}
        while True:
            self._skip_ws()
            if self.i < self.n and self.t[self.i] == "}":
                self.i += 1
                return out
            k = self.read()
            v = self.read()
            out[k] = v

    def _read_seq(self, close):
        self.i += 1
        out = []
        while True:
            self._skip_ws()
            if self.i < self.n and self.t[self.i] == close:
                self.i += 1
                return out
            out.append(self.read())

    def _read_dispatch(self):
        # #{...} sets, #inst "..." dates, tagged literals -> best effort
        self.i += 1
        c = self.t[self.i] if self.i < self.n else ""
        if c == "{":
            return set_safe(self._read_seq("}"))
        # tagged literal: read symbol then value, keep the value
        self._read_atom()
        return self.read()

    def _read_string(self):
        self.i += 1
        out = []
        while self.i < self.n:
            c = self.t[self.i]
            if c == "\\":
                nxt = self.t[self.i + 1]
                out.append({"n": "\n", "t": "\t", "r": "\r"}.get(nxt, nxt))
                self.i += 2
            elif c == '"':
                self.i += 1
                return "".join(out)
            else:
                out.append(c)
                self.i += 1
        raise ValueError("EDN: unterminated string")

    def _read_keyword(self):
        self.i += 1  # :
        return self._read_symbol_text()

    def _read_symbol_text(self) -> str:
        start = self.i
        while self.i < self.n and self.t[self.i] not in ' \t\r\n,()[]{}";':
            self.i += 1
        return self.t[start:self.i]

    def _read_atom(self):
        tok = self._read_symbol_text()
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        try:
            if any(ch in tok for ch in ".eEM") and not tok.startswith("0x"):
                if tok.endswith("M"):
                    return float(tok[:-1])
                return float(tok)
            if tok.endswith("N"):
                return int(tok[:-1])
            return int(tok, 0)
        except ValueError:
            return tok  # bare symbol


def set_safe(items):
    try:
        return set(items)
    except TypeError:
        return items


def parse_edn(text: str):
    return _EdnReader(text).read()


def parse_edn_stream(text: str) -> List[Any]:
    r = _EdnReader(text)
    out = []
    while not r.at_end():
        out.append(r.read())
    return out


# ---------------------------------------------------------------------------
# Struct-of-arrays device encoding
# ---------------------------------------------------------------------------


@dataclass
class HistorySOA:
    """Fixed-width column view of a history for device consumption.

    Columns (all int32, length = #entries):
      type    — TYPE_CODE
      process — client process id (nemesis = -1)
      f       — model-assigned function code
      a, b    — model-encoded value operands
      pair    — partner entry index (-1 if none)
      time    — int64 nanoseconds (kept host-side; not shipped to device)
    """

    type: np.ndarray
    process: np.ndarray
    f: np.ndarray
    a: np.ndarray
    b: np.ndarray
    pair: np.ndarray
    time: np.ndarray

    def __len__(self):
        return len(self.type)


def encode_soa(history: History, encode_op: Callable[[Op], Tuple[int, int, int]]) -> HistorySOA:
    """Encode a history with a model-supplied ``encode_op(op) -> (f, a, b)``.

    ``encode_op`` sees the *completed* view of each op (invoke values filled
    from completions), so reads carry their observed value on both entries.
    """
    h = history.complete()
    n = len(h)
    typ = np.empty(n, np.int32)
    proc = np.empty(n, np.int32)
    fc = np.empty(n, np.int32)
    av = np.empty(n, np.int32)
    bv = np.empty(n, np.int32)
    tm = np.zeros(n, np.int64)
    for i, op in enumerate(h):
        typ[i] = TYPE_CODE[op.type]
        proc[i] = -1 if op.process == NEMESIS else int(op.process)
        f, a, b = encode_op(op)
        fc[i], av[i], bv[i] = f, a, b
        tm[i] = op.time or 0
    return HistorySOA(type=typ, process=proc, f=fc, a=av, b=bv,
                      pair=h.pair_index().astype(np.int32), time=tm)
