"""Minimal FaunaDB FQL JSON client.

Parity: the reference drives FaunaDB through the official Java driver
(faunadb/src/jepsen/faunadb/client.clj:1-441, query.clj's FQL DSL).
This is an independent implementation of the public FQL 2.x JSON wire
form: one POST / per query (each query is one transaction), HTTP basic
auth with the secret as username, expressions as operator-keyed JSON
({"get": ref}, {"if": c, "then": t, "else": e}, {"let": ..., "in": ...}).
Targets FaunaDB Enterprise 2.5.x — the version the reference tested.
"""

from __future__ import annotations

import base64
import json
import socket
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

PORT = 8443
SECRET = "secret"  # faunadb/auto.clj's default root key

NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


class FaunaError(Exception):
    def __init__(self, status: int, body: Any):
        super().__init__(f"fauna {status}: {str(body)[:200]}")
        self.status = status
        self.body = body


class AbortError(FaunaError):
    """Explicit transaction abort() — definitely not applied."""


class FaunaClient:
    def __init__(self, node: str, port: int = PORT,
                 secret: str = SECRET, timeout: float = 10.0,
                 scheme: str = "http"):
        self.base = f"{scheme}://{node}:{port}"
        self.auth = base64.b64encode(f"{secret}:".encode()).decode()
        self.timeout = timeout

    def query(self, expr: Any) -> Any:
        req = urllib.request.Request(
            self.base + "/", data=json.dumps(expr).encode(),
            headers={"Authorization": f"Basic {self.auth}",
                     "Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            try:
                parsed = json.loads(body)
            except ValueError:
                parsed = body
            if "transaction aborted" in str(parsed) or \
                    "abort" in str(parsed):
                raise AbortError(e.code, parsed) from e
            raise FaunaError(e.code, parsed) from e
        return out.get("resource")


# -- expression builders (query.clj's DSL shapes) ---------------------------

def ref(cls: str, id_) -> Dict[str, Any]:
    return {"@ref": f"classes/{cls}/{id_}"}


def create_class(name: str) -> Dict[str, Any]:
    return {"create_class": {"object": {"name": name}}}


def create(cls: str, id_, data: Dict[str, Any]) -> Dict[str, Any]:
    return {"create": ref(cls, id_),
            "params": {"object": {"data": {"object": data}}}}


def get(r) -> Dict[str, Any]:
    return {"get": r}


def update(r, data: Dict[str, Any]) -> Dict[str, Any]:
    return {"update": r,
            "params": {"object": {"data": {"object": data}}}}


def delete(r) -> Dict[str, Any]:
    return {"delete": r}


def exists(r) -> Dict[str, Any]:
    return {"exists": r}


def select(path, from_, default=None) -> Dict[str, Any]:
    out = {"select": path, "from": from_}
    if default is not None:
        out["default"] = default
    return out


def equals(*args) -> Dict[str, Any]:
    return {"equals": list(args)}


def if_(cond, then, else_) -> Dict[str, Any]:
    return {"if": cond, "then": then, "else": else_}


def abort(msg: str) -> Dict[str, Any]:
    return {"abort": msg}


def do(*exprs) -> Dict[str, Any]:
    return {"do": list(exprs)}


def let(bindings: Dict[str, Any], in_) -> Dict[str, Any]:
    return {"let": bindings, "in": in_}


def var(name: str) -> Dict[str, Any]:
    return {"var": name}


def add(*args) -> Dict[str, Any]:
    return {"add": list(args)}


def subtract(*args) -> Dict[str, Any]:
    return {"subtract": list(args)}


def lt(*args) -> Dict[str, Any]:
    return {"lt": list(args)}


def time_() -> Dict[str, Any]:
    return {"time": "now"}


def create_index(name: str, source_class: str,
                 values_field: str = "value",
                 serialized: bool = True) -> Dict[str, Any]:
    """Index over a class's instances, emitting one data field
    (pages.clj's elements index; `serialized` mirrors the
    serialized-indices workload option)."""
    return {"create_index": {"object": {
        "name": name,
        "source": {"@ref": f"classes/{source_class}"},
        "values": [{"object": {"field": ["data", values_field]}}],
        "serialized": serialized}}}


def match(index: str) -> Dict[str, Any]:
    return {"match": {"@ref": f"indexes/{index}"}}


def paginate(set_expr, size: int, after=None) -> Dict[str, Any]:
    out = {"paginate": set_expr, "size": size}
    if after is not None:
        out["after"] = after
    return out
