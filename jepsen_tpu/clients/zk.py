"""ZooKeeper client — jute wire protocol subset.

The reference's canonical minimal suite drives ZooKeeper through avout's
distributed atom (zookeeper/src/jepsen/zookeeper.clj:91-104); here the suite
does the same compare-and-set over versioned znodes directly: ``get_data``
returns (value, version) and ``set_data`` with an expected version is the
CAS.  Subset implemented: connect/session, create, getData, setData,
exists, delete — all the register workload needs.
"""

from __future__ import annotations

import socket
import struct
from typing import Optional, Tuple

DEFAULT_PORT = 2181

# opcodes
OP_CREATE, OP_DELETE, OP_EXISTS, OP_GETDATA, OP_SETDATA = 1, 2, 3, 4, 5
OP_CLOSE = -11

# error codes
ERR_NONODE = -101
ERR_BADVERSION = -103
ERR_NODEEXISTS = -110


class ZkError(Exception):
    def __init__(self, code: int):
        super().__init__(f"zookeeper error {code}")
        self.code = code

    @property
    def bad_version(self) -> bool:
        return self.code == ERR_BADVERSION

    @property
    def no_node(self) -> bool:
        return self.code == ERR_NONODE


class ZkClient:
    def __init__(self, host: str, port: int = DEFAULT_PORT,
                 timeout: float = 10.0, session_timeout_ms: int = 10000):
        self.addr = (host, port)
        self.timeout = timeout
        self.session_timeout_ms = session_timeout_ms
        self.sock: Optional[socket.socket] = None
        self.buf = b""
        self.xid = 0

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> "ZkClient":
        self.sock = socket.create_connection(self.addr, timeout=self.timeout)
        self.buf, self.xid = b"", 0
        req = struct.pack("!iqi q", 0, 0, self.session_timeout_ms, 0)
        req += struct.pack("!i", 16) + b"\0" * 16  # passwd
        self._send_frame(req)
        resp = self._read_frame()
        # ConnectResponse: protoVersion(4) timeOut(4) sessionId(8) pw
        (self.session_id,) = struct.unpack("!q", resp[8:16])
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._request(OP_CLOSE, b"")
            except (OSError, ConnectionError, ZkError):
                pass
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- operations --------------------------------------------------------
    def create(self, path: str, data: bytes = b"",
               ephemeral: bool = False) -> str:
        flags = 1 if ephemeral else 0
        acl = struct.pack("!i", 1) + struct.pack("!i", 31) \
            + _s("world") + _s("anyone")
        payload = _s(path) + _b(data) + acl + struct.pack("!i", flags)
        resp = self._request(OP_CREATE, payload)
        n, = struct.unpack("!i", resp[:4])
        return resp[4:4 + n].decode()

    def get_data(self, path: str) -> Tuple[bytes, int]:
        """Returns (data, version) — the read half of the CAS."""
        resp = self._request(OP_GETDATA, _s(path) + b"\0")  # watch=false
        n, = struct.unpack("!i", resp[:4])
        data = resp[4:4 + n] if n > 0 else b""
        off = 4 + max(n, 0)
        # Stat: czxid mzxid ctime mtime version ...
        version, = struct.unpack_from("!i", resp, off + 32)
        return data, version

    def set_data(self, path: str, data: bytes, version: int = -1) -> int:
        """Write; with ``version`` >= 0 this is compare-and-set (BadVersion
        on mismatch).  Returns the new version."""
        payload = _s(path) + _b(data) + struct.pack("!i", version)
        resp = self._request(OP_SETDATA, payload)
        new_version, = struct.unpack_from("!i", resp, 32)
        return new_version

    def exists(self, path: str) -> bool:
        try:
            self._request(OP_EXISTS, _s(path) + b"\0")
            return True
        except ZkError as e:
            if e.no_node:
                return False
            raise

    def delete(self, path: str, version: int = -1) -> None:
        self._request(OP_DELETE, _s(path) + struct.pack("!i", version))

    # -- transport ---------------------------------------------------------
    def _request(self, opcode: int, payload: bytes) -> bytes:
        if self.sock is None:
            self.connect()
        self.xid += 1
        self._send_frame(struct.pack("!ii", self.xid, opcode) + payload)
        while True:
            frame = self._read_frame()
            xid, _zxid, err = struct.unpack("!iqi", frame[:16])
            if xid in (-1, -2):  # watch event / ping: not ours
                continue
            if err != 0:
                raise ZkError(err)
            return frame[16:]

    def _send_frame(self, body: bytes) -> None:
        self.sock.sendall(struct.pack("!i", len(body)) + body)

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _read_frame(self) -> bytes:
        (n,) = struct.unpack("!i", self._read_exact(4))
        return self._read_exact(n)


def _s(s: str) -> bytes:
    b = s.encode()
    return struct.pack("!i", len(b)) + b


def _b(b: bytes) -> bytes:
    return struct.pack("!i", len(b)) + b
