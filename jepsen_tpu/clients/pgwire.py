"""PostgreSQL wire protocol (v3) client — simple query mode.

Used by the postgres-rds, stolon, cockroachdb and yugabyte(YSQL) suites
(the reference drives these through JDBC, e.g.
stolon/src/jepsen/stolon/client.clj, cockroachdb/src/jepsen/cockroach/
client.clj); the simple-query subprotocol is enough for register/bank/
append workloads: one round trip per statement, text-format results,
SQLSTATE surfaced for the retry/definite-failure split every suite needs.

Auth: trust, cleartext password, and md5.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_PORT = 5432


class PgError(Exception):
    def __init__(self, fields: Dict[str, str]):
        self.fields = fields
        self.sqlstate = fields.get("C", "")
        self.severity = fields.get("S", "")
        super().__init__(fields.get("M", "postgres error"))

    @property
    def retryable(self) -> bool:
        """Serialization/deadlock failures: txn may be retried; the op
        definitely did not commit."""
        return self.sqlstate in ("40001", "40P01", "CR000")


class PgClient:
    def __init__(self, host: str, port: int = DEFAULT_PORT,
                 user: str = "postgres", database: str = "postgres",
                 password: str = "", timeout: float = 10.0):
        self.addr = (host, port)
        self.user, self.database, self.password = user, database, password
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.buf = b""
        self.rowcount = 0  # affected rows of the last statement

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> "PgClient":
        self.sock = socket.create_connection(self.addr, timeout=self.timeout)
        params = (f"user\0{self.user}\0database\0{self.database}\0\0"
                  .encode())
        payload = struct.pack("!I", 196608) + params  # protocol 3.0
        self.sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        self._auth()
        return self

    def _auth(self) -> None:
        while True:
            t, body = self._read_msg()
            if t == b"R":
                (code,) = struct.unpack("!I", body[:4])
                if code == 0:
                    continue  # AuthenticationOk
                if code == 3:  # cleartext
                    self._send(b"p", self.password.encode() + b"\0")
                elif code == 5:  # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        self.password.encode() + self.user.encode()
                    ).hexdigest().encode()
                    outer = hashlib.md5(inner + salt).hexdigest()
                    self._send(b"p", b"md5" + outer.encode() + b"\0")
                else:
                    raise PgError({"M": f"unsupported auth code {code}",
                                   "C": "XX000"})
            elif t == b"E":
                raise PgError(_error_fields(body))
            elif t == b"Z":
                return  # ReadyForQuery
            # S (ParameterStatus), K (BackendKeyData): ignore

    def close(self) -> None:
        if self.sock is not None:
            try:
                self._send(b"X", b"")
                self.sock.close()
            except OSError:
                pass
            finally:
                self.sock = None

    # -- queries -----------------------------------------------------------
    def query(self, sql: str) -> List[Tuple[Optional[str], ...]]:
        """Run one simple query; returns rows as tuples of text values
        (None for SQL NULL).  ErrorResponse raises PgError after the
        protocol resyncs on ReadyForQuery."""
        if self.sock is None:
            self.connect()
        self._send(b"Q", sql.encode() + b"\0")
        rows: List[Tuple[Optional[str], ...]] = []
        err: Optional[PgError] = None
        while True:
            t, body = self._read_msg()
            if t == b"D":
                (n,) = struct.unpack("!H", body[:2])
                off, vals = 2, []
                for _ in range(n):
                    (ln,) = struct.unpack("!i", body[off:off + 4])
                    off += 4
                    if ln < 0:
                        vals.append(None)
                    else:
                        vals.append(body[off:off + ln].decode())
                        off += ln
                rows.append(tuple(vals))
            elif t == b"E":
                err = PgError(_error_fields(body))
            elif t == b"C":
                # CommandComplete tag, e.g. "UPDATE 3" / "SELECT 5"
                tag = body.rstrip(b"\0").decode()
                parts = tag.rsplit(" ", 1)
                self.rowcount = (int(parts[-1])
                                 if parts[-1].isdigit() else 0)
            elif t == b"Z":
                if err is not None:
                    raise err
                return rows
            # T (RowDescription), N (Notice), I (EmptyQuery): ignore

    # -- transport ---------------------------------------------------------
    def _send(self, t: bytes, payload: bytes) -> None:
        self.sock.sendall(t + struct.pack("!I", len(payload) + 4) + payload)

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _read_msg(self) -> Tuple[bytes, bytes]:
        hdr = self._read_exact(5)
        t, ln = hdr[:1], struct.unpack("!I", hdr[1:])[0]
        return t, self._read_exact(ln - 4)


def _error_fields(body: bytes) -> Dict[str, str]:
    fields: Dict[str, str] = {}
    for part in body.split(b"\0"):
        if part:
            fields[part[:1].decode()] = part[1:].decode(errors="replace")
    return fields
