"""Thin JSON-over-HTTP client for REST-ish databases.

The consul/elasticsearch/crate/dgraph/chronos/ignite suites all talk HTTP
(the reference uses clj-http, e.g. consul/src/jepsen/consul/client.clj);
urllib with explicit timeouts and error mapping is all they need.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple


class HttpError(Exception):
    def __init__(self, status: int, body: str):
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status
        self.body = body


class HttpClient:
    def __init__(self, host: str, port: int, timeout: float = 5.0,
                 scheme: str = "http"):
        self.base = f"{scheme}://{host}:{port}"
        self.timeout = timeout

    def request(self, method: str, path: str,
                body: Any = None, raw: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                ) -> Tuple[int, Any]:
        """One request; returns (status, parsed-JSON-or-text).  4xx/5xx raise
        HttpError (with the body preserved for checkers)."""
        data = raw
        hdrs = dict(headers or {})
        if body is not None:
            data = json.dumps(body).encode()
            hdrs.setdefault("Content-Type", "application/json")
        req = urllib.request.Request(self.base + path, data=data,
                                     method=method, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status, _parse(r.read())
        except urllib.error.HTTPError as e:
            raise HttpError(e.code, e.read().decode(errors="replace")) from e

    def get(self, path: str, **kw):
        return self.request("GET", path, **kw)

    def put(self, path: str, body: Any = None, **kw):
        return self.request("PUT", path, body=body, **kw)

    def post(self, path: str, body: Any = None, **kw):
        return self.request("POST", path, body=body, **kw)

    def delete(self, path: str, **kw):
        return self.request("DELETE", path, **kw)


def _parse(b: bytes) -> Any:
    if not b:
        return None
    try:
        return json.loads(b)
    except ValueError:
        return b.decode(errors="replace")
