"""Dgraph HTTP transaction client.

Parity: the reference drives Dgraph over gRPC
(dgraph/src/jepsen/dgraph/client.clj:52-457: open/txn/mutate!/query/
upsert!/commit with TxnConflictException handling).  This is an
independent implementation over Dgraph's public HTTP API, which exposes
the same transaction model: /query returns a start_ts, /mutate?startTs=N
buffers writes and returns touched keys/preds, /commit?startTs=N
performs the OCC commit and signals conflicts ("Transaction has been
aborted") — which map to definite failures, like TxnConflictException.
"""

from __future__ import annotations

import json
import socket
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

ALPHA_HTTP_PORT = 8080

NET_ERRORS = (urllib.error.URLError, ConnectionError, OSError,
              socket.timeout, TimeoutError)


class DgraphError(Exception):
    pass


class TxnConflict(DgraphError):
    """OCC abort — definitely not applied (client.clj:96-110)."""


class DgraphClient:
    def __init__(self, node: str, port: int = ALPHA_HTTP_PORT,
                 timeout: float = 10.0):
        self.base = f"http://{node}:{port}"
        self.timeout = timeout

    def _req(self, path: str, body: bytes, content_type: str) -> Dict:
        req = urllib.request.Request(
            self.base + path, data=body,
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                out = json.loads(r.read())
        except urllib.error.HTTPError as e:
            raise DgraphError(e.read().decode(errors="replace")) from e
        errs = out.get("errors")
        if errs:
            msg = "; ".join(e.get("message", "") for e in errs)
            if "aborted" in msg.lower() or "conflict" in msg.lower():
                raise TxnConflict(msg)
            raise DgraphError(msg)
        return out

    def alter_schema(self, schema: str) -> None:
        self._req("/alter", json.dumps({"schema": schema}).encode(),
                  "application/json")

    def query(self, q: str, start_ts: Optional[int] = None,
              read_only: bool = False) -> "QueryResult":
        path = "/query"
        params = []
        if start_ts:
            params.append(f"startTs={start_ts}")
        if read_only:
            params.append("ro=true")
        if params:
            path += "?" + "&".join(params)
        out = self._req(path, q.encode(), "application/dql")
        txn = (out.get("extensions") or {}).get("txn") or {}
        return QueryResult(out.get("data") or {}, txn.get("start_ts"))

    def mutate(self, start_ts: int, set_json: Optional[List] = None,
               delete_json: Optional[List] = None) -> Dict[str, Any]:
        """Buffer mutations in the transaction; returns {uids, keys,
        preds}."""
        body: Dict[str, Any] = {}
        if set_json:
            body["set"] = set_json
        if delete_json:
            body["delete"] = delete_json
        out = self._req(f"/mutate?startTs={start_ts}",
                        json.dumps(body).encode(), "application/json")
        data = out.get("data") or {}
        ext = (out.get("extensions") or {}).get("txn") or {}
        return {"uids": data.get("uids") or {},
                "keys": ext.get("keys") or [],
                "preds": ext.get("preds") or []}

    def commit(self, start_ts: int, keys: List[str],
               preds: List[str]) -> None:
        self._req(f"/commit?startTs={start_ts}",
                  json.dumps({"keys": keys, "preds": preds}).encode(),
                  "application/json")

    def mutate_now(self, set_json: Optional[List] = None,
                   delete_json: Optional[List] = None) -> Dict[str, Any]:
        """commitNow one-shot mutation."""
        body: Dict[str, Any] = {}
        if set_json:
            body["set"] = set_json
        if delete_json:
            body["delete"] = delete_json
        out = self._req("/mutate?commitNow=true",
                        json.dumps(body).encode(), "application/json")
        return (out.get("data") or {})


class QueryResult:
    def __init__(self, data: Dict[str, Any], start_ts: Optional[int]):
        self.data = data
        self.start_ts = start_ts


class Txn:
    """Read-modify-write transaction helper mirroring client.clj's
    with-txn/upsert! flow."""

    def __init__(self, client: DgraphClient):
        self.c = client
        self.start_ts: Optional[int] = None
        self.keys: List[str] = []
        self.preds: List[str] = []

    def query(self, q: str) -> Dict[str, Any]:
        r = self.c.query(q, start_ts=self.start_ts)
        if self.start_ts is None:
            self.start_ts = r.start_ts
        return r.data

    def mutate(self, set_json: Optional[List] = None,
               delete_json: Optional[List] = None) -> Dict[str, Any]:
        if self.start_ts is None:
            # a txn may start with a mutation: draw a ts from a no-op query
            self.query("{ q(func: uid(0x1)) { uid } }")
        r = self.c.mutate(self.start_ts, set_json, delete_json)
        self.keys.extend(r["keys"])
        self.preds.extend(r["preds"])
        return r

    def commit(self) -> None:
        if self.start_ts is not None and (self.keys or self.preds):
            self.c.commit(self.start_ts, self.keys, self.preds)
