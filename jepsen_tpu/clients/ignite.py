"""Minimal Apache Ignite thin-client binary protocol.

Parity: the reference drives Ignite through the Java client
(ignite/src/jepsen/ignite/register.clj:22-49 cache get/put/replace,
bank.clj:27-32 transactional getAll).  This is an independent
implementation of the public "Binary Client Protocol": handshake
(op 1, version, client code 2), then [len i32][opcode i16][req id i64]
frames; cache ids are Java String.hashCode of the cache name; values are
binary-protocol primitives (int 3, long 4, string 9, bool 8, null 101).
Transactions use OP_TX_START/OP_TX_END (protocol 1.5+) with the
transactional flag bit on cache operations.
"""

from __future__ import annotations

import itertools
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

OP_HANDSHAKE = 1

OP_CACHE_GET = 1000
OP_CACHE_PUT = 1001
OP_CACHE_PUT_IF_ABSENT = 1002
OP_CACHE_GET_ALL = 1003
OP_CACHE_PUT_ALL = 1004
OP_CACHE_REPLACE = 1009
OP_CACHE_REPLACE_IF_EQUALS = 1010
OP_CACHE_GET_OR_CREATE_WITH_NAME = 1052
OP_TX_START = 4000
OP_TX_END = 4001

FLAG_TX = 0x02  # cache op participates in the connection's transaction

TYPE_INT = 3
TYPE_LONG = 4
TYPE_BOOL = 8
TYPE_STRING = 9
TYPE_NULL = 101

VER = (1, 6, 0)  # TX ops need 1.6 (Ignite 2.8+)

# response header flags (protocol >= 1.4)
RFLAG_ERROR = 0x01
RFLAG_TOPOLOGY_CHANGED = 0x02


class IgniteError(Exception):
    pass


def cache_id(name: str) -> int:
    """Java String.hashCode, as the protocol requires."""
    h = 0
    for c in name:
        h = (31 * h + ord(c)) & 0xFFFFFFFF
    return h - (1 << 32) if h >= (1 << 31) else h


def enc(v: Any) -> bytes:
    if v is None:
        return bytes([TYPE_NULL])
    if isinstance(v, bool):
        return struct.pack("<Bb", TYPE_BOOL, 1 if v else 0)
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return struct.pack("<Bi", TYPE_INT, v)
        return struct.pack("<Bq", TYPE_LONG, v)
    if isinstance(v, str):
        b = v.encode()
        return struct.pack("<Bi", TYPE_STRING, len(b)) + b
    raise TypeError(f"can't encode {type(v)}")


def dec(buf: bytes, off: int = 0) -> Tuple[Any, int]:
    t = buf[off]
    off += 1
    if t == TYPE_NULL:
        return None, off
    if t == TYPE_BOOL:
        return bool(buf[off]), off + 1
    if t == TYPE_INT:
        return struct.unpack_from("<i", buf, off)[0], off + 4
    if t == TYPE_LONG:
        return struct.unpack_from("<q", buf, off)[0], off + 8
    if t == TYPE_STRING:
        (n,) = struct.unpack_from("<i", buf, off)
        off += 4
        return buf[off:off + n].decode(), off + n
    raise IgniteError(f"can't decode type {t}")


class IgniteClient:
    def __init__(self, node: str, port: int = 10800,
                 timeout: float = 10.0):
        self.sock = socket.create_connection((node, port), timeout=timeout)
        self.req_ids = itertools.count(1)
        self.tx_id: Optional[int] = None
        self._handshake()

    def _handshake(self) -> None:
        body = struct.pack("<BhhhB", OP_HANDSHAKE, *VER, 2)
        self.sock.sendall(struct.pack("<i", len(body)) + body)
        resp = self._recv_frame()
        if resp[0] != 1:
            raise IgniteError(f"handshake rejected: {resp[1:]!r}")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            if not c:
                raise ConnectionError("ignite connection closed")
            buf += c
        return buf

    def _recv_frame(self) -> bytes:
        (n,) = struct.unpack("<i", self._recv_exact(4))
        return self._recv_exact(n)

    def _call(self, opcode: int, payload: bytes) -> bytes:
        """Response framing per protocol >= 1.4: [req id i64][flags i16];
        a topology-changed flag is followed by the affinity version
        (i64+i32), an error flag by [status i32][message]."""
        rid = next(self.req_ids)
        body = struct.pack("<hq", opcode, rid) + payload
        self.sock.sendall(struct.pack("<i", len(body)) + body)
        resp = self._recv_frame()
        (r_rid,) = struct.unpack_from("<q", resp)
        if r_rid != rid:
            raise IgniteError(f"request id mismatch {r_rid} != {rid}")
        (flags,) = struct.unpack_from("<h", resp, 8)
        off = 10
        if flags & RFLAG_TOPOLOGY_CHANGED:
            off += 12  # affinity topology version: i64 + i32
        if flags & RFLAG_ERROR:
            (status,) = struct.unpack_from("<i", resp, off)
            msg, _ = dec(resp, off + 4)
            raise IgniteError(f"status {status}: {msg}")
        return resp[off:]

    def _cache_header(self, cache: str) -> bytes:
        if self.tx_id is not None:
            return struct.pack("<iBi", cache_id(cache), FLAG_TX,
                               self.tx_id)
        return struct.pack("<iB", cache_id(cache), 0)

    # -- cache operations --------------------------------------------------

    def get_or_create_cache(self, name: str) -> None:
        self._call(OP_CACHE_GET_OR_CREATE_WITH_NAME, enc(name))

    def get(self, cache: str, key: Any) -> Any:
        out = self._call(OP_CACHE_GET, self._cache_header(cache) + enc(key))
        return dec(out)[0]

    def put(self, cache: str, key: Any, value: Any) -> None:
        self._call(OP_CACHE_PUT,
                   self._cache_header(cache) + enc(key) + enc(value))

    def replace_if_equals(self, cache: str, key: Any, old: Any,
                          new: Any) -> bool:
        out = self._call(OP_CACHE_REPLACE_IF_EQUALS,
                         self._cache_header(cache)
                         + enc(key) + enc(old) + enc(new))
        return bool(dec(out)[0])

    def get_all(self, cache: str, keys: List[Any]) -> Dict[Any, Any]:
        payload = self._cache_header(cache) + struct.pack("<i", len(keys))
        for k in keys:
            payload += enc(k)
        out = self._call(OP_CACHE_GET_ALL, payload)
        (n,) = struct.unpack_from("<i", out)
        off = 4
        result = {}
        for _ in range(n):
            k, off = dec(out, off)
            v, off = dec(out, off)
            result[k] = v
        return result

    def put_all(self, cache: str, entries: Dict[Any, Any]) -> None:
        payload = self._cache_header(cache) + struct.pack(
            "<i", len(entries))
        for k, v in entries.items():
            payload += enc(k) + enc(v)
        self._call(OP_CACHE_PUT_ALL, payload)

    # -- transactions ------------------------------------------------------

    def tx_start(self, concurrency: int = 1, isolation: int = 2,
                 timeout_ms: int = 5000) -> int:
        """concurrency: 0 optimistic / 1 pessimistic; isolation:
        0 read-committed / 1 repeatable-read / 2 serializable
        (bank.clj:28's txStart arguments)."""
        out = self._call(OP_TX_START,
                         struct.pack("<BBq", concurrency, isolation,
                                     timeout_ms) + enc(None))
        self.tx_id = struct.unpack_from("<i", out)[0]
        return self.tx_id

    def tx_end(self, commit: bool) -> None:
        txid, self.tx_id = self.tx_id, None
        if txid is None:
            raise IgniteError("no open transaction")
        self._call(OP_TX_END, struct.pack("<ib", txid, 1 if commit else 0))
