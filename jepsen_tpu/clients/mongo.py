"""MongoDB client — OP_MSG with a minimal BSON codec.

Used by the mongodb-rocks / mongodb-smartos suites (the reference drives
mongo through the Java driver, mongodb-smartos/src/jepsen/mongodb/*.clj);
the modern wire protocol is a single message kind (OP_MSG, opcode 2013)
carrying one BSON command document, which covers find / insert / update /
findAndModify (the CAS primitive) and replSetGetStatus for primary
discovery.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_PORT = 27017
OP_MSG = 2013


# --------------------------------------------------------------------------
# BSON (subset: the types the suites' documents use)
# --------------------------------------------------------------------------

def bson_encode(doc: Dict[str, Any]) -> bytes:
    body = b"".join(_elem(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\0"


def _elem(k: str, v: Any) -> bytes:
    key = k.encode() + b"\0"
    if isinstance(v, bool):
        return b"\x08" + key + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(2 ** 31) <= v < 2 ** 31:
            return b"\x10" + key + struct.pack("<i", v)
        return b"\x12" + key + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + key + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + key + struct.pack("<i", len(b) + 1) + b + b"\0"
    if isinstance(v, bytes):
        return b"\x05" + key + struct.pack("<i", len(v)) + b"\x00" + v
    if v is None:
        return b"\x0a" + key
    if isinstance(v, dict):
        return b"\x03" + key + bson_encode(v)
    if isinstance(v, (list, tuple)):
        return b"\x04" + key + bson_encode(
            {str(i): x for i, x in enumerate(v)})
    raise TypeError(f"bson: unsupported type {type(v)}")


def bson_decode(b: bytes) -> Dict[str, Any]:
    doc, _ = _dec_doc(b, 0)
    return doc


def _dec_doc(b: bytes, off: int) -> Tuple[Dict[str, Any], int]:
    (ln,) = struct.unpack_from("<i", b, off)
    end = off + ln - 1
    off += 4
    out: Dict[str, Any] = {}
    while off < end:
        t = b[off]
        off += 1
        z = b.index(b"\0", off)
        k = b[off:z].decode()
        off = z + 1
        if t == 0x01:
            (v,) = struct.unpack_from("<d", b, off)
            off += 8
        elif t == 0x02:
            (sl,) = struct.unpack_from("<i", b, off)
            v = b[off + 4:off + 4 + sl - 1].decode()
            off += 4 + sl
        elif t in (0x03, 0x04):
            v, off = _dec_doc(b, off)
            if t == 0x04:
                v = [v[str(i)] for i in range(len(v))]
        elif t == 0x05:
            (bl,) = struct.unpack_from("<i", b, off)
            v = b[off + 5:off + 5 + bl]
            off += 5 + bl
        elif t == 0x07:
            v = b[off:off + 12].hex()
            off += 12
        elif t == 0x08:
            v = b[off] == 1
            off += 1
        elif t == 0x09 or t == 0x12:
            (v,) = struct.unpack_from("<q", b, off)
            off += 8
        elif t == 0x0A:
            v = None
        elif t == 0x10:
            (v,) = struct.unpack_from("<i", b, off)
            off += 4
        elif t == 0x11:
            (v,) = struct.unpack_from("<Q", b, off)
            off += 8
        else:
            raise ValueError(f"bson: unsupported type 0x{t:02x}")
        out[k] = v
    return out, end + 1


# --------------------------------------------------------------------------
# Client
# --------------------------------------------------------------------------

class MongoError(Exception):
    def __init__(self, doc: Dict[str, Any]):
        self.doc = doc
        self.code = doc.get("code", 0)
        super().__init__(doc.get("errmsg", "mongodb error"))


class MongoClient:
    def __init__(self, host: str, port: int = DEFAULT_PORT,
                 database: str = "jepsen", timeout: float = 10.0):
        self.addr = (host, port)
        self.database = database
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.buf = b""
        self.req_id = 0

    def connect(self) -> "MongoClient":
        self.sock = socket.create_connection(self.addr, timeout=self.timeout)
        self.buf = b""
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    def command(self, doc: Dict[str, Any],
                database: Optional[str] = None) -> Dict[str, Any]:
        """Run one command document; raises MongoError when ok != 1."""
        if self.sock is None:
            self.connect()
        doc = dict(doc)
        doc["$db"] = database or self.database
        self.req_id += 1
        body = struct.pack("<i", 0) + b"\x00" + bson_encode(doc)
        hdr = struct.pack("<iiii", 16 + len(body), self.req_id, 0, OP_MSG)
        self.sock.sendall(hdr + body)
        resp = self._read_msg()
        if resp.get("ok") != 1 and resp.get("ok") != 1.0:
            raise MongoError(resp)
        # write commands report per-document and write-concern failures
        # in an ok:1 reply; treating those as success would fabricate
        # acknowledged-but-never-applied writes
        if resp.get("writeErrors") or resp.get("writeConcernError"):
            raise MongoError(resp)
        return resp

    # convenience ops used by the suites
    def find_one(self, coll: str, flt: Dict[str, Any]) -> Optional[Dict]:
        r = self.command({"find": coll, "filter": flt, "limit": 1})
        batch = r.get("cursor", {}).get("firstBatch", [])
        return batch[0] if batch else None

    def upsert(self, coll: str, flt: Dict[str, Any],
               update: Dict[str, Any]) -> Dict[str, Any]:
        return self.command({"update": coll, "updates": [
            {"q": flt, "u": update, "upsert": True}]})

    def find_and_modify(self, coll: str, query: Dict[str, Any],
                        update: Dict[str, Any]) -> Optional[Dict]:
        """The CAS primitive: atomically update iff query matches."""
        r = self.command({"findAndModify": coll, "query": query,
                          "update": update})
        return r.get("value")

    def update(self, coll: str, flt: Dict[str, Any],
               update: Dict[str, Any], upsert: bool = False,
               write_concern: Optional[str] = None) -> int:
        """Update matching docs; returns n matched.  write_concern is
        "majority"/"1"/… (mongodb_smartos/document_cas.clj's
        WriteConcern variants)."""
        cmd: Dict[str, Any] = {"update": coll, "updates": [
            {"q": flt, "u": update, "upsert": upsert}]}
        if write_concern:
            w: Any = int(write_concern) if write_concern.isdigit() \
                else write_concern
            cmd["writeConcern"] = {"w": w}
        r = self.command(cmd)
        return int(r.get("n", 0))

    def insert(self, coll: str, doc: Dict[str, Any],
               write_concern: Optional[str] = None) -> None:
        cmd: Dict[str, Any] = {"insert": coll, "documents": [doc]}
        if write_concern:
            w: Any = int(write_concern) if write_concern.isdigit() \
                else write_concern
            cmd["writeConcern"] = {"w": w}
        self.command(cmd)

    def delete(self, coll: str, flt: Dict[str, Any]) -> int:
        r = self.command({"delete": coll,
                          "deletes": [{"q": flt, "limit": 0}]})
        return int(r.get("n", 0))

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _read_msg(self) -> Dict[str, Any]:
        hdr = self._read_exact(16)
        ln, _rid, _rto, opcode = struct.unpack("<iiii", hdr)
        body = self._read_exact(ln - 16)
        if opcode != OP_MSG:
            raise MongoError({"errmsg": f"unexpected opcode {opcode}"})
        # flagBits(4) + kind byte + doc
        return bson_decode(body[5:])
