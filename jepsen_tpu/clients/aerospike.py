"""Minimal Aerospike wire-protocol client (AS_MSG, protocol type 3).

Parity: the reference drives Aerospike through the official Java client
(aerospike/src/aerospike/support.clj:101-133 connect, 389-446 put!/append!/
fetch/cas!/add!).  This is an independent implementation of the public
Aerospike binary protocol: an 8-byte proto header (version 2, type 3)
followed by a 22-byte message header, key fields (namespace / set /
RIPEMD-160 digest), and bin operations.  CAS is expressed exactly the way
the Java client's generation-write-policy does it
(support.clj:359-365): a write with the GENERATION info bit and the
expected generation in the header.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# RIPEMD-160 (pure Python fallback: OpenSSL 3 ships it only in the legacy
# provider, so hashlib.new("ripemd160") can raise at runtime).
# ---------------------------------------------------------------------------

_KL = (0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E)
_KR = (0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000)

_RL = (
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
    7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
    3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
    1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
    4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13)
_RR = (
    5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
    6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
    15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
    8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
    12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11)
_SL = (
    11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
    7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
    11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
    11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
    9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6)
_SR = (
    8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
    9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
    9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
    15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
    8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11)

_M32 = 0xFFFFFFFF


def _rol(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _M32


def _f(j: int, x: int, y: int, z: int) -> int:
    if j < 16:
        return x ^ y ^ z
    if j < 32:
        return (x & y) | (~x & z)
    if j < 48:
        return (x | ~y) ^ z
    if j < 64:
        return (x & z) | (y & ~z)
    return x ^ (y | ~z)


def _ripemd160_py(data: bytes) -> bytes:
    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    # MD-style padding, little-endian bit length
    padded = data + b"\x80" + b"\x00" * ((55 - len(data)) % 64)
    padded += struct.pack("<Q", 8 * len(data))
    for off in range(0, len(padded), 64):
        x = struct.unpack("<16I", padded[off:off + 64])
        al, bl, cl, dl, el = h
        ar, br, cr, dr, er = h
        for j in range(80):
            t = (_rol((al + _f(j, bl, cl, dl) + x[_RL[j]] + _KL[j // 16])
                      & _M32, _SL[j]) + el) & _M32
            al, el, dl, cl, bl = el, dl, _rol(cl, 10), bl, t
            t = (_rol((ar + _f(79 - j, br, cr, dr) + x[_RR[j]]
                       + _KR[j // 16]) & _M32, _SR[j]) + er) & _M32
            ar, er, dr, cr, br = er, dr, _rol(cr, 10), br, t
        h = [(h[1] + cl + dr) & _M32,
             (h[2] + dl + er) & _M32,
             (h[3] + el + ar) & _M32,
             (h[4] + al + br) & _M32,
             (h[0] + bl + cr) & _M32]
    return struct.pack("<5I", *h)


def ripemd160(data: bytes) -> bytes:
    try:
        return hashlib.new("ripemd160", data).digest()
    except (ValueError, TypeError):
        return _ripemd160_py(data)


# ---------------------------------------------------------------------------
# Protocol constants
# ---------------------------------------------------------------------------

PROTO_VERSION = 2
MSG_TYPE = 3
MSG_HEADER_SZ = 22

FIELD_NAMESPACE = 0
FIELD_SETNAME = 1
FIELD_DIGEST = 4

OP_READ = 1
OP_WRITE = 2
OP_INCR = 5
OP_APPEND = 9

PARTICLE_INTEGER = 1
PARTICLE_STRING = 3

INFO1_READ = 0x01
INFO1_GET_ALL = 0x02
INFO2_WRITE = 0x01
INFO2_GENERATION = 0x04

RESULT_OK = 0
RESULT_NOT_FOUND = 2
RESULT_GENERATION = 3


class AerospikeError(Exception):
    def __init__(self, code: int):
        super().__init__(f"aerospike result code {code}")
        self.code = code


def key_digest(set_name: str, key: Any) -> bytes:
    """RIPEMD-160 over set + particle-type byte + key bytes — the digest
    every official client computes for record addressing."""
    if isinstance(key, int):
        kt, kb = PARTICLE_INTEGER, struct.pack(">q", key)
    else:
        kt, kb = PARTICLE_STRING, str(key).encode()
    return ripemd160(set_name.encode() + bytes([kt]) + kb)


def _encode_value(v: Any) -> Tuple[int, bytes]:
    if isinstance(v, bool):
        raise TypeError("bool bins unsupported")
    if isinstance(v, int):
        return PARTICLE_INTEGER, struct.pack(">q", v)
    return PARTICLE_STRING, str(v).encode()


def _decode_value(ptype: int, data: bytes) -> Any:
    if ptype == PARTICLE_INTEGER:
        return struct.unpack(">q", data)[0]
    if ptype == PARTICLE_STRING:
        return data.decode()
    return data


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">IB", len(data) + 1, ftype) + data


def _op(op_type: int, name: str, value: Any = None) -> bytes:
    nb = name.encode()
    if value is None:
        body = struct.pack(">BBBB", op_type, 0, 0, len(nb)) + nb
    else:
        ptype, vb = _encode_value(value)
        body = struct.pack(">BBBB", op_type, ptype, 0, len(nb)) + nb + vb
    return struct.pack(">I", len(body)) + body


def build_message(info1: int, info2: int, fields: list, ops: list,
                  generation: int = 0) -> bytes:
    body = struct.pack(">BBBBBBIIIHH", MSG_HEADER_SZ, info1, info2, 0, 0, 0,
                       generation, 0, 1000, len(fields), len(ops))
    body += b"".join(fields) + b"".join(ops)
    return struct.pack(">Q",
                       (PROTO_VERSION << 56) | (MSG_TYPE << 48) | len(body)) \
        + body


def parse_message(body: bytes):
    """→ (result_code, generation, bins) for a single-record response."""
    (hsz, _i1, _i2, _i3, _u, code, gen, _ttl, _ttl2, n_fields,
     n_ops) = struct.unpack(">BBBBBBIIIHH", body[:MSG_HEADER_SZ])
    off = hsz
    for _ in range(n_fields):
        (sz,) = struct.unpack(">I", body[off:off + 4])
        off += 4 + sz
    bins: Dict[str, Any] = {}
    for _ in range(n_ops):
        (sz,) = struct.unpack(">I", body[off:off + 4])
        _opt, ptype, _ver, nlen = struct.unpack(
            ">BBBB", body[off + 4:off + 8])
        name = body[off + 8:off + 8 + nlen].decode()
        val = body[off + 8 + nlen:off + 4 + sz]
        bins[name] = _decode_value(ptype, val)
        off += 4 + sz
    return code, gen, bins


class AerospikeClient:
    """One socket to one node; issues single-record transactions."""

    def __init__(self, node: str, port: int = 3000,
                 namespace: str = "jepsen", timeout: float = 5.0):
        self.namespace = namespace
        self.sock = socket.create_connection((node, port), timeout=timeout)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("aerospike connection closed")
            buf += chunk
        return buf

    def _call(self, info1: int, info2: int, set_name: str, key: Any,
              ops: list, generation: int = 0):
        fields = [_field(FIELD_NAMESPACE, self.namespace.encode()),
                  _field(FIELD_SETNAME, set_name.encode()),
                  _field(FIELD_DIGEST, key_digest(set_name, key))]
        self.sock.sendall(build_message(info1, info2, fields, ops,
                                        generation))
        (header,) = struct.unpack(">Q", self._recv_exact(8))
        body = self._recv_exact(header & 0xFFFFFFFFFFFF)
        return parse_message(body)

    # -- record operations (support.clj:389-446 equivalents) --------------

    def put(self, set_name: str, key: Any, bins: Dict[str, Any],
            generation: Optional[int] = None) -> None:
        info2 = INFO2_WRITE
        gen = 0
        if generation is not None:
            info2 |= INFO2_GENERATION
            gen = generation
        code, _, _ = self._call(
            0, info2, set_name, key,
            [_op(OP_WRITE, n, v) for n, v in bins.items()], gen)
        if code != RESULT_OK:
            raise AerospikeError(code)

    def get(self, set_name: str, key: Any):
        """→ (bins, generation) or None when the record doesn't exist."""
        code, gen, bins = self._call(INFO1_READ | INFO1_GET_ALL, 0,
                                     set_name, key, [])
        if code == RESULT_NOT_FOUND:
            return None
        if code != RESULT_OK:
            raise AerospikeError(code)
        return bins, gen

    def add(self, set_name: str, key: Any, bins: Dict[str, int]) -> None:
        code, _, _ = self._call(
            0, INFO2_WRITE, set_name, key,
            [_op(OP_INCR, n, v) for n, v in bins.items()])
        if code != RESULT_OK:
            raise AerospikeError(code)

    def append(self, set_name: str, key: Any, bins: Dict[str, str]) -> None:
        code, _, _ = self._call(
            0, INFO2_WRITE, set_name, key,
            [_op(OP_APPEND, n, v) for n, v in bins.items()])
        if code != RESULT_OK:
            raise AerospikeError(code)
