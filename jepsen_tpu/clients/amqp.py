"""Minimal AMQP 0-9-1 client (RabbitMQ).

Parity: the reference drives RabbitMQ through langohr
(rabbitmq/src/jepsen/rabbitmq.clj:127-175: queue declare/purge, publish
with publisher confirms, basic.get with auto-ack, basic.reject).  This is
an independent implementation of the public AMQP 0-9-1 framing: AMQP\\0\\0\\9\\1
preamble, method/header/body frames terminated by 0xCE, PLAIN auth.
"""

from __future__ import annotations

import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE

# class ids
CONNECTION = 10
CHANNEL = 20
QUEUE = 50
BASIC = 60
CONFIRM = 85

# (class, method) ids
CONN_START = (10, 10)
CONN_START_OK = (10, 11)
CONN_TUNE = (10, 30)
CONN_TUNE_OK = (10, 31)
CONN_OPEN = (10, 40)
CONN_OPEN_OK = (10, 41)
CONN_CLOSE = (10, 50)
CONN_CLOSE_OK = (10, 51)
CH_OPEN = (20, 10)
CH_OPEN_OK = (20, 11)
CH_CLOSE = (20, 40)
CH_CLOSE_OK = (20, 41)
Q_DECLARE = (50, 10)
Q_DECLARE_OK = (50, 11)
Q_PURGE = (50, 30)
Q_PURGE_OK = (50, 31)
B_PUBLISH = (60, 40)
B_GET = (60, 70)
B_GET_OK = (60, 71)
B_GET_EMPTY = (60, 72)
B_ACK = (60, 80)
B_REJECT = (60, 90)
B_NACK = (60, 120)
CONFIRM_SELECT = (85, 10)
CONFIRM_SELECT_OK = (85, 11)


class AmqpError(Exception):
    pass


def _short_str(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _long_str(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def _read_short_str(buf: bytes, off: int) -> Tuple[str, int]:
    n = buf[off]
    return buf[off + 1:off + 1 + n].decode(), off + 1 + n


class AmqpClient:
    """One connection, one channel — enough for the queue/semaphore
    workloads."""

    def __init__(self, node: str, port: int = 5672, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout: float = 10.0):
        self.sock = socket.create_connection((node, port), timeout=timeout)
        self.confirming = False
        self.publish_seq = 0
        self._open(user, password, vhost)

    # -- framing -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            c = self.sock.recv(n - len(buf))
            if not c:
                raise ConnectionError("amqp connection closed")
            buf += c
        return buf

    def _send_frame(self, ftype: int, channel: int, payload: bytes) -> None:
        self.sock.sendall(struct.pack(">BHI", ftype, channel, len(payload))
                          + payload + bytes([FRAME_END]))

    def _recv_frame(self) -> Tuple[int, int, bytes]:
        ftype, channel, size = struct.unpack(">BHI", self._recv_exact(7))
        payload = self._recv_exact(size)
        if self._recv_exact(1)[0] != FRAME_END:
            raise AmqpError("bad frame end")
        return ftype, channel, payload

    def _send_method(self, channel: int, cm: Tuple[int, int],
                     args: bytes = b"") -> None:
        self._send_frame(FRAME_METHOD, channel,
                         struct.pack(">HH", *cm) + args)

    def _recv_method(self, expect=None) -> Tuple[Tuple[int, int], bytes]:
        while True:
            ftype, _ch, payload = self._recv_frame()
            if ftype == FRAME_HEARTBEAT:
                continue
            if ftype != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {ftype}")
            cm = struct.unpack(">HH", payload[:4])
            if cm == CONN_CLOSE or cm == CH_CLOSE:
                code = struct.unpack(">H", payload[4:6])[0]
                text, _ = _read_short_str(payload, 6)
                raise AmqpError(f"closed by server ({code}): {text}")
            if expect is not None and cm not in expect:
                raise AmqpError(f"expected {expect}, got {cm}")
            return cm, payload[4:]

    # -- connection lifecycle ---------------------------------------------

    def _open(self, user: str, password: str, vhost: str) -> None:
        self.sock.sendall(b"AMQP\x00\x00\x09\x01")
        self._recv_method(expect=[CONN_START])
        plain = f"\0{user}\0{password}".encode()
        self._send_method(0, CONN_START_OK,
                          struct.pack(">I", 0)  # empty client-properties
                          + _short_str("PLAIN") + _long_str(plain)
                          + _short_str("en_US"))
        _, args = self._recv_method(expect=[CONN_TUNE])
        channel_max, frame_max, _hb = struct.unpack(">HIH", args)
        self._send_method(0, CONN_TUNE_OK,
                          struct.pack(">HIH", channel_max or 1,
                                      frame_max or 131072, 0))
        self._send_method(0, CONN_OPEN, _short_str(vhost) + b"\x00\x00")
        self._recv_method(expect=[CONN_OPEN_OK])
        self._send_method(1, CH_OPEN, b"\x00")
        self._recv_method(expect=[CH_OPEN_OK])

    def close(self) -> None:
        try:
            self._send_method(0, CONN_CLOSE,
                              struct.pack(">H", 200) + _short_str("bye")
                              + struct.pack(">HH", 0, 0))
            self._recv_method(expect=[CONN_CLOSE_OK])
        except (OSError, AmqpError, ConnectionError):
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    # -- queue operations --------------------------------------------------

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        flags = 0b10 if durable else 0  # bit1 durable
        self._send_method(
            1, Q_DECLARE,
            struct.pack(">H", 0) + _short_str(queue)
            + bytes([flags]) + struct.pack(">I", 0))
        self._recv_method(expect=[Q_DECLARE_OK])

    def queue_purge(self, queue: str) -> int:
        self._send_method(1, Q_PURGE,
                          struct.pack(">H", 0) + _short_str(queue)
                          + b"\x00")
        _, args = self._recv_method(expect=[Q_PURGE_OK])
        return struct.unpack(">I", args[:4])[0]

    def confirm_select(self) -> None:
        self._send_method(1, CONFIRM_SELECT, b"\x00")
        self._recv_method(expect=[CONFIRM_SELECT_OK])
        self.confirming = True
        self.publish_seq = 0

    def publish(self, queue: str, body: bytes,
                wait_confirm: bool = True) -> bool:
        """Publish to the default exchange; with confirms on, block for the
        broker ack (rabbitmq.clj:152-166)."""
        self._send_method(1, B_PUBLISH,
                          struct.pack(">H", 0) + _short_str("")
                          + _short_str(queue) + bytes([0b01]))  # mandatory
        # content header: delivery-mode=2 (persistent)
        props = struct.pack(">H", 0x1000) + bytes([2])
        self._send_frame(FRAME_HEADER, 1,
                         struct.pack(">HHQ", BASIC, 0, len(body)) + props)
        if body:
            self._send_frame(FRAME_BODY, 1, body)
        if not (self.confirming and wait_confirm):
            return True
        self.publish_seq += 1
        cm, args = self._recv_method(expect=[B_ACK, B_NACK])
        tag, _flags = struct.unpack(">QB", args[:9])
        return cm == B_ACK

    def get(self, queue: str, no_ack: bool = True):
        """basic.get → (delivery_tag, body) or None when empty."""
        self._send_method(1, B_GET,
                          struct.pack(">H", 0) + _short_str(queue)
                          + bytes([1 if no_ack else 0]))
        cm, args = self._recv_method(expect=[B_GET_OK, B_GET_EMPTY])
        if cm == B_GET_EMPTY:
            return None
        (tag,) = struct.unpack(">Q", args[:8])
        # header frame then body frames
        ftype, _ch, payload = self._recv_frame()
        if ftype != FRAME_HEADER:
            raise AmqpError("expected content header")
        (body_size,) = struct.unpack(">Q", payload[4:12])
        body = b""
        while len(body) < body_size:
            ftype, _ch, chunk = self._recv_frame()
            if ftype != FRAME_BODY:
                raise AmqpError("expected content body")
            body += chunk
        return tag, body

    def reject(self, delivery_tag: int, requeue: bool = True) -> None:
        self._send_method(1, B_REJECT,
                          struct.pack(">QB", delivery_tag,
                                      1 if requeue else 0))
