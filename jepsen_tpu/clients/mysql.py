"""MySQL client/server protocol — text queries.

Used by the galera, percona, mysql-cluster and tidb suites (the reference
drives these through jdbc/clojure.java.jdbc, e.g. tidb/src/tidb/sql.clj,
galera/src/jepsen/galera.clj); COM_QUERY with the text resultset covers the
bank/register/append workloads.  Auth: mysql_native_password (and servers
configured with no password).  Error numbers are surfaced so suites can
split retryable conflicts (1213 deadlock, 1205 lock-wait) from definite
failures.
"""

from __future__ import annotations

import hashlib
import socket
import struct
from typing import Any, List, Optional, Tuple

DEFAULT_PORT = 3306

CLIENT_LONG_PASSWORD = 0x1
CLIENT_FOUND_ROWS = 0x2  # affected_rows counts matched, not changed, rows
CLIENT_PROTOCOL_41 = 0x200
CLIENT_TRANSACTIONS = 0x2000
CLIENT_SECURE_CONNECTION = 0x8000
CLIENT_PLUGIN_AUTH = 0x80000


class MysqlError(Exception):
    def __init__(self, errno: int, msg: str):
        super().__init__(f"({errno}) {msg}")
        self.errno = errno

    @property
    def retryable(self) -> bool:
        return self.errno in (1205, 1213, 1290, 2013, 8002, 8022, 9007)


class MysqlClient:
    def __init__(self, host: str, port: int = DEFAULT_PORT,
                 user: str = "root", password: str = "",
                 database: str = "", timeout: float = 10.0):
        self.addr = (host, port)
        self.user, self.password, self.database = user, password, database
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.buf = b""
        self.seq = 0
        self.rowcount = 0  # affected rows of the last statement

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> "MysqlClient":
        self.sock = socket.create_connection(self.addr, timeout=self.timeout)
        self.buf, self.seq = b"", 0
        pkt = self._read_packet()
        if pkt[0] == 0xFF:
            raise _err(pkt)
        seed = self._parse_handshake(pkt)
        # FOUND_ROWS is load-bearing: UPDATE-then-INSERT upserts decide
        # whether the row exists from affected_rows, which must count
        # matched rows even when the value is unchanged
        caps = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS |
                CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS |
                CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH)
        if self.database:
            caps |= 0x8  # CLIENT_CONNECT_WITH_DB
        auth = _native_password(self.password, seed)
        body = (struct.pack("<IIB23x", caps, 1 << 24, 0x21)
                + self.user.encode() + b"\0"
                + bytes([len(auth)]) + auth
                + (self.database.encode() + b"\0" if self.database else b"")
                + b"mysql_native_password\0")
        self._send_packet(body)
        pkt = self._read_packet()
        if pkt[0] == 0xFF:
            raise _err(pkt)
        if pkt[0] == 0xFE:  # AuthSwitchRequest -> resend native password
            plugin, _, rest = pkt[1:].partition(b"\0")
            seed2 = rest.rstrip(b"\0")
            self._send_packet(_native_password(self.password, seed2))
            pkt = self._read_packet()
            if pkt[0] == 0xFF:
                raise _err(pkt)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.seq = 0
                self._send_packet(b"\x01")  # COM_QUIT
                self.sock.close()
            except OSError:
                pass
            finally:
                self.sock = None

    # -- queries -----------------------------------------------------------
    def query(self, sql: str) -> List[Tuple[Optional[str], ...]]:
        """COM_QUERY; returns text rows ([] for OK-only responses)."""
        if self.sock is None:
            self.connect()
        self.seq = 0
        self._send_packet(b"\x03" + sql.encode())
        pkt = self._read_packet()
        if pkt[0] == 0xFF:
            raise _err(pkt)
        if pkt[0] == 0x00:
            self.rowcount, _ = _lenenc_int(pkt, 1)  # affected_rows
            return []  # OK packet (no resultset)
        ncols, _ = _lenenc_int(pkt, 0)
        for _ in range(ncols):
            self._read_packet()  # column definitions
        pkt = self._read_packet()
        if pkt[0] == 0xFE and len(pkt) < 9:
            pkt = self._read_packet()  # EOF after columns
        rows: List[Tuple[Optional[str], ...]] = []
        while True:
            if pkt[0] == 0xFE and len(pkt) < 9:
                self.rowcount = len(rows)
                return rows  # EOF
            if pkt[0] == 0xFF:
                raise _err(pkt)
            off, vals = 0, []
            for _ in range(ncols):
                if pkt[off] == 0xFB:
                    vals.append(None)
                    off += 1
                else:
                    n, off = _lenenc_int(pkt, off)
                    vals.append(pkt[off:off + n].decode())
                    off += n
            rows.append(tuple(vals))
            pkt = self._read_packet()

    # -- transport ---------------------------------------------------------
    def _send_packet(self, body: bytes) -> None:
        hdr = struct.pack("<I", len(body))[:3] + bytes([self.seq])
        self.seq = (self.seq + 1) & 0xFF
        self.sock.sendall(hdr + body)

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _read_packet(self) -> bytes:
        hdr = self._read_exact(4)
        ln = hdr[0] | (hdr[1] << 8) | (hdr[2] << 16)
        self.seq = (hdr[3] + 1) & 0xFF
        return self._read_exact(ln)

    @staticmethod
    def _parse_handshake(pkt: bytes) -> bytes:
        # protocol version (1) + server version (nul-str) + thread id (4)
        off = 1
        off = pkt.index(b"\0", off) + 1
        off += 4
        seed1 = pkt[off:off + 8]
        off += 8 + 1  # filler
        off += 2 + 1 + 2 + 2 + 1 + 10  # caps-lo, charset, status, caps-hi,
        #                                auth-len, reserved
        rest = pkt[off:]
        seed2 = rest[:max(13 - 8, 0)] if not rest else rest.split(b"\0")[0]
        seed2 = seed2[:12]
        return seed1 + seed2


def _native_password(password: str, seed: bytes) -> bytes:
    if not password:
        return b""
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    p3 = hashlib.sha1(seed + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, p3))


def _lenenc_int(b: bytes, off: int) -> Tuple[int, int]:
    v = b[off]
    if v < 0xFB:
        return v, off + 1
    if v == 0xFC:
        return struct.unpack_from("<H", b, off + 1)[0], off + 3
    if v == 0xFD:
        return b[off + 1] | (b[off + 2] << 8) | (b[off + 3] << 16), off + 4
    return struct.unpack_from("<Q", b, off + 1)[0], off + 9


def _err(pkt: bytes) -> MysqlError:
    errno = struct.unpack_from("<H", pkt, 1)[0]
    msg = pkt[3:].decode(errors="replace")
    if msg.startswith("#"):
        msg = msg[6:]  # strip sql-state marker
    return MysqlError(errno, msg)
