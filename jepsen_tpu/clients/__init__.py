"""Wire-protocol clients for the database suites.

The reference's suites each pull a JVM driver (avout/ZK, jdbc, jedis, …);
here the suites speak the databases' actual wire protocols through small
stdlib-socket clients, so a suite is runnable with zero external driver
dependencies and testable against in-process fake servers:

- :mod:`resp`    — Redis serialization protocol (raftis, disque)
- :mod:`pgwire`  — PostgreSQL simple-query protocol (postgres-rds, stolon,
                   cockroachdb, yugabyte YSQL)
- :mod:`mysql`   — MySQL client/server protocol (galera, percona,
                   mysql-cluster, tidb)
- :mod:`http`    — thin JSON-over-HTTP helper (consul, elasticsearch,
                   crate, dgraph, chronos, ignite, rethinkdb-admin, …)
- :mod:`zk`      — ZooKeeper jute subset (zookeeper)
- :mod:`mongo`   — MongoDB OP_MSG + minimal BSON (mongodb suites)
"""

from jepsen_tpu.clients import http, mongo, mysql, pgwire, resp, zk  # noqa: F401
