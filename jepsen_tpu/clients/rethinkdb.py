"""Minimal RethinkDB (ReQL) wire client: V1_0 handshake with SCRAM-SHA-256
auth, JSON-serialized query terms.

Parity: the reference drives RethinkDB through the clojure rethinkdb
driver (rethinkdb/src/jepsen/rethinkdb.clj:97-120 conn/run!,
document_cas.clj:53-107 insert/update/branch CAS).  This is an independent
implementation of the public ReQL wire protocol: 0x34c2bdc3 magic, SCRAM
handshake frames, then [token u64][len u32][json] query frames.  Term type
codes are the public ql2.proto enum.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

V1_0 = 0x34C2BDC3

# ql2.proto Term::TermType
DATUM = 1
MAKE_ARRAY = 2
DB = 14
TABLE = 15
GET = 16
EQ = 17
FUNC = 69
VAR = 10
GET_FIELD = 31
BRANCH = 65
ERROR = 12
UPDATE = 53
INSERT = 56
DB_CREATE = 57
TABLE_CREATE = 60
DEFAULT = 92
STATUS = 175
RECONFIGURE = 176
WAIT = 177

START = 1  # Query::QueryType

SUCCESS_ATOM = 1
SUCCESS_SEQUENCE = 2
CLIENT_ERROR = 16
COMPILE_ERROR = 17
RUNTIME_ERROR = 18


class ReqlError(Exception):
    pass


def _scram_hash(password: str, salt: bytes, i: int) -> bytes:
    return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, i)


def _hmac(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha256).digest()


class RethinkClient:
    """One connection; run(term) executes a ReQL term and returns the
    decoded result (atom or sequence)."""

    def __init__(self, node: str, port: int = 28015, user: str = "admin",
                 password: str = "", timeout: float = 10.0):
        self.sock = socket.create_connection((node, port), timeout=timeout)
        self.token = 0
        self._handshake(user, password)

    # -- handshake ---------------------------------------------------------

    def _read_null_terminated(self) -> bytes:
        out = b""
        while not out.endswith(b"\0"):
            c = self.sock.recv(1)
            if not c:
                raise ConnectionError("closed during handshake")
            out += c
        return out[:-1]

    def _handshake(self, user: str, password: str) -> None:
        self.sock.sendall(struct.pack("<I", V1_0))
        hello = json.loads(self._read_null_terminated())
        if not hello.get("success"):
            raise ReqlError(str(hello))
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={user},r={nonce}"
        self.sock.sendall(json.dumps({
            "protocol_version": 0,
            "authentication_method": "SCRAM-SHA-256",
            "authentication": "n,," + first_bare}).encode() + b"\0")
        resp = json.loads(self._read_null_terminated())
        if not resp.get("success"):
            raise ReqlError(str(resp))
        server_first = resp["authentication"]
        fields = dict(kv.split("=", 1) for kv in server_first.split(","))
        r, s, i = fields["r"], fields["s"], int(fields["i"])
        if not r.startswith(nonce):
            raise ReqlError("server nonce mismatch")
        salted = _scram_hash(password, base64.b64decode(s), i)
        client_key = _hmac(salted, b"Client Key")
        stored = hashlib.sha256(client_key).digest()
        without_proof = f"c=biws,r={r}"
        auth_msg = ",".join([first_bare, server_first,
                             without_proof]).encode()
        sig = _hmac(stored, auth_msg)
        proof = bytes(a ^ b for a, b in zip(client_key, sig))
        final = f"{without_proof},p={base64.b64encode(proof).decode()}"
        self.sock.sendall(json.dumps(
            {"authentication": final}).encode() + b"\0")
        resp = json.loads(self._read_null_terminated())
        if not resp.get("success"):
            raise ReqlError(str(resp))
        server_sig = _hmac(_hmac(salted, b"Server Key"), auth_msg)
        fields = dict(kv.split("=", 1)
                      for kv in resp["authentication"].split(","))
        if base64.b64decode(fields["v"]) != server_sig:
            raise ReqlError("bad server signature")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    # -- queries -----------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("connection closed")
            buf += chunk
        return buf

    def run(self, term: Any, optargs: Optional[Dict[str, Any]] = None):
        self.token += 1
        q = json.dumps([START, term, optargs or {}]).encode()
        self.sock.sendall(struct.pack("<QI", self.token, len(q)) + q)
        token, ln = struct.unpack("<QI", self._recv_exact(12))
        resp = json.loads(self._recv_exact(ln))
        t = resp.get("t")
        if t in (SUCCESS_ATOM, SUCCESS_SEQUENCE):
            r = resp.get("r", [])
            return r[0] if t == SUCCESS_ATOM else r
        raise ReqlError(f"type {t}: {resp.get('r')}")


# -- term builders ---------------------------------------------------------

def db(name: str):
    return [DB, [name]]


def table(dbname: str, tname: str, read_mode: Optional[str] = None):
    t = [TABLE, [db(dbname), tname]]
    if read_mode:
        t = [TABLE, [db(dbname), tname], {"read_mode": read_mode}]
    return t


def get(tbl, key):
    return [GET, [tbl, key]]


def get_field(row, name, default=None):
    """row[name] with a fallback for missing rows/fields — always wrapped
    in DEFAULT, mirroring (term :DEFAULT [(r/get-field row "val") nil])
    (document_cas.clj:83-86)."""
    return [DEFAULT, [[GET_FIELD, [row, name]], default]]


def insert(tbl, doc: Dict[str, Any], conflict: str = "error"):
    return [INSERT, [tbl, {k: v for k, v in doc.items()}],
            {"conflict": conflict}]


def update_cas(row, field: str, old, new):
    """row.update(fn(r): branch(r[field] == old, {field: new},
    error("abort"))) — the reference's CAS shape
    (document_cas.clj:93-102)."""
    var = [VAR, [1]]
    body = [BRANCH, [[EQ, [[GET_FIELD, [var, field]], old]],
                     {field: new},
                     [ERROR, ["abort"]]]]
    fn = [FUNC, [[MAKE_ARRAY, [1]], body]]
    return [UPDATE, [row, fn]]


def db_create(name: str):
    return [DB_CREATE, [name]]


def table_create(dbname: str, tname: str, **opts):
    return [TABLE_CREATE, [db(dbname), tname], opts or {}]


def status(dbname: str, tname: str):
    return [STATUS, [table(dbname, tname)]]


def reconfigure(dbname: str, tname: str, shards: int,
                replicas: Dict[str, int], primary_tag: str):
    return [RECONFIGURE, [table(dbname, tname)],
            {"shards": shards, "replicas": replicas,
             "primary_replica_tag": primary_tag}]


def wait_table(dbname: str, tname: str):
    return [WAIT, [table(dbname, tname)],
            {"wait_for": "all_replicas_ready"}]
