"""Redis serialization protocol (RESP2) client.

Used by the raftis and disque suites (the reference's use jedis/spinach,
raftis/src/jepsen/raftis.clj, disque/src/jepsen/disque.clj); RESP is also
what several Redis-compatible stores under test speak.

Blocking, one socket, no pipelining — Jepsen clients are logically
single-threaded, so a plain request/response loop is the right shape.
"""

from __future__ import annotations

import socket
from typing import Any, List, Optional, Union

DEFAULT_PORT = 6379


class RespError(Exception):
    """Server returned an error reply (-ERR ...)."""


class RespClient:
    def __init__(self, host: str, port: int = DEFAULT_PORT,
                 timeout: float = 5.0):
        self.addr = (host, port)
        self.timeout = timeout
        self.sock: Optional[socket.socket] = None
        self.buf = b""

    # -- lifecycle ---------------------------------------------------------
    def connect(self) -> "RespClient":
        self.sock = socket.create_connection(self.addr, timeout=self.timeout)
        return self

    def close(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            finally:
                self.sock = None

    # -- protocol ----------------------------------------------------------
    def call(self, *args: Union[str, bytes, int]) -> Any:
        """Send one command, read one reply.  Error replies raise."""
        if self.sock is None:
            self.connect()
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            b = (a if isinstance(a, bytes)
                 else str(a).encode("utf-8"))
            out.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self.sock.sendall(b"".join(out))
        return self._read_reply()

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]
        return data

    def _read_reply(self) -> Any:
        line = self._read_line()
        t, rest = line[:1], line[1:]
        if t == b"+":
            return rest.decode()
        if t == b"-":
            raise RespError(rest.decode())
        if t == b":":
            return int(rest)
        if t == b"$":
            n = int(rest)
            if n < 0:
                return None
            return self._read_exact(n)
        if t == b"*":
            n = int(rest)
            if n < 0:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"bad reply type {line!r}")
