"""Lift single-key workloads over a space of keys.

Parity: jepsen.independent (jepsen/src/jepsen/independent.clj): ops carry
``(key, value)`` tuples; generators run one key at a time
(sequential_generator) or k keys across disjoint thread groups
(concurrent_generator, independent.clj:213-239); the checker splits the
history per key and checks each sub-history (independent.clj:266-317).

TPU-first difference: when the sub-checker is a device-tier linearizable
checker, the per-key sub-histories are checked as ONE vmapped batch sharded
over the mesh (jepsen_tpu.parallel.check_batch) instead of a bounded pmap of
independent solver runs — the per-key independence the reference exploits
for CPU parallelism maps directly onto the ``data`` mesh axis.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from jepsen_tpu import generator as gen
from jepsen_tpu.checker.core import Checker, UNKNOWN, check_safe, merge_valid
from jepsen_tpu.checker.linearizable import Linearizable
from jepsen_tpu.history import History, INVOKE, NEMESIS, Op

KeyedValue = Tuple[Any, Any]

#: host-tier per-key check parallelism when nothing else configures it
DEFAULT_WORKERS = 8


def worker_count(test: Optional[Dict[str, Any]] = None,
                 explicit: Optional[int] = None) -> int:
    """Resolve the per-key checking thread count: an explicit argument
    wins, then the test map's ``independent_workers`` opt, then the
    ``JEPSEN_TPU_WORKERS`` env var, then :data:`DEFAULT_WORKERS`."""
    for v in (explicit,
              (test or {}).get("independent_workers"),
              os.environ.get("JEPSEN_TPU_WORKERS")):
        if v:
            return max(1, int(v))
    return DEFAULT_WORKERS


def tuple_(k, v) -> KeyedValue:
    """A keyed value (independent.clj:21)."""
    return (k, v)


def key_of(op: Op) -> Optional[Any]:
    v = op.value
    if isinstance(v, tuple) and len(v) == 2:
        return v[0]
    return None


def rewrap_tuples(history: History) -> History:
    """Restore keyed-value tuples on a deserialized history: JSON has no
    tuple type, so a stored independent-workload history comes back with
    ``[k, v]`` lists that :func:`key_of` (correctly) refuses to treat as
    keys — an unkeyed cas value ``[old, new]`` is also a 2-element list,
    so the caller must *assert* the independent shape explicitly (the
    ``submit --independent`` flag / the web API's ``independent`` key)."""
    return History(
        [op.with_(value=tuple(op.value))
         if (op.process != NEMESIS and isinstance(op.value, list)
             and len(op.value) == 2) else op
         for op in history], reindex=True)


def history_keys(history: History) -> List[Any]:
    """All keys in the history, in first-appearance order
    (independent.clj:240)."""
    seen = []
    ss = set()
    for op in history:
        k = key_of(op)
        if k is not None and k not in ss:
            ss.add(k)
            seen.append(k)
    return seen


def subhistory(k, history: History) -> History:
    """The sub-history of key ``k``, values unwrapped
    (independent.clj:252)."""
    out = []
    for op in history:
        kk = key_of(op)
        if kk is None and op.process == NEMESIS:
            out.append(op)  # nemesis ops apply to every key's timeline
        elif kk == k:
            out.append(op.with_(value=op.value[1]))
    return History(out, reindex=True)


class _WrapKey(gen.Generator):
    """Wrap an inner generator's op values as (key, value)."""

    def __init__(self, k, inner):
        self.k = k
        self.inner = gen.lift(inner)

    def op(self, test, ctx):
        if self.inner is None:
            return None
        r = self.inner.op(test, ctx)
        if r is None:
            return None
        v, g2 = r
        if v is gen.PENDING:
            return (gen.PENDING, _WrapKey(self.k, g2))
        v = v.with_(value=(self.k, v.value))
        return (v, _WrapKey(self.k, g2) if g2 is not None else None)

    def update(self, test, ctx, event):
        if self.inner is None:
            return self
        k = key_of(event)
        if k == self.k:
            event = event.with_(value=event.value[1])
            return _WrapKey(self.k, self.inner.update(test, ctx, event))
        return self


def sequential_generator(keys: Iterable[Any],
                         fgen: Callable[[Any], Any]) -> gen.Generator:
    """One key at a time: when key k's generator exhausts, move to the next
    (independent.clj:31)."""
    return gen.Concat([_WrapKey(k, fgen(k)) for k in keys])


class ConcurrentGenerator(gen.Generator):
    """k keys at once, each owning a disjoint group of n threads
    (independent.clj:213-239): when a key's generator exhausts, its thread
    group moves on to the next unclaimed key."""

    def __init__(self, n: int, keys: Sequence[Any],
                 fgen: Callable[[Any], Any]):
        self.n = n
        self.keys = list(keys)
        self.fgen = fgen
        self.active: Dict[int, Optional[gen.Generator]] = {}  # group -> gen
        self.next_key = 0
        self.rr = 0  # round-robin cursor for same-time candidate ties

    def _clone(self):
        c = ConcurrentGenerator.__new__(ConcurrentGenerator)
        c.n = self.n
        c.keys = self.keys
        c.fgen = self.fgen
        c.active = dict(self.active)
        c.next_key = self.next_key
        c.rr = self.rr
        return c

    def _groups(self, ctx) -> List[List[Any]]:
        threads = [t for t in ctx.all_threads() if t != NEMESIS]
        return [threads[i:i + self.n]
                for i in range(0, len(threads) - len(threads) % self.n, self.n)]

    def _ensure(self, c, gi):
        if gi not in c.active:
            if c.next_key < len(c.keys):
                k = c.keys[c.next_key]
                c.next_key += 1
                c.active[gi] = _WrapKey(k, c.fgen(k))
            else:
                c.active[gi] = None

    def op(self, test, ctx):
        # Draw a CANDIDATE op from every group and dispense the soonest
        # (generator.clj `any`'s rule).  Returning the first group's op
        # starved the others whenever an outer pacing wrapper (stagger)
        # kept group 0's threads free at each draw: with k keys only the
        # first thread-group ever ran, so whole nodes had no clients.
        # Non-chosen groups keep their pre-draw state (no op was taken);
        # pending continuations ARE kept (they carry timer anchors).
        c = self._clone()
        groups = self._groups(ctx)
        pending = False
        cands = []  # (v, g2, gi)
        for gi, threads in enumerate(groups):
            while True:
                self._ensure(c, gi)
                g = c.active[gi]
                if g is None:
                    break
                r = g.op(test, ctx.restrict(threads))
                if r is None:
                    # group's key exhausted: advance to next key
                    del c.active[gi]
                    if c.next_key >= len(c.keys):
                        c.active[gi] = None
                        break
                    continue
                v, g2 = r
                if v is gen.PENDING:
                    pending = True
                    c.active[gi] = g2
                    break
                cands.append((v, g2, gi))
                break
        if cands:
            # Soonest op wins; ties (the common case — unpaced gens stamp
            # ops "now") rotate round-robin so no group monopolizes draws.
            tmin = min(v.time for v, _, _ in cands)
            ng = max(1, len(groups))
            v, g2, gi = min((cand for cand in cands if cand[0].time == tmin),
                            key=lambda cand: (cand[2] - c.rr) % ng)
            c.rr = (gi + 1) % ng
            if g2 is None:
                # key exhausted via a final (op, None) draw (limit's
                # shape): free the group so the next draw advances it
                # to the next unclaimed key instead of parking forever
                del c.active[gi]
            else:
                c.active[gi] = g2
            return (v, c)
        if pending:
            return (gen.PENDING, c)
        if all(g is None for g in c.active.values()) and \
                c.next_key >= len(c.keys):
            return None
        return (gen.PENDING, c)

    def update(self, test, ctx, event):
        t = ctx.process_thread(getattr(event, "process", None))
        if t is None or t == NEMESIS:
            return self
        c = self._clone()
        for gi, threads in enumerate(self._groups(ctx)):
            if t in threads and c.active.get(gi) is not None:
                c.active[gi] = c.active[gi].update(
                    test, ctx.restrict(threads), event)
                break
        return c


def concurrent_generator(n: int, keys: Sequence[Any],
                         fgen: Callable[[Any], Any]) -> gen.Generator:
    return ConcurrentGenerator(n, keys, fgen)


class IndependentChecker(Checker):
    """Split the history per key; check each sub-history
    (independent.clj:266-317).  Device-tier linearizable sub-checkers batch
    all keys into one vmapped engine call (optionally mesh-sharded)."""

    def __init__(self, inner: Checker, mesh=None,
                 max_workers: Optional[int] = None):
        self.inner = inner
        self.mesh = mesh
        # None = resolve at check time (test opts / JEPSEN_TPU_WORKERS env)
        self.max_workers = max_workers

    def check(self, test, history, opts=None):
        keys = history_keys(history)
        subs = {k: subhistory(k, history) for k in keys}
        results: Dict[Any, Dict[str, Any]] = {}

        inner = self.inner
        # only the pure-device algorithms take the batched engine; an
        # explicit host algorithm stays off the device, and "competition"
        # must race host+device per key rather than be hijacked
        # (checker.clj:199-202's algorithm switch semantics)
        wants_device = isinstance(inner, Linearizable) and \
            inner.algorithm in (None, "tpu")
        if wants_device and inner._jax_model() is not None:
            from jepsen_tpu.parallel import check_batch
            jm = inner._jax_model()
            rs = check_batch(jm, [subs[k] for k in keys], mesh=self.mesh,
                             **{k: v for k, v in inner.engine_opts.items()
                                if k in ("capacity", "max_capacity", "chunk")})
            results = dict(zip(keys, rs))
            # Refuted keys are rare and precious: re-derive them through the
            # full single-history checker so they carry a witness and a
            # linear.svg in their own result dir (the reference's per-key
            # result dirs + knossos render, independent.clj:266-317,
            # checker.clj:207-211).  The batched pass already paid for the
            # common case; this pays only for failures.
            for k, r in results.items():
                if r.get("valid") is False:
                    rech = check_safe(inner, test, subs[k],
                                      self._key_opts(opts, k))
                    if rech.get("valid") is False:
                        results[k] = rech
                    else:
                        # A crashed or disagreeing re-derivation must never
                        # soften a definite refutation to unknown/true.
                        r["recheck"] = {"valid": rech.get("valid"),
                                        "note": "re-derivation did not "
                                                "confirm; batch refutation "
                                                "stands"}
        else:
            mw = worker_count(test, self.max_workers)
            with ThreadPoolExecutor(max_workers=mw) as ex:
                futs = {k: ex.submit(check_safe, inner, test, subs[k],
                                     self._key_opts(opts, k))
                        for k in keys}
                # Merge in first-appearance key order regardless of which
                # future lands first: the results map (and everything
                # derived from it downstream) is deterministic for a given
                # history, independent of thread scheduling.
                results = {k: futs[k].result() for k in keys}

        bad = {k: r for k, r in results.items() if r.get("valid") is not True}
        out = {"valid": merge_valid([r.get("valid")
                                     for r in results.values()]),
               "key-count": len(keys),
               "results": results,
               "failures": sorted(bad, key=repr)}
        # Engine disagreement is a framework bug signal: surface it beside
        # `failures` so nobody has to scan per-key result maps to notice a
        # batch refutation the re-derivation didn't confirm.
        disagreements = sorted((k for k, r in results.items()
                                if "recheck" in r), key=repr)
        if disagreements:
            out["disagreements"] = disagreements
        return out

    @staticmethod
    def _key_opts(opts, k):
        """Per-key result dir under independent/<key>/ so sub-checker
        artifacts (linear.svg, timelines) never collide across keys."""
        d = (opts or {}).get("store_dir")
        if not d:
            return opts
        kd = os.path.join(d, "independent", str(k))
        try:
            os.makedirs(kd, exist_ok=True)
        except OSError:
            return opts
        return {**opts, "store_dir": kd}


def checker(inner: Checker, mesh=None) -> Checker:
    return IndependentChecker(inner, mesh=mesh)
