"""CharybdeFS integration — syscall-level filesystem error injection.

Parity: the charybdefs wrapper suite
(charybdefs/src/jepsen/charybdefs.clj:40-87): build the CharybdeFS
Thrift+FUSE filesystem on each node, mount it at /faulty, and inject
EIO-class faults: break everything, break probabilistically, clear.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from jepsen_tpu.control import session
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis

REPO = "https://github.com/scylladb/charybdefs.git"
DIR = "/opt/jepsen-tpu/charybdefs"
MOUNT = "/faulty"


def install(test, node) -> None:
    """Clone + build (charybdefs.clj:40-67)."""
    s = session(test, node).sudo()
    if cu.exists(s, f"{DIR}/charybdefs"):
        return
    s.env(DEBIAN_FRONTEND="noninteractive").exec(
        "apt-get", "install", "-y", "git", "g++", "cmake", "libfuse-dev",
        "thrift-compiler", "libthrift-dev", "python3-thrift")
    s.exec("rm", "-rf", DIR)
    s.exec("git", "clone", REPO, DIR)
    s.cd(DIR).exec("thrift", "-r", "--gen", "cpp", "server.thrift")
    s.cd(DIR).exec("cmake", ".")
    s.cd(DIR).exec("make")


def mount(test, node, backing_dir: str = "/faulty-data") -> None:
    s = session(test, node).sudo()
    s.exec("mkdir", "-p", MOUNT, backing_dir)
    cu.start_daemon(s, f"{DIR}/charybdefs", MOUNT,
                    "-oallow_other", "-omodules=subdir",
                    f"-osubdir={backing_dir}",
                    pidfile="/var/run/charybdefs.pid",
                    logfile="/var/log/charybdefs.log")


def _client_cmd(test, node, method: str, *args) -> None:
    """Drive the Thrift control interface via the bundled client
    (charybdefs.clj:74-87's cookbook recipes)."""
    s = session(test, node).sudo()
    argv = " ".join(str(a) for a in args)
    s.exec("python3", f"{DIR}/cookbook/recipes.py", method, *map(str, args)) \
        if cu.exists(s, f"{DIR}/cookbook/recipes.py") else \
        s.exec("bash", "-c",
               f"cd {DIR}/cookbook && python3 -c "
               f"'import recipes; recipes.{method}({argv})'")


def break_all(test, node) -> None:
    _client_cmd(test, node, "break_all")


def break_one_percent(test, node) -> None:
    _client_cmd(test, node, "break_one_percent")


def clear(test, node) -> None:
    _client_cmd(test, node, "clear")


class CharybdeFSNemesis(Nemesis):
    def invoke(self, test, op: Op) -> Op:
        from jepsen_tpu.nemesis.faults import pick_nodes
        targets = pick_nodes(test, op.value)
        fn = {"break-all": break_all,
              "break-some": break_one_percent,
              "clear-faults": clear}.get(op.f)
        if fn is None:
            raise ValueError(f"charybdefs nemesis doesn't handle f={op.f!r}")
        for n in targets:
            fn(test, n)
        return op.with_(type="info", value=sorted(targets))

    def fs(self):
        return ["break-all", "break-some", "clear-faults"]
