"""Frontier sharding: one history's configuration set split across a mesh.

The long-history analog of sequence parallelism (SURVEY.md §5.7): instead of
splitting a history into short per-key pieces the way the reference must
(jepsen/src/jepsen/independent.clj:1-7), the configuration frontier itself is
sharded over the ``model`` mesh axis.  Each device expands its local shard of
configurations (vmapped model steps), candidates are exchanged with
all_gather over ICI, every device deduplicates the global set identically
(replicated sort), and keeps its deterministic slice.  Failure/overflow flags
are psum-reduced so all shards agree.

The host driver mirrors the single-chip lessons (wgl_tpu.check): LOOKAHEAD
chunks stay in flight so the per-chunk flags transfer overlaps device
compute (chunk-boundary polls dominate on tunneled/DCN-attached hosts), an
overflow resumes from the pre-chunk snapshot at a peak-informed capacity
instead of restarting the whole history, and the engine drops back to a
cheaper per-round shape once a crash-burst's transient demand passes.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at top level ...
    from jax import shard_map as _shard_map
    _NO_CHECK = {"check_vma": False}
except ImportError:  # ... older versions only under experimental, and the
    # replication-check kwarg is spelled check_rep there
    from jax.experimental.shard_map import shard_map as _shard_map
    _NO_CHECK = {"check_rep": False}

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import (EV_NOP, LOOKAHEAD, _chunk_slicer,
                                        chosen_gwords, events_array,
                                        make_engine)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel

_CACHE: Dict[Any, Any] = {}


def _sharded_runner(model: JaxModel, window: int, capacity_per_shard: int,
                    mesh: Mesh, axis: str, gwords: int = 1,
                    work_budget: Optional[int] = None):
    key = ("shard", model.name, model.variant, model.state_size,
           tuple(model.init_state_array().tolist()), window,
           capacity_per_shard, id(mesh), axis, gwords, work_budget)
    if key in _CACHE:
        return _CACHE[key]
    n = mesh.shape[axis]
    # The capacity-scaled per-dispatch closure budget (the single-chip
    # watchdog mitigation, wgl_tpu.closure_budget) applies to the sharded
    # engine too; the host loop below resumes mid-chunk from the
    # consumed-events flag exactly like wgl_tpu.check.  Each shard's
    # closure round sorts the *gathered global* set, so the per-iteration
    # cost scales with capacity_per_shard * n — the budget divides by the
    # global capacity, keeping one dispatch's wall-clock at the same bound
    # regardless of shard count.
    if work_budget is None:
        from jepsen_tpu.checker.wgl_tpu import closure_budget
        work_budget = closure_budget(capacity_per_shard * n)
    _, _, run_chunk = make_engine(model, window, capacity_per_shard,
                                  axis_name=axis, num_shards=n,
                                  gwords=gwords, work_budget=work_budget)
    # carry layout: (mask[C,MW], states[C,S], valid[C], win_ops, active,
    #               dirty, failed, failed_op, overflow, explored, rounds,
    #               peak, ghosts, budget, consumed, cl_iters, fresh[W],
    #               cur_new[C]) — ghosts/fresh are per-slot and the
    #               scalars are identical across shards, hence replicated;
    #               cur_new is a per-row delta flag, sharded like valid.
    sharded = P(axis)
    repl = P()
    in_specs = ((sharded, sharded, sharded) + (repl,) * 14 + (sharded,),
                repl)
    out_specs = ((sharded, sharded, sharded) + (repl,) * 14 + (sharded,),
                 repl)
    # Replication checking off (check_vma / legacy check_rep): closure dedup
    # sorts the *gathered* global row set, so every shard computes
    # bit-identical "replicated" scalars (counts, flags), but the
    # varying-axes checker can't prove that post-all_gather.
    fn = jax.jit(_shard_map(run_chunk, mesh=mesh, in_specs=in_specs,
                            out_specs=out_specs, **_NO_CHECK))
    _CACHE[key] = fn
    return fn


def _initial_carry(model, window, cap, n, mesh, axis):
    from jepsen_tpu.checker.wgl_tpu import engine_window
    window = engine_window(window)  # match the engine's block padding
    MW = (window + 31) // 32
    gcap = cap * n

    def put(x, spec):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

    return (
        put(np.zeros((gcap, MW), np.uint32), P(axis)),
        put(np.tile(model.init_state_array()[None], (gcap, 1)), P(axis)),
        put(np.arange(gcap) == 0, P(axis)),
        put(np.concatenate([np.zeros((window, 3), np.int32),
                            np.full((window, 1), -1, np.int32),
                            np.zeros((window, 2), np.int32)], axis=1), P()),
        put(np.zeros(window, bool), P()),
        put(np.bool_(False), P()),
        put(np.bool_(False), P()),
        put(np.int32(-1), P()),
        put(np.bool_(False), P()),
        put(np.int32(0), P()),
        put(np.int32(0), P()),
        put(np.int32(1), P()),
        put(np.zeros(MW, np.uint32), P()),
        put(np.int32(0), P()),           # budget (run_chunk resets it)
        put(np.int32(0), P()),           # consumed
        put(np.int32(0), P()),           # cl_iters (paused-closure its)
        put(np.zeros(window, bool), P()),     # fresh slots
        put(np.zeros(gcap, bool), P(axis)),   # cur_new delta frontier
    )


def _resize_carry_sharded(carry, n, old_cap, new_cap, mesh, axis):
    """Re-lay a chunk-boundary carry for a different per-shard capacity.

    Shard i's rows live at global slice [i*cap, (i+1)*cap): a plain global
    pad/truncate would migrate rows across shards, so resize per-shard —
    grow pads each shard's block with dead rows; shrink compacts the global
    live set and deals it round-robin so shards stay balanced for the next
    closure's all_gather.  Host-side: resizes are rare (one per escalation
    step / burst decay), and the buffers are MBs."""
    mask = np.asarray(carry[0]).reshape(n, old_cap, -1)
    states = np.asarray(carry[1]).reshape(n, old_cap, -1)
    valid = np.asarray(carry[2]).reshape(n, old_cap)
    cur_new = np.asarray(carry[17]).reshape(n, old_cap)

    nm = np.zeros((n, new_cap, mask.shape[2]), mask.dtype)
    ns = np.zeros((n, new_cap, states.shape[2]), states.dtype)
    nv = np.zeros((n, new_cap), bool)
    nn = np.zeros((n, new_cap), bool)
    if new_cap >= old_cap:
        nm[:, :old_cap] = mask
        ns[:, :old_cap] = states
        nv[:, :old_cap] = valid
        nn[:, :old_cap] = cur_new
    else:
        # round-robin deal: global live row j -> shard j % n, slot j // n
        idx, sh = np.divmod(np.arange(n * new_cap), n)
        live = np.flatnonzero(valid.reshape(-1))[:n * new_cap]
        k = len(live)
        fm, fs = mask.reshape(n * old_cap, -1), states.reshape(n * old_cap, -1)
        nm[sh[:k], idx[:k]] = fm[live]
        ns[sh[:k], idx[:k]] = fs[live]
        nv[sh[:k], idx[:k]] = True
        nn[sh[:k], idx[:k]] = cur_new.reshape(-1)[live]

    def put(x):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P(axis)))

    return (put(nm.reshape(n * new_cap, -1)),
            put(ns.reshape(n * new_cap, -1)),
            put(nv.reshape(n * new_cap))) + tuple(carry[3:17]) \
        + (put(nn.reshape(n * new_cap)),)


def check_sharded(model: JaxModel,
                  history: Optional[History] = None,
                  prepared: Optional[PreparedHistory] = None,
                  mesh: Optional[Mesh] = None,
                  axis: str = "model",
                  capacity_per_shard: int = 1024,
                  max_capacity_per_shard: int = 65536,
                  chunk: int = 2048,
                  max_window: int = 4096,
                  work_budget: Optional[int] = None) -> Dict[str, Any]:
    """Frontier-sharded linearizability check of one history.

    ``work_budget`` overrides the per-dispatch closure-iteration budget
    (None = the capacity-scaled default, see _sharded_runner; tests pass a
    tiny value to force the mid-chunk pause/resume path on small meshes)."""
    assert mesh is not None, "check_sharded requires a mesh"
    from jepsen_tpu.checker.wgl_tpu import _round_window
    p = prepared if prepared is not None else prepare(
        history, model, max_window=max_window)
    window = _round_window(p.window)
    ev = events_array(p, chunk)
    n_events = ev.shape[0]
    # One chunk-sized NOP cushion so a mid-chunk resume offset can always
    # slice a full chunk without clamping back into real events (see
    # wgl_tpu.check).
    ev = np.concatenate([ev, np.zeros((chunk, ev.shape[1]), ev.dtype)])
    ev[n_events:, 0] = EV_NOP
    n = mesh.shape[axis]

    def put_repl(x):
        return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))

    # Whole event stream uploaded once (replicated); chunks are sliced
    # device-side — a per-chunk host->device put is a blocking RPC on
    # tunneled/DCN-attached hosts (see wgl_tpu.check).
    ev_dev = put_repl(ev)
    slice_chunk = _chunk_slicer(chunk)

    gw = chosen_gwords(p)
    cap = capacity_per_shard
    max_cap_reached = cap  # diagnostics: how far escalation actually went
    run = _sharded_runner(model, window, cap, mesh, axis, gw, work_budget)
    carry = _initial_carry(model, window, cap, n, mesh, axis)
    # (peak, events-consumed) samples since the last capacity change (see
    # wgl_tpu.check: shrink-back weighs samples by events covered because a
    # budget-paused dispatch can cover anywhere from 0 to chunk events).
    SHRINK_WINDOW = 4 * chunk
    recent_peaks: deque = deque()
    inflight: deque = deque()  # (pos, carry_before, carry_after, flags)
    pos = 0
    failed = overflow = False
    done = carry
    # Pipelined dispatch (see wgl_tpu.check): speculation past a failure or
    # overflow is safe because the failed/overflow lanes gate all updates in
    # event_step — speculative chunks are simply discarded on resume.
    # Pipelining pays where the device→host flags transfer has real latency
    # (tunneled TPU, DCN-attached pod); on the host-platform CPU mesh the
    # transfer is a memcpy and extra in-flight chunks only cost memory
    # (measured ~20% slower), so keep the pipeline depth at 1 there.
    lookahead = (LOOKAHEAD
                 if mesh.devices.flat[0].platform != "cpu" else 1)
    while True:
        while len(inflight) < lookahead and pos < n_events:
            prev = carry
            carry, flags = run(carry, slice_chunk(ev_dev, pos))
            inflight.append((pos, prev, carry, flags))
            pos += chunk
        if not inflight:
            break
        cpos, prev, after, flags = inflight.popleft()
        fl = np.asarray(flags)
        failed, overflow = bool(fl[0]), bool(fl[1])
        peak = int(fl[2])  # global (psum'd) distinct-config high-water mark
        consumed = int(fl[3])
        if overflow and cap < max_capacity_per_shard:
            # Escalate straight to a capacity the observed global peak says
            # is enough (peak may itself be clipped, so the loop can escalate
            # again), and resume from the pre-chunk snapshot: no restart.
            old = cap
            while cap < max_capacity_per_shard and cap * n < 2 * peak:
                cap = min(cap * 4, max_capacity_per_shard)
            if cap == old:
                cap = min(old * 4, max_capacity_per_shard)
            max_cap_reached = max(max_cap_reached, cap)
            recent_peaks.clear()
            inflight.clear()
            run = _sharded_runner(model, window, cap, mesh, axis, gw,
                                  work_budget)
            carry = _resize_carry_sharded(prev, n, old, cap, mesh, axis)
            pos = cpos
            overflow = False
            continue
        done = after
        if failed or overflow:
            break
        recent_peaks.append((peak, consumed))
        covered = sum(e for _, e in recent_peaks)
        while len(recent_peaks) > 1 and covered - recent_peaks[0][1] >= \
                SHRINK_WINDOW:
            covered -= recent_peaks.popleft()[1]
        resumed = consumed < chunk
        if cap > capacity_per_shard and covered >= SHRINK_WINDOW:
            # Transient crash-burst demand has passed: drop back to a
            # cheaper-per-round engine once 2x the recent global peak fits.
            need = 2 * max(pk for pk, _ in recent_peaks)
            target = cap
            while (target > capacity_per_shard
                   and (target // 4) * n >= need):
                target //= 4
            # an escalation clamped to max_capacity can sit off the
            # power-of-4 lattice; never shrink below the configured floor
            target = max(target, capacity_per_shard)
            if target < cap:
                old = cap
                cap = target
                recent_peaks.clear()
                inflight.clear()
                run = _sharded_runner(model, window, cap, mesh, axis, gw,
                                      work_budget)
                carry = _resize_carry_sharded(after, n, old, cap, mesh, axis)
                pos = cpos + consumed
                continue
        if resumed:
            # Closure budget exhausted mid-chunk: discard speculative
            # dispatches and resume exactly where the engine stopped (the
            # single-chip watchdog-bound pattern, wgl_tpu.check).
            inflight.clear()
            carry = after
            pos = cpos + consumed
    carry = done

    explored = int(carry[9])
    if overflow:
        return {"valid": "unknown", "analyzer": "wgl-tpu-sharded",
                "error": f"capacity exceeded at {cap}x{n}",
                "configs-explored": explored}
    if not failed:
        return {"valid": True, "analyzer": "wgl-tpu-sharded",
                "configs-explored": explored, "shards": n,
                "capacity": cap * n,
                "max-capacity-reached": max_cap_reached * n}
    # witness: frontier emptied across ALL shards; refuting op attached
    return {"valid": False, "analyzer": "wgl-tpu-sharded",
            "op": p.ops[int(carry[7])].to_dict(),
            "configs-explored": explored, "shards": n}
