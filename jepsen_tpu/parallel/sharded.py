"""Frontier sharding: one history's configuration set split across a mesh.

The long-history analog of sequence parallelism (SURVEY.md §5.7): instead of
splitting a history into short per-key pieces the way the reference must
(jepsen/src/jepsen/independent.clj:1-7), the configuration frontier itself is
sharded over the ``model`` mesh axis.  Each device expands its local shard of
configurations (vmapped model steps), candidates are exchanged with
all_gather over ICI, every device deduplicates the global set identically
(replicated sort), and keeps its deterministic slice.  Failure/overflow flags
are psum-reduced so all shards agree.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import events_array, make_engine
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel

_CACHE: Dict[Any, Any] = {}


def _sharded_runner(model: JaxModel, window: int, capacity_per_shard: int,
                    mesh: Mesh, axis: str):
    key = ("shard", model.name, model.state_size,
           tuple(model.init_state_array().tolist()), window,
           capacity_per_shard, id(mesh), axis)
    if key in _CACHE:
        return _CACHE[key]
    n = mesh.shape[axis]
    _, _, run_chunk = make_engine(model, window, capacity_per_shard,
                                  axis_name=axis, num_shards=n)
    # carry layout: (mask[C,MW], states[C,S], valid[C], win_ops, active,
    #               dirty, failed, failed_op, overflow, explored, rounds, peak)
    sharded = P(axis)
    repl = P()
    in_specs = ((sharded, sharded, sharded, repl, repl, repl, repl, repl,
                 repl, repl, repl, repl), repl)
    out_specs = ((sharded, sharded, sharded, repl, repl, repl, repl, repl,
                  repl, repl, repl, repl), repl)
    # check_vma=False: closure dedup sorts the *gathered* global row set, so
    # every shard computes bit-identical "replicated" scalars (counts, flags),
    # but the varying-axes checker can't prove that post-all_gather.
    fn = jax.jit(shard_map(run_chunk, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False))
    _CACHE[key] = fn
    return fn


def check_sharded(model: JaxModel,
                  history: Optional[History] = None,
                  prepared: Optional[PreparedHistory] = None,
                  mesh: Optional[Mesh] = None,
                  axis: str = "model",
                  capacity_per_shard: int = 1024,
                  max_capacity_per_shard: int = 65536,
                  chunk: int = 2048,
                  max_window: int = 4096) -> Dict[str, Any]:
    """Frontier-sharded linearizability check of one history."""
    assert mesh is not None, "check_sharded requires a mesh"
    from jepsen_tpu.checker.wgl_tpu import _round_window
    p = prepared if prepared is not None else prepare(
        history, model, max_window=max_window)
    window = _round_window(p.window)
    ev = events_array(p, chunk)
    n_chunks = ev.shape[0] // chunk
    n = mesh.shape[axis]
    MW, S = (window + 31) // 32, model.state_size

    cap = capacity_per_shard
    while True:
        run = _sharded_runner(model, window, cap, mesh, axis)
        gcap = cap * n
        shard_rows = NamedSharding(mesh, P(axis))

        def put(x, spec):
            return jax.device_put(jnp.asarray(x), NamedSharding(mesh, spec))

        carry = (
            put(np.zeros((gcap, MW), np.uint32), P(axis)),
            put(np.tile(model.init_state_array()[None], (gcap, 1)), P(axis)),
            put(np.arange(gcap) == 0, P(axis)),
            put(np.zeros((window, 3), np.int32), P()),
            put(np.zeros(window, bool), P()),
            put(np.bool_(False), P()),
            put(np.bool_(False), P()),
            put(np.int32(-1), P()),
            put(np.bool_(False), P()),
            put(np.int32(0), P()),
            put(np.int32(0), P()),
            put(np.int32(1), P()),
        )
        failed = overflow = False
        for ci in range(n_chunks):
            carry, flags = run(carry, put(ev[ci * chunk:(ci + 1) * chunk], P()))
            fl = np.asarray(flags)
            failed, overflow = bool(fl[0]), bool(fl[1])
            if failed or overflow:
                break
        if overflow and cap < max_capacity_per_shard:
            cap = min(cap * 8, max_capacity_per_shard)
            continue
        break

    explored = int(carry[9])
    if overflow:
        return {"valid": "unknown", "analyzer": "wgl-tpu-sharded",
                "error": f"capacity exceeded at {cap}x{n}",
                "configs-explored": explored}
    if not failed:
        return {"valid": True, "analyzer": "wgl-tpu-sharded",
                "configs-explored": explored, "shards": n,
                "capacity": cap * n}
    return {"valid": False, "analyzer": "wgl-tpu-sharded",
            "op": p.ops[int(carry[7])].to_dict(),
            "configs-explored": explored, "shards": n}
