"""Device-mesh parallelism for the analysis engines.

Two axes, matching how the reference scales analysis (SURVEY.md §2.4, §5.7):

- ``data`` — independent sub-histories checked in parallel (the reference
  shards workloads per key via jepsen.independent and pmaps per-key checks,
  jepsen/src/jepsen/independent.clj:213-317).  Embarrassingly parallel:
  a batch of prepared histories is sharded across the mesh.
- ``model`` — ONE long history's configuration frontier sharded across
  devices (the reference's answer was "keep per-key histories short because
  the search is NP-hard", independent.clj:1-7; ours is to split the frontier).
  Closure candidates are exchanged with all_gather; every device dedups the
  global set identically and keeps its slice.
"""

from jepsen_tpu.parallel.mesh import make_mesh  # noqa: F401
from jepsen_tpu.parallel.batch import check_batch  # noqa: F401
from jepsen_tpu.parallel.megabatch import check_megabatch  # noqa: F401
from jepsen_tpu.parallel.sharded import check_sharded  # noqa: F401
