"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data", "model"),
              devices=None) -> Mesh:
    """Build a mesh over available devices.

    Default: all devices on the ``data`` axis, 1 on ``model``; pass an
    explicit shape (e.g. ``(4, 2)``) to split.
    """
    devs = list(devices if devices is not None else jax.devices())
    if shape is None:
        shape = (len(devs),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n != len(devs):
        devs = devs[:n]
        if len(devs) != n:
            raise ValueError(f"mesh shape {shape} needs {n} devices, "
                             f"have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(shape), tuple(axis_names))
