"""Batch-parallel checking: many independent histories, sharded over a mesh.

This is the device-side realization of the reference's per-key parallel
checking (jepsen.independent/checker splits a multi-key history and runs
sub-checkers in a bounded pmap, jepsen/src/jepsen/independent.clj:266-317):
sub-histories become lanes of a vmapped engine, and lanes are sharded across
the ``data`` mesh axis with pjit — no collectives needed, pure SPMD fan-out.

**Watchdog bounding (round-4).**  Under vmap, ``lax.cond``/``switch``
execute EVERY branch for the whole batch, so the standard engine's
fixpoint loops and multi-width merges multiply into per-step costs that
outrun the TPU worker's ~60 s watchdog (the round-2/3 batch-tier killer).
The batched engine therefore runs in *single-round* mode
(``make_engine(single_round_closure=True)``): exactly one fixed-width
merge per scan step, a pending-return register continuing multi-round
closures across steps, and each lane's step gathering its next event by
the lane's own absolute ``consumed`` cursor — per-step device work is a
constant, a dispatch's wall-clock is bounded by its step count alone,
and lanes progress at fully independent rates with no idle steps.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import EV_NOP, events_array, make_engine
# The ladder/cache/group/budget/witness disciplines live in the shared
# engine substrate; the historical names stay importable from here (the
# serve scheduler, megabatch, tests, and external callers bind them).
from jepsen_tpu.engine.budget import exhausted_result
from jepsen_tpu.engine.cache import (
    CACHE as _CACHE, EngineCache as _LRUCache, engine_cache_stats,  # noqa: F401
)
from jepsen_tpu.engine.groups import MAX_LANES_PER_GROUP, group_slices
from jepsen_tpu.engine.ladder import (
    LANE_EVENTS_PER_DISPATCH, batch_chunk as _batch_chunk, batch_shape,  # noqa: F401
    mega_chunk, next_capacity,
)
from jepsen_tpu.engine.witness import refuted_result
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel


def donate_carry_argnums() -> tuple:
    """Argnums to donate for the per-chunk engine carry.

    The carry is the dominant device allocation (capacity x window words
    per lane); donating it lets XLA update it in place instead of
    reallocating every dispatch.  The CPU backend cannot honor carry
    donation (it warns per call and copies anyway), so donation is gated
    on the real backend — shapes and results are identical either way.
    """
    try:
        return (0,) if jax.default_backend() != "cpu" else ()
    except Exception:  # backend probe must never break checking
        return ()


def check_batch(model: JaxModel,
                histories: Sequence[History],
                mesh: Optional[Mesh] = None,
                axis: str = "data",
                capacity: int = 256,
                max_capacity: int = 65536,
                chunk: Optional[int] = None,
                window_floor: int = 0,
                fission: Optional[bool] = None,
                _group_reuse: bool = False) -> List[Dict[str, Any]]:
    """Check many histories at once; returns one result dict per history.

    All lanes share one engine shape (window = max over histories, events
    NOP-padded to the longest).  With ``mesh``, lanes are sharded over the
    ``axis`` mesh axis; the batch is padded to a multiple of the axis size.
    ``chunk=None`` picks the batch-size-scaled default (``_batch_chunk``).
    ``window_floor`` pads the shared window up to a caller-chosen bucket so
    successive batches of similar histories reuse one compiled engine (the
    serve scheduler's shape-bucketing lever; 0 = tightest window).

    Unlike the single-history engine (kernel-latency bound, per-round
    cost flat in capacity), the vmapped engine's per-step cost IS
    capacity-proportional — every lane pays C+NC merge rows every step —
    so the default capacity starts LOW (measured on hardware: 42 vs 17
    histories/sec at 256 vs 1024 on 200-op crash lanes) and the retry
    loop escalates only the lanes that overflow.

    ``fission`` controls frontier fission for overflowing lanes: once the
    next escalation rung would cross the fission threshold (and the
    caller's ``max_capacity`` lies beyond it), the lane is split into
    independent sub-problems instead of compiling an ever-larger batched
    engine (see :mod:`jepsen_tpu.engine.fission`).  ``None`` reads the
    ``JTPU_FISSION`` knob; fission's own sub-dispatches pin it False so a
    sub-problem can never re-split.
    """
    if not histories:
        return []
    if len(histories) > MAX_LANES_PER_GROUP:
        # Dispatch in bounded groups (engine.groups owns the cap and its
        # bool-scatter/throughput-knee rationale).  Groups share the
        # compiled engine when their shapes agree (the engine cache keys
        # on window/capacity/chunk/bpad).
        out: List[Dict[str, Any]] = []
        for start, stop, reuse in group_slices(len(histories)):
            out.extend(check_batch(model, histories[start:stop],
                                   mesh=mesh, axis=axis, capacity=capacity,
                                   max_capacity=max_capacity, chunk=chunk,
                                   window_floor=window_floor,
                                   fission=fission,
                                   _group_reuse=_group_reuse or reuse))
        return out
    preps = [prepare(h, model) for h in histories]
    window, gw, longest = batch_shape(preps, window_floor=window_floor)
    out: List[Optional[Dict[str, Any]]] = [None] * len(preps)
    lanes = list(range(len(preps)))
    cap: Optional[int] = capacity
    while lanes:
        res = _run_lanes(model, [preps[i] for i in lanes],
                         window, cap, mesh, axis, chunk, gw, longest,
                         group_reuse=_group_reuse)
        retry = []
        for lane, r in zip(lanes, res):
            if r is None:
                retry.append(lane)
            else:
                out[lane] = r
        if not retry:
            break
        nxt = next_capacity(cap, max_capacity)
        if _fission_here(fission, nxt, max_capacity):
            # Frontier fission: the remaining lanes' next rung would cross
            # the threshold — split each into sub-problems on small,
            # cache-hot shapes instead of escalating the whole batched
            # engine (unknown-never-false recombination; the monolithic
            # escalation path survives inside fission as the fallback).
            from jepsen_tpu.engine.fission import split_check
            for lane in retry:
                out[lane] = split_check(model, histories[lane],
                                        capacity=capacity,
                                        max_capacity=max_capacity)
            break
        if nxt is None:
            for lane in retry:
                out[lane] = exhausted_result(
                    "wgl-tpu-batch", f"capacity exceeded at {cap}",
                    **{"capacity-exceeded": True})
            break
        lanes = retry
        cap = nxt
    return out  # type: ignore[return-value]


def _fission_here(fission: Optional[bool], nxt: Optional[int],
                  max_capacity: int) -> bool:
    """Should the escalation loop split instead of taking rung ``nxt``?"""
    from jepsen_tpu.engine.fission import fission_enabled, fission_threshold
    enabled = fission if fission is not None else fission_enabled()
    if not enabled:
        return False
    thr = fission_threshold()
    return max_capacity > thr and (nxt is None or nxt > thr)


def _run_lanes(model: JaxModel, preps, window: int, cap: int,
               mesh: Optional[Mesh], axis: str, chunk: Optional[int],
               gwords: int, longest: int,
               group_reuse: bool = False) -> List[Optional[Dict[str, Any]]]:
    """One vmapped pass over a set of lanes at a fixed capacity.  Returns a
    result per lane, or None where the lane overflowed (caller escalates).

    Each dispatch runs a fixed number of single-round steps; a lane's step
    gathers the event at the lane's own absolute ``consumed`` cursor, so
    lanes progress at fully independent rates and the host just re-invokes
    until every lane's cursor passes its stream (or fails/overflows)."""
    b = len(preps)
    bpad = b
    if mesh is not None:
        n = mesh.shape[axis]
        bpad = ((b + n - 1) // n) * n
    # The state-width-aware chunk derivation shared with megabatch: one
    # ladder, one bounded (lane, events, state-width)-bucket chunk
    # universe for both dispatch paths.
    cc = chunk if chunk else mega_chunk(bpad, longest, model.state_size)
    evs = [events_array(p, cc) for p in preps]
    # >= 1 trailing NOP row per lane: finished lanes' cursors clamp onto
    # it (the gather-based engine reads events by each lane's absolute
    # consumed cursor; see wgl_tpu run_chunk's single-round variant).
    emax = max(e.shape[0] for e in evs) + 1
    batch = np.zeros((bpad, emax, 10), np.int32)
    batch[:, :, 0] = EV_NOP
    for i, e in enumerate(evs):
        batch[i, :e.shape[0]] = e

    carry0, vrun = _batched_runner(model, window, cap, gwords, cc, bpad,
                                   group_reuse=group_reuse)
    c0 = carry0()
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (bpad,) + x.shape), c0)
    if mesh is not None:
        carry = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
            carry)
        batch_dev = jax.device_put(
            jnp.asarray(batch), NamedSharding(mesh, P(axis, None, None)))
    else:
        batch_dev = jnp.asarray(batch)

    lane_len = np.array([e.shape[0] for e in evs]
                        + [0] * (bpad - b), np.int32)
    failed = np.zeros(bpad, bool)
    overflow = np.zeros(bpad, bool)
    while True:
        carry, flags = vrun(carry, batch_dev)
        fl = np.asarray(flags)              # [bpad, 5]
        failed = fl[:, 0].astype(bool)
        overflow = fl[:, 1].astype(bool)
        consumed = fl[:, 3]                 # absolute per-lane cursors
        stalled = fl[:, 4].astype(bool)     # unconverged pending return
        # A lane whose cursor passed its stream may STILL have its final
        # return's closure in flight (consume-on-arrival): it stays live
        # until the stalled flag clears, or its prune could be dropped —
        # a false "valid" on a refuting final return.
        if not (~failed & ~overflow
                & ((consumed < lane_len) | stalled)).any():
            break

    failed_op = np.asarray(carry[7])[:b]
    explored = np.asarray(carry[9])[:b]
    out: List[Optional[Dict[str, Any]]] = []
    for i in range(b):
        if overflow[i]:
            out.append(None)
        elif failed[i]:
            out.append(refuted_result("wgl-tpu-batch",
                                      preps[i].ops[int(failed_op[i])],
                                      int(explored[i])))
        else:
            out.append({"valid": True, "analyzer": "wgl-tpu-batch",
                        "configs-explored": int(explored[i])})
    return out


def _batched_runner(model: JaxModel, window: int, capacity: int,
                    gwords: int, chunk: int, bpad: int,
                    group_reuse: bool = False):
    key = ("batchv", model.name, model.variant, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords, chunk, bpad)
    hit = _CACHE.get(key, group_reuse=group_reuse)
    if hit is not None:
        return hit
    # single_round_closure: under vmap every cond/switch branch executes
    # for the whole batch, so the batched engine runs exactly ONE closure
    # round (one fixed-width merge) per scan step — per-step device work
    # is constant, a dispatch's wall-clock is bounded by the step count
    # alone, and no iteration budget is needed (work_budget=0).  Each
    # lane's step gathers its next event by the lane's own absolute
    # consumed cursor, so lanes progress at fully independent rates with
    # no idle steps.
    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords, work_budget=0,
                                       single_round_closure=True,
                                       steps_per_dispatch=chunk)
    # Donate the carry (argnum 0): the batched carry dominates device
    # memory and is dead after each dispatch — in-place update instead of
    # a fresh allocation per chunk.  The events buffer (argnum 1) is NOT
    # donated; it is reused across every dispatch of the batch.
    vrun = jax.jit(jax.vmap(run_chunk, in_axes=(0, 0)),
                   donate_argnums=donate_carry_argnums())
    from jepsen_tpu.obs.hist import timed_first_call
    vrun = timed_first_call(
        vrun, f"compile:batchv:{model.name}:w{window}:c{capacity}"
              f":k{chunk}:b{bpad}")
    return _CACHE.put(key, (carry0, vrun))
