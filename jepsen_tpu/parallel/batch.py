"""Batch-parallel checking: many independent histories, sharded over a mesh.

This is the device-side realization of the reference's per-key parallel
checking (jepsen.independent/checker splits a multi-key history and runs
sub-checkers in a bounded pmap, jepsen/src/jepsen/independent.clj:266-317):
sub-histories become lanes of a vmapped engine, and lanes are sharded across
the ``data`` mesh axis with pjit — no collectives needed, pure SPMD fan-out.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import (EV_NOP, events_array, ghost_words,
                                        make_engine)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel

_CACHE: Dict[Any, Any] = {}


def check_batch(model: JaxModel,
                histories: Sequence[History],
                mesh: Optional[Mesh] = None,
                axis: str = "data",
                capacity: int = 1024,
                max_capacity: int = 65536,
                chunk: int = 2048) -> List[Dict[str, Any]]:
    """Check many histories at once; returns one result dict per history.

    All lanes share one engine shape (window = max over histories, events
    NOP-padded to the longest).  With ``mesh``, lanes are sharded over the
    ``axis`` mesh axis; the batch is padded to a multiple of the axis size.
    """
    if not histories:
        return []
    from jepsen_tpu.checker.wgl_tpu import _round_window
    preps = [prepare(h, model) for h in histories]
    window = _round_window(max(p.window for p in preps))
    # Clamp the chunk to the longest lane (rounded to 128) so short per-key
    # histories don't pay a scan over thousands of NOP-padding events.
    longest = max(len(p) for p in preps)
    chunk = min(chunk, max(128, ((longest + 127) // 128) * 128))
    evs = [events_array(p, chunk) for p in preps]

    # Per-lane capacity adaptivity: most lanes (short per-key histories)
    # finish at the starting capacity; only the lanes that actually
    # overflowed are regrouped into a smaller batch and re-run at an
    # escalated capacity — one deep lane no longer makes every lane pay
    # the O(C·W) closure cost of the rare worst case.
    gw = max(ghost_words(p) for p in preps)
    out: List[Optional[Dict[str, Any]]] = [None] * len(evs)
    lanes = list(range(len(evs)))
    cap = capacity
    while lanes:
        res = _run_lanes(model, [evs[i] for i in lanes],
                         [preps[i] for i in lanes],
                         window, cap, mesh, axis, chunk, gw)
        retry = []
        for lane, r in zip(lanes, res):
            if r is None:
                retry.append(lane)
            else:
                out[lane] = r
        if not retry or cap >= max_capacity:
            for lane in retry:
                out[lane] = {"valid": "unknown", "analyzer": "wgl-tpu-batch",
                             "error": f"capacity exceeded at {cap}"}
            break
        lanes = retry
        cap = min(cap * 8, max_capacity)
    return out  # type: ignore[return-value]


def _run_lanes(model: JaxModel, evs, preps, window: int, cap: int,
               mesh: Optional[Mesh], axis: str, chunk: int,
               gwords: int = 1) -> List[Optional[Dict[str, Any]]]:
    """One vmapped pass over a set of lanes at a fixed capacity.  Returns a
    result per lane, or None where the lane overflowed (caller escalates)."""
    emax = max(e.shape[0] for e in evs)
    b = len(evs)
    bpad = b
    if mesh is not None:
        n = mesh.shape[axis]
        bpad = ((b + n - 1) // n) * n
    batch = np.full((bpad, emax, 10), 0, np.int32)
    batch[:, :, 0] = EV_NOP
    for i, e in enumerate(evs):
        batch[i, :e.shape[0]] = e

    carry0, vrun = _batched_runner_simple(model, window, cap, gwords)
    c0 = carry0()
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (bpad,) + x.shape), c0)
    if mesh is not None:
        carry = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
            carry)
        batch_dev = jax.device_put(
            jnp.asarray(batch), NamedSharding(mesh, P(axis, None, None)))
    else:
        batch_dev = jnp.asarray(batch)
    from jepsen_tpu.checker.wgl_tpu import _chunk_slicer
    slice_chunk = _chunk_slicer(chunk, axis=1)
    n_chunks = emax // chunk
    for ci in range(n_chunks):
        carry, _ = vrun(carry, slice_chunk(batch_dev, ci * chunk))

    overflow = np.asarray(carry[8])[:b]
    failed = np.asarray(carry[6])[:b]
    failed_op = np.asarray(carry[7])[:b]
    explored = np.asarray(carry[9])[:b]
    out: List[Optional[Dict[str, Any]]] = []
    for i in range(b):
        if overflow[i]:
            out.append(None)
        elif failed[i]:
            out.append({"valid": False, "analyzer": "wgl-tpu-batch",
                        "op": preps[i].ops[int(failed_op[i])].to_dict(),
                        "configs-explored": int(explored[i])})
        else:
            out.append({"valid": True, "analyzer": "wgl-tpu-batch",
                        "configs-explored": int(explored[i])})
    return out


def _batched_runner_simple(model: JaxModel, window: int, capacity: int,
                           gwords: int = 1):
    key = ("batchv", model.name, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords)
    if key in _CACHE:
        return _CACHE[key]
    # work_budget=0 (unlimited): vmapped lanes advance in lockstep and
    # cannot resume at per-lane positions; lanes are short per-key
    # histories whose chunks stay far from the watchdog bound.
    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords, work_budget=0)
    vrun = jax.jit(jax.vmap(run_chunk))
    _CACHE[key] = (carry0, vrun)
    return _CACHE[key]
