"""Batch-parallel checking: many independent histories, sharded over a mesh.

This is the device-side realization of the reference's per-key parallel
checking (jepsen.independent/checker splits a multi-key history and runs
sub-checkers in a bounded pmap, jepsen/src/jepsen/independent.clj:266-317):
sub-histories become lanes of a vmapped engine, and lanes are sharded across
the ``data`` mesh axis with pjit — no collectives needed, pure SPMD fan-out.

**Watchdog bounding (round-4).**  A vmapped dispatch's wall-clock is the sum
over scan steps of the *slowest lane's* closure work at that step, times the
batched per-iteration cost (~all lanes' sorts fused).  Round 3 ran lanes
with an unlimited work budget and a near-full-history chunk; one dispatch
over 96 lanes outlived the TPU worker's ~60 s watchdog and killed the bench
tier.  Two bounds now apply:

- the chunk shrinks with the batch size (``_batch_chunk``), so the number
  of scan steps — each of which can carry some lane's closure — divides
  the per-dispatch work across more, shorter programs; and
- each lane carries the capacity- and batch-scaled closure budget
  (``wgl_tpu.closure_budget`` semantics): a lane that runs out pauses
  mid-closure and the host resumes it from its per-lane ``consumed``
  counter — lanes advance at *independent* positions via device-side
  dynamic slicing, so one deep lane no longer holds a whole dispatch
  hostage.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import (EV_NOP, closure_budget,
                                        events_array, ghost_words,
                                        make_engine)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel

_CACHE: Dict[Any, Any] = {}

#: Target lane-events per dispatch: the vmapped scan costs ~(batch x chunk)
#: lane-event steps, so the chunk shrinks as the batch grows to keep one
#: XLA program's duration roughly constant regardless of batch size.
LANE_EVENTS_PER_DISPATCH = 16384


def _batch_chunk(bpad: int, longest: int) -> int:
    """Events per dispatch for a ``bpad``-lane batch (multiple of 64,
    clamped to [64, 2048] and to the longest lane rounded up)."""
    c = max(64, min(2048, (LANE_EVENTS_PER_DISPATCH // max(1, bpad))
                    // 64 * 64))
    return min(c, max(64, ((longest + 63) // 64) * 64))


def check_batch(model: JaxModel,
                histories: Sequence[History],
                mesh: Optional[Mesh] = None,
                axis: str = "data",
                capacity: int = 1024,
                max_capacity: int = 65536,
                chunk: Optional[int] = None) -> List[Dict[str, Any]]:
    """Check many histories at once; returns one result dict per history.

    All lanes share one engine shape (window = max over histories, events
    NOP-padded to the longest).  With ``mesh``, lanes are sharded over the
    ``axis`` mesh axis; the batch is padded to a multiple of the axis size.
    ``chunk=None`` picks the batch-size-scaled default (``_batch_chunk``).
    """
    if not histories:
        return []
    from jepsen_tpu.checker.wgl_tpu import _round_window
    preps = [prepare(h, model) for h in histories]
    window = _round_window(max(p.window for p in preps))
    longest = max(len(p) for p in preps)
    gw = max(ghost_words(p) for p in preps)
    out: List[Optional[Dict[str, Any]]] = [None] * len(preps)
    lanes = list(range(len(preps)))
    cap = capacity
    while lanes:
        res = _run_lanes(model, [preps[i] for i in lanes],
                         window, cap, mesh, axis, chunk, gw, longest)
        retry = []
        for lane, r in zip(lanes, res):
            if r is None:
                retry.append(lane)
            else:
                out[lane] = r
        if not retry or cap >= max_capacity:
            for lane in retry:
                out[lane] = {"valid": "unknown", "analyzer": "wgl-tpu-batch",
                             "error": f"capacity exceeded at {cap}"}
            break
        lanes = retry
        cap = min(cap * 8, max_capacity)
    return out  # type: ignore[return-value]


def _run_lanes(model: JaxModel, preps, window: int, cap: int,
               mesh: Optional[Mesh], axis: str, chunk: Optional[int],
               gwords: int, longest: int) -> List[Optional[Dict[str, Any]]]:
    """One vmapped pass over a set of lanes at a fixed capacity.  Returns a
    result per lane, or None where the lane overflowed (caller escalates).

    Lanes progress at independent event positions: each dispatch slices a
    per-lane chunk at that lane's position device-side, and the per-lane
    ``consumed`` flag advances it — a budget-paused lane simply consumes
    fewer events that dispatch (wgl_tpu's mid-chunk resume, vmapped)."""
    b = len(preps)
    bpad = b
    if mesh is not None:
        n = mesh.shape[axis]
        bpad = ((b + n - 1) // n) * n
    cc = chunk if chunk else _batch_chunk(bpad, longest)
    evs = [events_array(p, cc) for p in preps]
    emax = max(e.shape[0] for e in evs)
    # One chunk-sized NOP cushion so any in-bounds resume offset slices a
    # full chunk without clamping back into real events.
    batch = np.zeros((bpad, emax + cc, 10), np.int32)
    batch[:, :, 0] = EV_NOP
    for i, e in enumerate(evs):
        batch[i, :e.shape[0]] = e

    carry0, vrun = _batched_runner(model, window, cap, gwords, cc, bpad)
    c0 = carry0()
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (bpad,) + x.shape), c0)
    if mesh is not None:
        carry = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
            carry)
        batch_dev = jax.device_put(
            jnp.asarray(batch), NamedSharding(mesh, P(axis, None, None)))
        pos_sharding = NamedSharding(mesh, P(axis))
    else:
        batch_dev = jnp.asarray(batch)
        pos_sharding = None

    lane_len = np.array([e.shape[0] for e in evs]
                        + [0] * (bpad - b), np.int32)
    pos = np.zeros(bpad, np.int32)
    failed = np.zeros(bpad, bool)
    overflow = np.zeros(bpad, bool)
    while True:
        active = ~failed & ~overflow & (pos < lane_len)
        if not active.any():
            break
        pos_dev = jnp.asarray(pos)
        if pos_sharding is not None:
            pos_dev = jax.device_put(pos_dev, pos_sharding)
        carry, flags = vrun(carry, batch_dev, pos_dev)
        fl = np.asarray(flags)              # [bpad, 4]
        failed = fl[:, 0].astype(bool)
        overflow = fl[:, 1].astype(bool)
        # A lane is done once its position passes its real events (the
        # tail beyond lane_len is the NOP cushion); clamping there keeps
        # finished lanes' positions stable across further dispatches.
        pos = np.minimum(pos + fl[:, 3], lane_len)

    failed_op = np.asarray(carry[7])[:b]
    explored = np.asarray(carry[9])[:b]
    out: List[Optional[Dict[str, Any]]] = []
    for i in range(b):
        if overflow[i]:
            out.append(None)
        elif failed[i]:
            out.append({"valid": False, "analyzer": "wgl-tpu-batch",
                        "op": preps[i].ops[int(failed_op[i])].to_dict(),
                        "configs-explored": int(explored[i])})
        else:
            out.append({"valid": True, "analyzer": "wgl-tpu-batch",
                        "configs-explored": int(explored[i])})
    return out


def _batched_runner(model: JaxModel, window: int, capacity: int,
                    gwords: int, chunk: int, bpad: int):
    # Per-lane closure budget, scaled down by the batch size: a vmapped
    # closure iteration costs ~bpad single-lane iterations (every lane's
    # block merges run, masked or not), so the budget divides by
    # (capacity * bpad) to keep one dispatch's wall-clock at the same
    # bound as the single-history engine.
    budget = closure_budget(capacity * bpad)
    key = ("batchv", model.name, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords, chunk, bpad, budget)
    if key in _CACHE:
        return _CACHE[key]
    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords, work_budget=budget)

    def run_lane(carry, ev_all, p):
        ev = lax.dynamic_slice_in_dim(ev_all, p, chunk)
        return run_chunk(carry, ev)

    vrun = jax.jit(jax.vmap(run_lane, in_axes=(0, 0, 0)))
    _CACHE[key] = (carry0, vrun)
    return _CACHE[key]
