"""Batch-parallel checking: many independent histories, sharded over a mesh.

This is the device-side realization of the reference's per-key parallel
checking (jepsen.independent/checker splits a multi-key history and runs
sub-checkers in a bounded pmap, jepsen/src/jepsen/independent.clj:266-317):
sub-histories become lanes of a vmapped engine, and lanes are sharded across
the ``data`` mesh axis with pjit — no collectives needed, pure SPMD fan-out.

**Watchdog bounding (round-4).**  Under vmap, ``lax.cond``/``switch``
execute EVERY branch for the whole batch, so the standard engine's
fixpoint loops and multi-width merges multiply into per-step costs that
outrun the TPU worker's ~60 s watchdog (the round-2/3 batch-tier killer).
The batched engine therefore runs in *single-round* mode
(``make_engine(single_round_closure=True)``): exactly one fixed-width
merge per scan step, a pending-return register continuing multi-round
closures across steps, and each lane's step gathering its next event by
the lane's own absolute ``consumed`` cursor — per-step device work is a
constant, a dispatch's wall-clock is bounded by its step count alone,
and lanes progress at fully independent rates with no idle steps.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu.checker.prep import PreparedHistory, prepare
from jepsen_tpu.checker.wgl_tpu import (EV_NOP, chosen_gwords,
                                        events_array, make_engine)
from jepsen_tpu.history import History
from jepsen_tpu.models.base import JaxModel


class _LRUCache:
    """Bounded compiled-engine cache.

    Each entry pins a jitted vmapped engine (traced program + XLA
    executable) whose size scales with window*capacity*chunk — a service
    that sees many shapes would grow an unbounded dict without end.  LRU
    eviction keeps the hot buckets resident; hit/miss/eviction counters
    feed the serve metrics endpoint (an eviction storm means the bucket
    ladder is too fine)."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.group_reuses = 0

    def get(self, key, group_reuse: bool = False):
        """``group_reuse=True`` marks a lookup made for an additional
        dispatch group within ONE logical batch (check_batch's >512-lane
        split, megabatch's grouped vmap): a found entry counts toward
        ``group_reuses`` instead of ``hits``, so the hit rate keeps
        measuring cross-call cache effectiveness rather than being
        inflated by same-dispatch reuse."""
        with self._lock:
            if key in self._d:
                self._d.move_to_end(key)
                if group_reuse:
                    self.group_reuses += 1
                else:
                    self.hits += 1
                return self._d[key]
            self.misses += 1
            return None

    def put(self, key, value):
        with self._lock:
            self._d[key] = value
            self._d.move_to_end(key)
            while len(self._d) > self.capacity:
                self._d.popitem(last=False)
                self.evictions += 1
            return value

    def __len__(self):
        return len(self._d)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._d), "capacity": self.capacity,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions,
                    "group_reuses": self.group_reuses}


_CACHE = _LRUCache(int(os.environ.get("JEPSEN_TPU_ENGINE_CACHE", "32")))


def engine_cache_stats() -> Dict[str, int]:
    """Hit/miss/eviction counters of the compiled-engine cache (a miss is
    a fresh trace+compile — the serve metrics' recompile counter)."""
    return _CACHE.stats()

#: Target lane-events per dispatch: the vmapped scan costs ~(batch x chunk)
#: lane-event steps, so the chunk shrinks as the batch grows to keep one
#: XLA program's duration roughly constant regardless of batch size.
LANE_EVENTS_PER_DISPATCH = 16384

#: Max lanes per vmapped dispatch group.  Root cause (minimized to pure
#: JAX, reproduces on CPU and TPU backends and with eager vmap): a
#: vmapped scatter into a BOOL array inside ``lax.scan`` computes wrong
#: results at batch >= 1024 — ``jax.vmap(lambda arr, slot:
#: arr.at[slot].set(False))`` over bool[W] carriers, exactly the engine's
#: ``active``/``fresh`` slot updates; int32 carriers are unaffected, 1023
#: lanes are verdict-perfect (see tests/test_parallel.py regression and
#: ops/jax_bug_repro.py).  Engine-side symptom before the cap: two
#: distinct valid 8-op histories alternated 512x -> every lane of one
#: history refuted at its first return.  512 is also the throughput knee
#: measured in the one-off hardware tuning sweep (58.9 h/s at 512 lanes
#: vs 52.1 at 256 on 200-op lanes; the committed bench artifact's
#: 512-lane row reproduces the level at 56.3 h/s), so grouping costs
#: nothing.
MAX_LANES_PER_GROUP = 512


def donate_carry_argnums() -> tuple:
    """Argnums to donate for the per-chunk engine carry.

    The carry is the dominant device allocation (capacity x window words
    per lane); donating it lets XLA update it in place instead of
    reallocating every dispatch.  The CPU backend cannot honor carry
    donation (it warns per call and copies anyway), so donation is gated
    on the real backend — shapes and results are identical either way.
    """
    try:
        return (0,) if jax.default_backend() != "cpu" else ()
    except Exception:  # backend probe must never break checking
        return ()


def _batch_chunk(bpad: int, longest: int) -> int:
    """Events per dispatch for a ``bpad``-lane batch (multiple of 64,
    clamped to [64, 2048] and to the longest lane rounded up)."""
    c = max(64, min(2048, (LANE_EVENTS_PER_DISPATCH // max(1, bpad))
                    // 64 * 64))
    return min(c, max(64, ((longest + 63) // 64) * 64))


def check_batch(model: JaxModel,
                histories: Sequence[History],
                mesh: Optional[Mesh] = None,
                axis: str = "data",
                capacity: int = 256,
                max_capacity: int = 65536,
                chunk: Optional[int] = None,
                window_floor: int = 0,
                _group_reuse: bool = False) -> List[Dict[str, Any]]:
    """Check many histories at once; returns one result dict per history.

    All lanes share one engine shape (window = max over histories, events
    NOP-padded to the longest).  With ``mesh``, lanes are sharded over the
    ``axis`` mesh axis; the batch is padded to a multiple of the axis size.
    ``chunk=None`` picks the batch-size-scaled default (``_batch_chunk``).
    ``window_floor`` pads the shared window up to a caller-chosen bucket so
    successive batches of similar histories reuse one compiled engine (the
    serve scheduler's shape-bucketing lever; 0 = tightest window).

    Unlike the single-history engine (kernel-latency bound, per-round
    cost flat in capacity), the vmapped engine's per-step cost IS
    capacity-proportional — every lane pays C+NC merge rows every step —
    so the default capacity starts LOW (measured on hardware: 42 vs 17
    histories/sec at 256 vs 1024 on 200-op crash lanes) and the retry
    loop escalates only the lanes that overflow.
    """
    if not histories:
        return []
    if len(histories) > MAX_LANES_PER_GROUP:
        # Dispatch in bounded groups (see MAX_LANES_PER_GROUP): verdicts
        # corrupt at >= 1024 vmapped lanes, and 512-lane groups are the
        # measured throughput knee anyway.  Groups share the compiled
        # engine when their shapes agree (the engine cache keys on
        # window/capacity/chunk/bpad).
        out: List[Dict[str, Any]] = []
        for i in range(0, len(histories), MAX_LANES_PER_GROUP):
            out.extend(check_batch(model,
                                   histories[i:i + MAX_LANES_PER_GROUP],
                                   mesh=mesh, axis=axis, capacity=capacity,
                                   max_capacity=max_capacity, chunk=chunk,
                                   window_floor=window_floor,
                                   _group_reuse=_group_reuse or i > 0))
        return out
    from jepsen_tpu.checker.wgl_tpu import _round_window
    preps = [prepare(h, model) for h in histories]
    window = _round_window(max(window_floor, max(p.window for p in preps)))
    longest = max(len(p) for p in preps)
    # Lean (gwords=0) only when EVERY lane qualifies — the engine shape is
    # shared across the batch, and a non-qualifying lane's ghost_words
    # dominates the max anyway.
    gw = max(chosen_gwords(p) for p in preps)
    out: List[Optional[Dict[str, Any]]] = [None] * len(preps)
    lanes = list(range(len(preps)))
    cap = capacity
    while lanes:
        res = _run_lanes(model, [preps[i] for i in lanes],
                         window, cap, mesh, axis, chunk, gw, longest,
                         group_reuse=_group_reuse)
        retry = []
        for lane, r in zip(lanes, res):
            if r is None:
                retry.append(lane)
            else:
                out[lane] = r
        if not retry or cap >= max_capacity:
            for lane in retry:
                out[lane] = {"valid": "unknown", "analyzer": "wgl-tpu-batch",
                             "error": f"capacity exceeded at {cap}"}
            break
        lanes = retry
        cap = min(cap * 8, max_capacity)
    return out  # type: ignore[return-value]


def _run_lanes(model: JaxModel, preps, window: int, cap: int,
               mesh: Optional[Mesh], axis: str, chunk: Optional[int],
               gwords: int, longest: int,
               group_reuse: bool = False) -> List[Optional[Dict[str, Any]]]:
    """One vmapped pass over a set of lanes at a fixed capacity.  Returns a
    result per lane, or None where the lane overflowed (caller escalates).

    Each dispatch runs a fixed number of single-round steps; a lane's step
    gathers the event at the lane's own absolute ``consumed`` cursor, so
    lanes progress at fully independent rates and the host just re-invokes
    until every lane's cursor passes its stream (or fails/overflows)."""
    b = len(preps)
    bpad = b
    if mesh is not None:
        n = mesh.shape[axis]
        bpad = ((b + n - 1) // n) * n
    cc = chunk if chunk else _batch_chunk(bpad, longest)
    evs = [events_array(p, cc) for p in preps]
    # >= 1 trailing NOP row per lane: finished lanes' cursors clamp onto
    # it (the gather-based engine reads events by each lane's absolute
    # consumed cursor; see wgl_tpu run_chunk's single-round variant).
    emax = max(e.shape[0] for e in evs) + 1
    batch = np.zeros((bpad, emax, 10), np.int32)
    batch[:, :, 0] = EV_NOP
    for i, e in enumerate(evs):
        batch[i, :e.shape[0]] = e

    carry0, vrun = _batched_runner(model, window, cap, gwords, cc, bpad,
                                   group_reuse=group_reuse)
    c0 = carry0()
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (bpad,) + x.shape), c0)
    if mesh is not None:
        carry = jax.tree.map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(axis, *([None] * (x.ndim - 1))))),
            carry)
        batch_dev = jax.device_put(
            jnp.asarray(batch), NamedSharding(mesh, P(axis, None, None)))
    else:
        batch_dev = jnp.asarray(batch)

    lane_len = np.array([e.shape[0] for e in evs]
                        + [0] * (bpad - b), np.int32)
    failed = np.zeros(bpad, bool)
    overflow = np.zeros(bpad, bool)
    while True:
        carry, flags = vrun(carry, batch_dev)
        fl = np.asarray(flags)              # [bpad, 5]
        failed = fl[:, 0].astype(bool)
        overflow = fl[:, 1].astype(bool)
        consumed = fl[:, 3]                 # absolute per-lane cursors
        stalled = fl[:, 4].astype(bool)     # unconverged pending return
        # A lane whose cursor passed its stream may STILL have its final
        # return's closure in flight (consume-on-arrival): it stays live
        # until the stalled flag clears, or its prune could be dropped —
        # a false "valid" on a refuting final return.
        if not (~failed & ~overflow
                & ((consumed < lane_len) | stalled)).any():
            break

    failed_op = np.asarray(carry[7])[:b]
    explored = np.asarray(carry[9])[:b]
    out: List[Optional[Dict[str, Any]]] = []
    for i in range(b):
        if overflow[i]:
            out.append(None)
        elif failed[i]:
            # witness: the lane's frontier emptied; its refuting op rides
            out.append({"valid": False, "analyzer": "wgl-tpu-batch",
                        "op": preps[i].ops[int(failed_op[i])].to_dict(),
                        "configs-explored": int(explored[i])})
        else:
            out.append({"valid": True, "analyzer": "wgl-tpu-batch",
                        "configs-explored": int(explored[i])})
    return out


def _batched_runner(model: JaxModel, window: int, capacity: int,
                    gwords: int, chunk: int, bpad: int,
                    group_reuse: bool = False):
    key = ("batchv", model.name, model.variant, model.state_size,
           tuple(model.init_state_array().tolist()), window, capacity,
           gwords, chunk, bpad)
    hit = _CACHE.get(key, group_reuse=group_reuse)
    if hit is not None:
        return hit
    # single_round_closure: under vmap every cond/switch branch executes
    # for the whole batch, so the batched engine runs exactly ONE closure
    # round (one fixed-width merge) per scan step — per-step device work
    # is constant, a dispatch's wall-clock is bounded by the step count
    # alone, and no iteration budget is needed (work_budget=0).  Each
    # lane's step gathers its next event by the lane's own absolute
    # consumed cursor, so lanes progress at fully independent rates with
    # no idle steps.
    carry0, _, run_chunk = make_engine(model, window, capacity,
                                       gwords=gwords, work_budget=0,
                                       single_round_closure=True,
                                       steps_per_dispatch=chunk)
    # Donate the carry (argnum 0): the batched carry dominates device
    # memory and is dead after each dispatch — in-place update instead of
    # a fresh allocation per chunk.  The events buffer (argnum 1) is NOT
    # donated; it is reused across every dispatch of the batch.
    vrun = jax.jit(jax.vmap(run_chunk, in_axes=(0, 0)),
                   donate_argnums=donate_carry_argnums())
    return _CACHE.put(key, (carry0, vrun))
